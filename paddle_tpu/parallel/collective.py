"""Collective communication API.

Parity: paddle/fluid/operators/distributed + NCCL ops (allreduce,
broadcast, allgather) and the gRPC send/recv pserver ops. Here every
collective is an XLA primitive over named mesh axes — inside jit/
shard_map these compile to ICI/DCN collectives; there is no separate
runtime to manage (no rendezvous, no nccl communicator setup — XLA owns
scheduling/overlap).

Functions are meant to be called INSIDE shard_map-ped functions (axis
names bound by the enclosing mesh).

Telemetry: each wrapper records `collective.<op>.count` / `.bytes`
counters and a span on the unified timeline. These fire at TRACE time
— in the XLA world a collective exists once per compiled signature,
not once per step, so runtime occurrences = count x steps of that
program (the per-step cost shows up in device profiles, not here).
The raw psum/pmean/pmax aliases stay uninstrumented.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry as _tm
from ..resilience import chaos as _chaos

__all__ = ["all_reduce", "all_reduce_bf16", "all_reduce_int8_blockwise",
           "all_gather", "reduce_scatter", "broadcast",
           "all_to_all", "ppermute", "barrier", "psum", "pmean", "pmax",
           "pmin", "axis_index"]


def _nbytes(x):
    try:
        size = 1
        for d in getattr(x, "shape", ()):
            size *= int(d)
        return size * np.dtype(x.dtype).itemsize
    except Exception:
        return 0


def _traced_bytes(op, nbytes, axis_name, **meta):
    """Trace-time accounting for one collective with a known wire
    payload; returns the span context (the shared no-op singleton when
    telemetry is off). Also the `collective` chaos point: like the
    telemetry, injection is host-side at issuance/trace time —
    collective_fail raises a transient the surrounding retry/Guardian
    layer must absorb, collective_delay sleeps (late-rank
    simulation)."""
    if _chaos.armed():
        _chaos.check("collective", detail=f"collective {op}", op=op)
    if not _tm.enabled():
        return _tm.span(op)
    _tm.counter(f"collective.{op}.count").inc()
    _tm.counter(f"collective.{op}.bytes").inc(nbytes)
    return _tm.span(f"collective.{op}", cat="collective",
                    axis=str(axis_name), bytes=nbytes, **meta)


def _traced(op, x, axis_name):
    return _traced_bytes(op, _nbytes(x) if _tm.enabled() else 0,
                         axis_name)


def all_reduce(x, op="sum", axis_name="dp"):
    with _traced("all_reduce", x, axis_name):
        if op == "sum":
            return lax.psum(x, axis_name)
        if op == "mean":
            return lax.pmean(x, axis_name)
        if op == "max":
            return lax.pmax(x, axis_name)
        if op == "min":
            return lax.pmin(x, axis_name)
        if op == "prod":
            # exp(psum(log|x|)) alone NaNs on negatives and poisons the
            # whole reduction with -inf on zeros; decompose into
            # sign (psum of negative-counts mod 2), zero mask (pmax of
            # is-zero), and log-magnitude psum instead
            mag = jnp.abs(x)
            is_zero = (mag == 0)
            n_neg = lax.psum((x < 0).astype(jnp.int32), axis_name)
            any_zero = lax.pmax(is_zero.astype(jnp.int32), axis_name)
            log_mag = jnp.log(jnp.where(is_zero, 1.0, mag)
                              .astype(jnp.float32))
            sign = 1.0 - 2.0 * (n_neg % 2).astype(jnp.float32)
            res = jnp.where(any_zero > 0, 0.0,
                            sign * jnp.exp(lax.psum(log_mag, axis_name)))
            return res.astype(x.dtype)
    raise ValueError(f"unsupported all_reduce op {op!r}")


def all_reduce_bf16(x, axis_name="dp"):
    """Cast-reduce-cast sum: the bf16 payload is what crosses the wire
    (half the fp32 bytes), the result comes back in x's dtype. Lossy —
    gradsync's bf16 policy is the intended caller."""
    sent = x.astype(jnp.bfloat16)
    with _traced("all_reduce", sent, axis_name):
        return lax.psum(sent, axis_name).astype(x.dtype)


def all_reduce_int8_blockwise(q, scales, axis_name="dp"):
    """Blockwise-quantized all-reduce body (EQuARX-style): each member
    contributes int8 codes `q` [n_blocks, block] with per-block fp32
    `scales` [n_blocks, 1]; the wire carries 1 byte/element plus the
    scale sidecar, and the sum is accumulated in fp32 after per-member
    dequantize. Accounted under `collective.all_reduce` (it is one
    logical all-reduce; the internal gathers stay uninstrumented so the
    payload is not double-counted). Returns the fp32 global sum
    [n_blocks, block]."""
    nbytes = _nbytes(q) + _nbytes(scales)
    with _traced_bytes("all_reduce", nbytes, axis_name,
                       wire="int8-blockwise"):
        qg = lax.all_gather(q, axis_name, axis=0, tiled=False)
        sg = lax.all_gather(scales, axis_name, axis=0, tiled=False)
        # per-member dequantize is the shared wire primitive
        # (ops/kern/quant.py) vmapped over the member axis — the sum
        # stays the same fp32 accumulation over members
        from ..ops.kern.quant import dequantize_int8_blockwise
        deq = jax.vmap(dequantize_int8_blockwise)(qg, sg)  # [M, nb*bs]
        return jnp.sum(deq, axis=0).reshape(q.shape)


psum = lambda x, axis_name="dp": lax.psum(x, axis_name)
pmean = lambda x, axis_name="dp": lax.pmean(x, axis_name)
pmax = lambda x, axis_name="dp": lax.pmax(x, axis_name)
pmin = lambda x, axis_name="dp": lax.pmin(x, axis_name)


def all_gather(x, axis_name="dp", axis=0, tiled=True):
    with _traced("all_gather", x, axis_name):
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="dp", scatter_axis=0):
    with _traced("reduce_scatter", x, axis_name):
        return lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_axis,
                                tiled=True)


def broadcast(x, root=0, axis_name="dp"):
    """Root's value on every member: psum of the root-masked value —
    no gathered 8x buffer, lowers to one collective."""
    with _traced("broadcast", x, axis_name):
        idx = lax.axis_index(axis_name)
        masked = jnp.where(idx == root, x, jnp.zeros_like(x))
        return lax.psum(masked, axis_name)


def all_to_all(x, axis_name="sp", split_axis=0, concat_axis=0):
    with _traced("all_to_all", x, axis_name):
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def ppermute(x, perm, axis_name="sp"):
    with _traced("ppermute", x, axis_name):
        return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name="dp"):
    return lax.axis_index(axis_name)


def barrier(axis_name="dp"):
    """psum of a scalar — the XLA equivalent of a device barrier."""
    with _traced("barrier", jnp.ones(()), axis_name):
        return lax.psum(jnp.ones(()), axis_name)
