"""Collective communication API.

Parity: paddle/fluid/operators/distributed + NCCL ops (allreduce,
broadcast, allgather) and the gRPC send/recv pserver ops. Here every
collective is an XLA primitive over named mesh axes — inside jit/
shard_map these compile to ICI/DCN collectives; there is no separate
runtime to manage (no rendezvous, no nccl communicator setup — XLA owns
scheduling/overlap).

Functions are meant to be called INSIDE shard_map-ped functions (axis
names bound by the enclosing mesh).

Telemetry: each wrapper records `collective.<op>.count` / `.bytes`
counters and a span on the unified timeline. These fire at TRACE time
— in the XLA world a collective exists once per compiled signature,
not once per step, so runtime occurrences = count x steps of that
program (the per-step cost shows up in device profiles, not here).
The raw psum/pmean/pmax aliases stay uninstrumented.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry as _tm

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "broadcast",
           "all_to_all", "ppermute", "barrier", "psum", "pmean", "pmax",
           "axis_index"]


def _traced(op, x, axis_name):
    """Trace-time accounting for one collective call; returns the span
    context (the shared no-op singleton when telemetry is off)."""
    if not _tm.enabled():
        return _tm.span(op)
    nbytes = 0
    try:
        size = 1
        for d in getattr(x, "shape", ()):
            size *= int(d)
        nbytes = size * np.dtype(x.dtype).itemsize
    except Exception:
        pass
    _tm.counter(f"collective.{op}.count").inc()
    _tm.counter(f"collective.{op}.bytes").inc(nbytes)
    return _tm.span(f"collective.{op}", cat="collective",
                    axis=str(axis_name), bytes=nbytes)


def all_reduce(x, op="sum", axis_name="dp"):
    with _traced("all_reduce", x, axis_name):
        if op == "sum":
            return lax.psum(x, axis_name)
        if op == "mean":
            return lax.pmean(x, axis_name)
        if op == "max":
            return lax.pmax(x, axis_name)
        if op == "min":
            return lax.pmin(x, axis_name)
        if op == "prod":
            return jnp.exp(lax.psum(jnp.log(x), axis_name))
    raise ValueError(f"unsupported all_reduce op {op!r}")


psum = lambda x, axis_name="dp": lax.psum(x, axis_name)
pmean = lambda x, axis_name="dp": lax.pmean(x, axis_name)
pmax = lambda x, axis_name="dp": lax.pmax(x, axis_name)


def all_gather(x, axis_name="dp", axis=0, tiled=True):
    with _traced("all_gather", x, axis_name):
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="dp", scatter_axis=0):
    with _traced("reduce_scatter", x, axis_name):
        return lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_axis,
                                tiled=True)


def broadcast(x, root=0, axis_name="dp"):
    """Root's value on every member: psum of the root-masked value —
    no gathered 8x buffer, lowers to one collective."""
    with _traced("broadcast", x, axis_name):
        idx = lax.axis_index(axis_name)
        masked = jnp.where(idx == root, x, jnp.zeros_like(x))
        return lax.psum(masked, axis_name)


def all_to_all(x, axis_name="sp", split_axis=0, concat_axis=0):
    with _traced("all_to_all", x, axis_name):
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def ppermute(x, perm, axis_name="sp"):
    with _traced("ppermute", x, axis_name):
        return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name="dp"):
    return lax.axis_index(axis_name)


def barrier(axis_name="dp"):
    """psum of a scalar — the XLA equivalent of a device barrier."""
    with _traced("barrier", jnp.ones(()), axis_name):
        return lax.psum(jnp.ones(()), axis_name)
