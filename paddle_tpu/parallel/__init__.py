"""Distributed / parallel execution.

Parity: ref parallel_executor.py + transpiler/distribute_transpiler.py +
operators/distributed (gRPC pserver, NCCL). TPU-native design: a
jax.sharding.Mesh with named axes (dp/tp/sp/pp), sharding annotations,
and XLA collectives over ICI — see SURVEY §2.4/§6.
"""
from . import mesh
from .mesh import make_mesh, local_mesh, axis_size
from . import collective
from . import gradsync
from .gradsync import GradSyncPolicy
from . import parallel_executor
from .parallel_executor import ParallelExecutor
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig
from . import ring_attention
from . import sharding
from . import fleet
from . import ulysses
from . import moe


def __getattr__(name):
    # the sparse engine stays unimported until a distributed table
    # actually asks for it (bench-contract: the engine-off path loads
    # zero extra code) — PEP 562 lazy module attribute
    if name == "sparse":
        import importlib
        return importlib.import_module(".sparse", __name__)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
