"""Pipeline parallelism over the `pp` mesh axis.

The reference era scaled depth via pserver sharding only; modern Paddle
added pipeline stages. TPU-native GPipe-style schedule: stage functions
run under shard_map over `pp`, microbatches stream through with
lax.scan + ppermute handing activations to the next stage over ICI.

Two layers of API:
- pipeline_forward / gpipe_schedule: the generic schedule for stage
  functions expressed as pure JAX callables.
- PipelineTrainer: TRAINING integrated with the Program IR — splits a
  built Program (with backward_macro + optimizer ops from
  optimizer.minimize) into stages at caller-named activation
  boundaries, runs the GPipe forward under shard_map, and gets the
  backward schedule from jax.value_and_grad: the transpose of the
  stage-to-stage ppermute IS the reverse permute, so gradients flow
  across stage boundaries over the same ICI links, microbatch by
  microbatch, without hand-written backward plumbing. The Program's own
  optimizer ops then apply the updates.
"""
import functools
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import telemetry as _tm
from ..telemetry import spans as _tspans

__all__ = ["pipeline_forward", "gpipe_schedule", "one_f_one_b_schedule",
           "bubble_fraction", "record_bubble", "PipelineTrainer"]


def pipeline_forward(mesh, stage_fn, params_per_stage, x, n_microbatch,
                     axis_name="pp"):
    """Run x [B, ...] through n_stages stage_fn's, stage i on device i of
    the pp axis (GPipe forward).

    stage_fn(stage_params, h) -> h; all stages must share one signature
    (same activation shape), the usual transformer-block case.
    params_per_stage: pytree whose leaves are stacked on axis 0 with
    length n_stages (leaf i goes to stage i).
    """
    n_stages = mesh.shape[axis_name]
    if x.shape[0] % n_microbatch:
        raise ValueError("batch must divide into microbatches")
    mb = jnp.reshape(x, (n_microbatch, x.shape[0] // n_microbatch)
                     + x.shape[1:])

    def per_stage(params, mb_local):
        """Runs on ONE pp member. params arrive as the local shard of the
        stage-stacked pytree (leading dim 1) — unwrap it."""
        params = jax.tree.map(lambda a: a[0], params)
        stage = lax.axis_index(axis_name)
        n_steps = n_microbatch + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def step(carry, t):
            # carry: (incoming activation buffer, outputs accumulator)
            inflight, outs = carry
            # stage 0 ingests microbatch t (when valid); others take the
            # activation handed over from the previous stage
            mb_idx = jnp.clip(t, 0, n_microbatch - 1)
            my_in = jnp.where(stage == 0, mb_local[mb_idx], inflight)
            h = stage_fn(params, my_in)
            # last stage records its finished microbatch (t - n_stages + 1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatch - 1)
            valid = (t >= stage) & (t - stage < n_microbatch)
            record = (stage == n_stages - 1) & valid & \
                (t >= n_stages - 1)
            outs = jnp.where(
                record,
                outs.at[out_idx].set(h),
                outs)
            # hand my activation to the next stage
            nxt = lax.ppermute(h, axis_name, perm)
            return (nxt, outs), None

        outs0 = jnp.zeros((n_microbatch,) + mb_local.shape[1:],
                          mb_local.dtype)
        inflight0 = jnp.zeros_like(mb_local[0])
        (_, outs), _ = lax.scan(step, (inflight0, outs0),
                                jnp.arange(n_steps))
        return outs[None]               # leading stage axis for out_specs

    sm = jax.shard_map(
        per_stage, mesh=mesh,
        in_specs=(P(axis_name), P()),   # stage params sharded over pp
        out_specs=P(axis_name),         # [n_stages, n_mb, ...]
        check_vma=False)
    outs = sm(params_per_stage, mb)[-1]  # only the last stage's buffer
    return jnp.reshape(outs, x.shape[:1] + outs.shape[2:])


def gpipe_schedule(n_microbatch, n_stages):
    """Return the (t, stage)->microbatch table of the GPipe schedule —
    useful for tests/visualization."""
    table = {}
    for t in range(n_microbatch + n_stages - 1):
        for s in range(n_stages):
            m = t - s
            if 0 <= m < n_microbatch:
                table[(t, s)] = m
    return table


def one_f_one_b_schedule(n_microbatch, n_stages, n_slots=None):
    """Simulate the 1F1B (one-forward-one-backward) schedule and return
    (act, mbi): two [T, n_stages] int lists with act in {0 idle, 1 fwd,
    2 bwd} and mbi the microbatch index.

    Policy: a stage runs a backward as soon as a cotangent is available
    (the 1F1B invariant), otherwise a forward — capped at `n_slots`
    microbatches in flight (default n_stages), which is what bounds
    activation memory to n_slots slots instead of GPipe's n_microbatch.
    Dependencies honored: fwd(s,m) needs fwd(s-1,m) at an earlier tick;
    bwd(s,m) needs bwd(s+1,m) earlier (or its own fwd for the last
    stage)."""
    S = n_stages
    n_slots = n_slots or S
    F, B = {}, {}
    fwd_done = [0] * S
    bwd_done = [0] * S
    act, mbi = [], []
    t = 0
    while not all(b == n_microbatch for b in bwd_done):
        arow, mrow = [], []
        for s in range(S):
            m_b, m_f = bwd_done[s], fwd_done[s]
            can_b = m_b < n_microbatch and (
                (s == S - 1 and F.get((s, m_b), t) < t)
                or (s < S - 1 and B.get((s + 1, m_b), t) < t))
            can_f = (m_f < n_microbatch
                     and m_f - m_b < n_slots
                     and (s == 0 or F.get((s - 1, m_f), t) < t))
            if can_b:
                B[(s, m_b)] = t
                bwd_done[s] += 1
                arow.append(2)
                mrow.append(m_b)
            elif can_f:
                F[(s, m_f)] = t
                fwd_done[s] += 1
                arow.append(1)
                mrow.append(m_f)
            else:
                arow.append(0)
                mrow.append(0)
        act.append(arow)
        mbi.append(mrow)
        t += 1
        if t > 4 * (n_microbatch + S) + 8:  # safety: schedule must close
            raise RuntimeError("1F1B schedule did not converge")
    return act, mbi


def bubble_fraction(schedule, n_microbatch, n_stages, n_slots=None):
    """Idle fraction of the (tick, stage) schedule grid — the classic
    pipeline bubble. GPipe's forward grid has (S-1)/(n_mb+S-1) idle
    ticks per stage in closed form; 1F1B is read off the simulated
    schedule table (idle cells / total cells)."""
    if schedule == "gpipe":
        total = (n_microbatch + n_stages - 1) * n_stages
        busy = n_microbatch * n_stages
        return 1.0 - busy / total
    if schedule == "1f1b":
        act, _ = one_f_one_b_schedule(n_microbatch, n_stages, n_slots)
        cells = [a for row in act for a in row]
        return cells.count(0) / len(cells)
    raise ValueError(f"unknown pipeline schedule {schedule!r}")


def record_bubble(schedule, n_microbatch, n_stages, n_slots=None):
    """Compute the bubble fraction AND publish the
    `pipeline.bubble_fraction` gauge (the same gauge
    PipelineTrainer.run sets) when telemetry is enabled."""
    bf = bubble_fraction(schedule, n_microbatch, n_stages, n_slots)
    if _tm.enabled():
        _tm.gauge("pipeline.bubble_fraction").set(bf)
    return bf


class PipelineTrainer:
    """GPipe training of a Program over the `pp` mesh axis.

    Parity: the reference scaled depth via pserver param placement
    (transpiler/distribute_transpiler.py); this is the TPU-native
    replacement — stage ops stay on their pp member, activations hop
    stage→stage via ppermute, gradients hop back via the AD-transposed
    permute, and the Program's optimizer ops run on the accumulated
    grads (true GPipe: updates apply after all microbatches).

    Constraints (the homogeneous-block case — transformer/MLP stacks):
    - `boundaries` names n_stages-1 activation vars splitting the
      forward op list into contiguous segments;
    - every stage must hold the same NUMBER and SHAPES of trainable
      params (stage i's params live on pp member i, stacked leaf-wise);
    - the boundary activations must share one shape [B, ...].
    """

    def __init__(self, program, loss_name, boundaries, mesh,
                 n_microbatch=4, axis_name="pp", scope=None,
                 schedule="gpipe", data_axis=None):
        from ..core.trace import exec_op, _find_backward
        from ..core.framework import grad_var_name
        from ..core.scope import global_scope
        self.program = program
        self.loss_name = loss_name if isinstance(loss_name, str) \
            else loss_name.name
        self.mesh = mesh
        self.axis = axis_name
        self.n_mb = n_microbatch
        self.scope = scope or global_scope()
        self.n_stages = mesh.shape[axis_name]
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        self.schedule = schedule
        # dp x pp composition: feeds shard their microbatch batch dim
        # over `data_axis`; params stay replicated across it, so the
        # shard_map AD transpose inserts the gradient psum over dp
        # automatically (grads of unmapped inputs are summed)
        self.data_axis = data_axis
        self.n_dp = mesh.shape[data_axis] if data_axis else 1

        block = program.global_block()
        ops = list(block.ops)
        bi = _find_backward(ops)
        if bi is None:
            raise ValueError("program has no backward; call "
                             "optimizer.minimize(loss) first")
        fwd_ops, self._bwd_op = ops[:bi], ops[bi]
        self._update_ops = ops[bi + 1:]

        # split forward ops at the boundary-producing ops
        if len(boundaries) != self.n_stages - 1:
            raise ValueError(f"need {self.n_stages - 1} boundaries for "
                             f"{self.n_stages} stages")
        self.boundaries = list(boundaries)
        cut_after = {}
        for i, op in enumerate(fwd_ops):
            for b in boundaries:
                if b in op.output_names():
                    cut_after[b] = i
        missing = [b for b in boundaries if b not in cut_after]
        if missing:
            raise ValueError(f"boundary vars not produced: {missing}")
        cuts = sorted(cut_after[b] for b in boundaries)
        segs = []
        lo = 0
        for c in cuts:
            segs.append(fwd_ops[lo:c + 1])
            lo = c + 1
        segs.append(fwd_ops[lo:])
        self.segments = segs

        # per-stage trainable params (deterministic first-use order)
        persistable = {v.name: v for v in program.persistable_vars()}
        if self._bwd_op.attrs.get("sparse_params"):
            # the pipeline's stage-wise backward never produces the
            # row-grad taps the sparse update ops consume — fail with
            # the contract instead of a KeyError deep in the replay
            raise NotImplementedError(
                "PipelineTrainer does not support embedding("
                "is_sparse=True) tables; build the pipeline program "
                "with is_sparse=False (dense gather grads)")
        bwd_params = set(self._bwd_op.attrs["param_names"])
        self.stage_params = []
        for seg in segs:
            names, seen = [], set()
            for op in seg:
                for n in op.input_names():
                    if n in bwd_params and n not in seen:
                        seen.add(n)
                        names.append(n)
            self.stage_params.append(names)
        shapes0 = [tuple(persistable[n].shape) for n in self.stage_params[0]]
        for i, names in enumerate(self.stage_params):
            sh = [tuple(persistable[n].shape) for n in names]
            if sh != shapes0:
                raise NotImplementedError(
                    f"pipeline stages must be homogeneous: stage 0 params "
                    f"{shapes0} vs stage {i} {sh}")
        self._block = block
        self._exec_op = exec_op
        self._grad_name = grad_var_name
        self._jit_cache = {}
        self._step = 0
        self._bubble = None            # computed lazily on first run
        self._schedule_emitted = False

    # ------------------------------------------------------------------
    def _stage_branch(self, si, feed_names):
        """Branch fn for stage si: (param_list, h, feed_slice, key) ->
        (h_out, loss)."""
        seg = self.segments[si]
        in_b = None if si == 0 else self.boundaries[si - 1]
        out_b = self.boundaries[si] if si < self.n_stages - 1 else None
        pnames = self.stage_params[si]
        exec_op = self._exec_op
        block = self._block

        def branch(params, h, feed, key):
            env = dict(zip(feed_names, feed))
            env.update(dict(zip(pnames, params)))
            if in_b is not None:
                env[in_b] = h
            for j, op in enumerate(seg):
                exec_op(env, op, si * 10000 + j, key, False, None, block)
            if out_b is not None:
                return env[out_b], jnp.zeros((), jnp.float32)
            loss = env[self.loss_name]
            return jnp.zeros_like(h), jnp.sum(loss.astype(jnp.float32))

        return branch

    def _build_fn(self, feed_names):
        n_stages, n_mb, axis = self.n_stages, self.n_mb, self.axis
        branches = [self._stage_branch(si, feed_names)
                    for si in range(n_stages)]

        def per_member(stacked, feed_mb, key):
            """One pp member. stacked: leaves [1, ...] (local shard of the
            stage-stacked params); feed_mb: [n_mb, mb, ...] replicated."""
            params = [p[0] for p in stacked]
            stage = lax.axis_index(axis)
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            n_steps = n_mb + n_stages - 1

            def first_shape():
                # boundary activation shape: run stage 0 shape-only
                mb0 = jax.tree.map(lambda a: a[0], feed_mb)
                h0, _ = jax.eval_shape(branches[0], params, 0.0, mb0, key)
                return h0

            hshape = first_shape()
            h0 = jnp.zeros(hshape.shape, hshape.dtype)

            # dp members hold DIFFERENT examples, so their dropout
            # streams must differ — fold the dp member index once
            mkey = (jax.random.fold_in(key, lax.axis_index(
                self.data_axis)) if self.data_axis else key)

            def step(carry, t):
                inflight, loss_sum = carry
                mb_idx = jnp.clip(t - stage, 0, n_mb - 1)
                mb = jax.tree.map(lambda a: a[mb_idx], feed_mb)
                # key folds the MICROBATCH index (not the tick) so the
                # dropout stream matches the 1F1B schedule bit-for-bit
                h_out, loss = lax.switch(
                    stage, branches, params, inflight, mb,
                    jax.random.fold_in(mkey, mb_idx))
                valid = (t >= stage) & (t - stage < n_mb)
                loss_sum = loss_sum + jnp.where(valid, loss, 0.0)
                nxt = lax.ppermute(h_out, axis, perm)
                return (nxt, loss_sum), None

            (_, loss_sum), _ = lax.scan(
                step, (h0, jnp.zeros((), jnp.float32)),
                jnp.arange(n_steps))
            # only the LAST stage produced loss; psum replicates the
            # total (and averages the dp members' local-shard means)
            axes = (axis,) + ((self.data_axis,) if self.data_axis
                              else ())
            return lax.psum(loss_sum, axes) / (n_mb * self.n_dp)

        feed_spec = P(None, self.data_axis) if self.data_axis else P()
        in_specs = ([P(axis)] * len(self.stage_params[0]), feed_spec,
                    P())
        sm = jax.shard_map(per_member, mesh=self.mesh, in_specs=in_specs,
                           out_specs=P(), check_vma=False)

        def train_loss(stacked, feed_mb, key):
            return sm(stacked, feed_mb, key)

        def step_fn(persist, feed_mb, key):
            stacked = [
                jnp.stack([persist[self.stage_params[s][i]]
                           for s in range(n_stages)])
                for i in range(len(self.stage_params[0]))]
            loss, grads = jax.value_and_grad(train_loss)(
                stacked, feed_mb, key)
            env = dict(persist)
            for i in range(len(grads)):
                for s in range(n_stages):
                    pname = self.stage_params[s][i]
                    env[self._grad_name(pname)] = grads[i][s].astype(
                        env[pname].dtype)
            for j, op in enumerate(self._update_ops):
                self._exec_op(env, op, 900000 + j, key, False, None,
                              self._block)
            new_persist = {n: env[n] for n in persist if n in env}
            return loss, new_persist

        return step_fn

    # ------------------------------------------------------------------
    def _build_fn_1f1b(self, feed_names):
        """1F1B schedule: activation memory is bounded by n_stages slots
        (vs GPipe's n_microbatch residuals) — backwards run via jax.vjp
        with the stage forward REMATERIALIZED from the stored stage
        input, so only inputs are kept in flight. The schedule is
        simulated host-side (one_f_one_b_schedule) and baked into static
        [T, S] action/microbatch tables; every tick all members run one
        masked compute (lax.cond — no collectives inside) and two
        unconditional ppermutes (activations forward, cotangents
        backward), so SPMD stays uniform."""
        n_stages, n_mb, axis = self.n_stages, self.n_mb, self.axis
        n_slots = n_stages
        branches = [self._stage_branch(si, feed_names)
                    for si in range(n_stages)]
        act_tab_h, mb_tab_h = one_f_one_b_schedule(n_mb, n_stages,
                                                   n_slots)
        n_ticks = len(act_tab_h)
        act_tab = jnp.asarray(act_tab_h, jnp.int32)
        mb_tab = jnp.asarray(mb_tab_h, jnp.int32)

        def per_member(stacked, feed_mb, key):
            params = [p[0] for p in stacked]
            stage = lax.axis_index(axis)
            perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            perm_bwd = [(i, (i - 1) % n_stages) for i in range(n_stages)]

            mb0 = jax.tree.map(lambda a: a[0], feed_mb)
            hs = jax.eval_shape(branches[0], params, 0.0, mb0, key)[0]
            zeros_h = jnp.zeros(hs.shape, hs.dtype)
            zeros_p = [jnp.zeros(p.shape, p.dtype) for p in params]
            # last stage's bwd seeds the loss cotangent: the global
            # objective is the mean over microbatches AND dp shards
            seed = jnp.where(stage == n_stages - 1,
                             jnp.float32(1.0 / (n_mb * self.n_dp)),
                             jnp.float32(0.0))

            def apply(p, h, feed, k):
                return lax.switch(stage, branches, p, h, feed, k)

            # dp members hold different examples: decorrelate their
            # dropout streams (mirrors the GPipe path exactly)
            mkey = (jax.random.fold_in(key, lax.axis_index(
                self.data_axis)) if self.data_axis else key)

            def step(carry, t):
                act_in, x_store, cot_in, gacc, loss_sum = carry
                a = act_tab[t, stage]
                m = mb_tab[t, stage]
                slot = m % n_slots
                feed_m = jax.tree.map(lambda arr: arr[m], feed_mb)
                key_m = jax.random.fold_in(mkey, m)  # fwd == remat key

                def fwd(_):
                    return apply(params, act_in[slot], feed_m, key_m)

                h_out, floss = lax.cond(
                    a == 1, fwd,
                    lambda _: (zeros_h, jnp.zeros((), jnp.float32)),
                    None)

                def bwd(_):
                    f = lambda p, x: apply(p, x, feed_m, key_m)
                    _, vjp_fn = jax.vjp(f, params, x_store[slot])
                    dp, dx = vjp_fn((cot_in[slot], seed))
                    return dp, dx

                dp, dx = lax.cond(
                    a == 2, bwd, lambda _: (zeros_p, zeros_h), None)

                gacc = [g + d.astype(jnp.float32)
                        for g, d in zip(gacc, dp)]
                loss_sum = loss_sum + floss
                x_store = jnp.where(a == 1,
                                    x_store.at[slot].set(act_in[slot]),
                                    x_store)

                # hand activations downstream, cotangents upstream; the
                # receiver files arrivals under the SENDER's static
                # schedule entry for this tick
                h_recv = lax.ppermute(h_out, axis, perm_fwd)
                dx_recv = lax.ppermute(dx, axis, perm_bwd)
                prev = (stage - 1) % n_stages
                nxt = (stage + 1) % n_stages
                pa, pm = act_tab[t, prev], mb_tab[t, prev]
                na, nm = act_tab[t, nxt], mb_tab[t, nxt]
                act_in = jnp.where(
                    (pa == 1) & (stage > 0),
                    act_in.at[pm % n_slots].set(h_recv), act_in)
                cot_in = jnp.where(
                    (na == 2) & (stage < n_stages - 1),
                    cot_in.at[nm % n_slots].set(dx_recv), cot_in)
                return (act_in, x_store, cot_in, gacc, loss_sum), None

            buf = jnp.zeros((n_slots,) + hs.shape, hs.dtype)
            gacc0 = [jnp.zeros(p.shape, jnp.float32) for p in params]
            carry0 = (buf, buf, buf, gacc0,
                      jnp.zeros((), jnp.float32))
            (_, _, _, gacc, loss_sum), _ = lax.scan(
                step, carry0, jnp.arange(n_ticks))
            axes = (axis,) + ((self.data_axis,) if self.data_axis
                              else ())
            loss = lax.psum(loss_sum, axes) / (n_mb * self.n_dp)
            if self.data_axis:
                # grads accumulated explicitly (not via AD transpose
                # through shard_map), so the dp reduction is explicit
                # too; out_specs leave dp unmapped = must be replicated
                gacc = [lax.psum(g, self.data_axis) for g in gacc]
            return loss, [g[None] for g in gacc]

        feed_spec = P(None, self.data_axis) if self.data_axis else P()
        in_specs = ([P(axis)] * len(self.stage_params[0]), feed_spec,
                    P())
        sm = jax.shard_map(per_member, mesh=self.mesh, in_specs=in_specs,
                           out_specs=(P(), [P(axis)] * len(
                               self.stage_params[0])),
                           check_vma=False)

        def step_fn(persist, feed_mb, key):
            stacked = [
                jnp.stack([persist[self.stage_params[s][i]]
                           for s in range(n_stages)])
                for i in range(len(self.stage_params[0]))]
            loss, grads = sm(stacked, feed_mb, key)
            env = dict(persist)
            for i in range(len(grads)):
                for s in range(n_stages):
                    pname = self.stage_params[s][i]
                    env[self._grad_name(pname)] = grads[i][s].astype(
                        env[pname].dtype)
            for j, op in enumerate(self._update_ops):
                self._exec_op(env, op, 900000 + j, key, False, None,
                              self._block)
            new_persist = {n: env[n] for n in persist if n in env}
            return loss, new_persist

        return step_fn

    # ------------------------------------------------------------------
    def _emit_schedule_spans(self, step_seconds):
        """Lay the (tick, stage) schedule grid onto the trace ONCE per
        trainer, each cell scaled to the measured step time: a visual
        per-microbatch/per-stage breakdown (fwd/bwd/idle) on its own
        synthetic tracks — the bubble, drawn. Host-side estimate (ticks
        are assumed uniform), labeled as such via cat="pipeline"."""
        if self._schedule_emitted:
            return
        self._schedule_emitted = True
        if self.schedule == "gpipe":
            table = gpipe_schedule(self.n_mb, self.n_stages)
            n_ticks = self.n_mb + self.n_stages - 1
            cells = [(t, s, "fwd", m) for (t, s), m in table.items()]
        else:
            act, mbi = one_f_one_b_schedule(self.n_mb, self.n_stages)
            n_ticks = len(act)
            cells = [(t, s, {1: "fwd", 2: "bwd"}[a], mbi[t][s])
                     for t in range(n_ticks)
                     for s, a in enumerate(act[t]) if a]
        tick_us = step_seconds * 1e6 / max(n_ticks, 1)
        t0 = _tspans.now_us() - step_seconds * 1e6
        for t, s, kind, m in cells:
            _tspans.append_span(
                f"{kind} mb{m}", cat="pipeline",
                ts_us=t0 + t * tick_us, dur_us=tick_us,
                tid=f"pp stage {s}",
                args={"tick": t, "stage": s, "microbatch": m,
                      "schedule": self.schedule})

    # ------------------------------------------------------------------
    def run(self, feed, fetch_loss=True):
        """One GPipe training step over the microbatched feed."""
        import numpy as np
        from ..core.dtypes import as_jnp_dtype
        feed_names = sorted(feed)
        key = jax.random.fold_in(
            jax.random.PRNGKey(self.program.random_seed), self._step)
        self._step += 1
        feed_mb = []
        for k in feed_names:
            arr = np.asarray(feed[k])
            var = self._block.vars.get(k)
            dt = as_jnp_dtype(var.dtype) if var is not None else None
            if arr.shape[0] % (self.n_mb * self.n_dp):
                raise ValueError(
                    f"batch {arr.shape[0]} must divide into "
                    f"{self.n_mb} microbatches x {self.n_dp} dp shards")
            a = jnp.asarray(arr, dtype=dt)
            feed_mb.append(a.reshape((self.n_mb, arr.shape[0] // self.n_mb)
                                     + arr.shape[1:]))

        persist = {}
        for v in self.program.persistable_vars():
            val = self.scope.get(v.name)
            if val is None:
                raise RuntimeError(f"{v.name!r} not initialized; run the "
                                   f"startup program first")
            persist[v.name] = jnp.asarray(val)

        ck = tuple((k, tuple(a.shape), str(a.dtype))
                   for k, a in zip(feed_names, feed_mb))
        fn = self._jit_cache.get(ck)
        tm_on = _tm.enabled()
        if fn is None:
            if tm_on:
                _tm.counter("pipeline.compile_count").inc()
            step = (self._build_fn_1f1b(feed_names)
                    if self.schedule == "1f1b"
                    else self._build_fn(feed_names))
            fn = jax.jit(step)
            self._jit_cache[ck] = fn
        t0 = time.perf_counter()
        with _tm.span("pipeline.step", schedule=self.schedule,
                      stages=self.n_stages, microbatches=self.n_mb):
            loss, new_persist = fn(persist, feed_mb, key)
            loss = float(np.asarray(loss))   # completion barrier
        if tm_on:
            dt = time.perf_counter() - t0
            if self._bubble is None:
                self._bubble = bubble_fraction(
                    self.schedule, self.n_mb, self.n_stages)
            _tm.counter("pipeline.steps").inc()
            _tm.counter("pipeline.microbatches").inc(self.n_mb)
            _tm.histogram("pipeline.step_seconds").observe(dt)
            _tm.gauge("pipeline.bubble_fraction").set(self._bubble)
            self._emit_schedule_spans(dt)
            _tm.fleet.on_step(dt)
        for n, v in new_persist.items():
            self.scope.set(n, v)
        return loss
