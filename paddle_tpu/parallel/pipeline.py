"""Pipeline parallelism over the `pp` mesh axis.

The reference era scaled depth via pserver sharding only; modern Paddle
added pipeline stages. TPU-native GPipe-style schedule: stage functions
run under shard_map over `pp`, microbatches stream through with
lax.scan + ppermute handing activations to the next stage over ICI.

This module provides the generic schedule for stage functions expressed
as pure JAX callables (models built with the Program IR can export one
via core/trace.build_step_fn on a sub-program).
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_forward", "gpipe_schedule"]


def pipeline_forward(mesh, stage_fn, params_per_stage, x, n_microbatch,
                     axis_name="pp"):
    """Run x [B, ...] through n_stages stage_fn's, stage i on device i of
    the pp axis (GPipe forward).

    stage_fn(stage_params, h) -> h; all stages must share one signature
    (same activation shape), the usual transformer-block case.
    params_per_stage: pytree whose leaves are stacked on axis 0 with
    length n_stages (leaf i goes to stage i).
    """
    n_stages = mesh.shape[axis_name]
    if x.shape[0] % n_microbatch:
        raise ValueError("batch must divide into microbatches")
    mb = jnp.reshape(x, (n_microbatch, x.shape[0] // n_microbatch)
                     + x.shape[1:])

    def per_stage(params, mb_local):
        """Runs on ONE pp member. params arrive as the local shard of the
        stage-stacked pytree (leading dim 1) — unwrap it."""
        params = jax.tree.map(lambda a: a[0], params)
        stage = lax.axis_index(axis_name)
        n_steps = n_microbatch + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def step(carry, t):
            # carry: (incoming activation buffer, outputs accumulator)
            inflight, outs = carry
            # stage 0 ingests microbatch t (when valid); others take the
            # activation handed over from the previous stage
            mb_idx = jnp.clip(t, 0, n_microbatch - 1)
            my_in = jnp.where(stage == 0, mb_local[mb_idx], inflight)
            h = stage_fn(params, my_in)
            # last stage records its finished microbatch (t - n_stages + 1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatch - 1)
            valid = (t >= stage) & (t - stage < n_microbatch)
            record = (stage == n_stages - 1) & valid & \
                (t >= n_stages - 1)
            outs = jnp.where(
                record,
                outs.at[out_idx].set(h),
                outs)
            # hand my activation to the next stage
            nxt = lax.ppermute(h, axis_name, perm)
            return (nxt, outs), None

        outs0 = jnp.zeros((n_microbatch,) + mb_local.shape[1:],
                          mb_local.dtype)
        inflight0 = jnp.zeros_like(mb_local[0])
        (_, outs), _ = lax.scan(step, (inflight0, outs0),
                                jnp.arange(n_steps))
        return outs[None]               # leading stage axis for out_specs

    sm = jax.shard_map(
        per_stage, mesh=mesh,
        in_specs=(P(axis_name), P()),   # stage params sharded over pp
        out_specs=P(axis_name),         # [n_stages, n_mb, ...]
        check_vma=False)
    outs = sm(params_per_stage, mb)[-1]  # only the last stage's buffer
    return jnp.reshape(outs, x.shape[:1] + outs.shape[2:])


def gpipe_schedule(n_microbatch, n_stages):
    """Return the (t, stage)->microbatch table of the GPipe schedule —
    useful for tests/visualization."""
    table = {}
    for t in range(n_microbatch + n_stages - 1):
        for s in range(n_stages):
            m = t - s
            if 0 <= m < n_microbatch:
                table[(t, s)] = m
    return table
