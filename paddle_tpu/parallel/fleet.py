"""Fleet-style high-level distributed API.

Parity: the reference era's paddle.fluid.incubate.fleet — init() +
distributed_optimizer() + worker introspection, mapped onto the mesh/
jax.distributed world.
"""
import jax

from .. import telemetry as _tm
from ..resilience import chaos as _chaos
from ..resilience import retry as _retry
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig

__all__ = ["init", "reform", "distributed_optimizer", "worker_num",
           "worker_index", "is_first_worker", "barrier_all"]

_state = {"initialized": False, "transpiler": None}

# gang bring-up races the other hosts' process start; barriers race
# transient coordinator/DCN flake — both are the canonical retryable
# seams (the reference's grpc pserver channels retried the same way)
_INIT_POLICY = _retry.RetryPolicy(max_attempts=3, base_delay_s=1.0,
                                  max_delay_s=10.0, deadline_s=120.0)
_BARRIER_POLICY = _retry.RetryPolicy(max_attempts=3, base_delay_s=0.2,
                                     max_delay_s=2.0)


def init(role_maker=None, coordinator_address=None, num_processes=None,
         process_id=None):
    """Single-host: no-op. Multi-host: jax.distributed.initialize — after
    which jax.devices() spans the pod and the SAME mesh code works.
    Bring-up is retried under _INIT_POLICY: hosts of a gang start at
    different times, and the first connect losing the race is routine,
    not fatal."""
    if coordinator_address is not None:
        _retry.call(jax.distributed.initialize, coordinator_address,
                    num_processes, process_id,
                    policy=_INIT_POLICY, name="fleet.init")
    _state["initialized"] = True
    # fleet observability: from here on every metric/span this process
    # exports carries its rank (registry default-labels hook; zero cost
    # while telemetry is off, and snapshot() gains process.index/count)
    try:
        _tm.fleet.configure_from_jax()
    except Exception:
        pass   # observability must never block gang bring-up


# elastic re-form is noisier than first bring-up: every surviving rank
# tears down and reconnects at once, racing the coordinator's own
# restart — more attempts, longer deadline. Classification is the
# point: coordinator-unavailable / failed-to-connect / address-in-use
# are Retryable (retry.transient's transport markers), a TypeError or
# config bug surfaces on attempt 1.
_REFORM_POLICY = _retry.RetryPolicy(max_attempts=5, base_delay_s=0.5,
                                    max_delay_s=8.0, deadline_s=180.0)


def reform(coordinator_address=None, num_processes=None,
           process_id=None):
    """Tear down the collective world and bring it back up — the
    elastic re-form step (resilience/elastic.py drives this when a
    rank dies or a resize request arrives, then restores from the
    topology-independent checkpoint). Single-process (no coordinator
    address): there is no gang to tear down, only the fleet telemetry
    identity is refreshed. Multi-process: jax.distributed.shutdown()
    best-effort (a dead coordinator raising here is exactly WHY we are
    re-forming), then initialize at the new world size under
    _REFORM_POLICY."""
    if coordinator_address is not None:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass   # already down — the dead coordinator is the cause
        _retry.call(jax.distributed.initialize, coordinator_address,
                    num_processes, process_id,
                    policy=_REFORM_POLICY, name="fleet.reform")
    _state["initialized"] = True
    try:
        _tm.fleet.configure_from_jax()
    except Exception:
        pass   # observability must never block re-form
    if _tm.enabled():
        _tm.counter("fleet.reforms").inc()


def worker_num():
    return jax.process_count()


def worker_index():
    return jax.process_index()


def is_first_worker():
    return jax.process_index() == 0


def barrier_all():
    """Blocking barrier: a real psum collective over ALL devices (and a
    host-level sync across processes when running multi-host) — the
    NCCL/gRPC barrier analog, not a single-device no-op.

    With telemetry on, the moment the barrier RETURNS is stamped as a
    fleet clock marker: every rank's marker corresponds to (nearly) the
    same true instant, which is what lets stitch_traces put all ranks'
    span timelines on one clock."""
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    def _barrier_once():
        # fleet.barrier chaos point INSIDE the retried callable, so an
        # injected transient (barrier_fail:at=N,times=K) exercises the
        # same absorb-and-retry path a real coordinator flake takes
        _chaos.check("fleet.barrier")
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("fleet_barrier_all")
        else:
            devs = jax.devices()
            mesh = Mesh(np.array(devs), ("all",))
            f = jax.jit(
                jax.shard_map(lambda x: jax.lax.psum(x, "all"),
                              mesh=mesh, in_specs=P("all"),
                              out_specs=P()),
                in_shardings=NamedSharding(mesh, P("all")))
            jax.block_until_ready(f(jnp.ones(len(devs))))

    with _tm.span("fleet.barrier_all", cat="fleet"):
        _retry.call(_barrier_once, policy=_BARRIER_POLICY,
                    name="fleet.barrier")
    if _tm.enabled():
        _tm.counter("fleet.barriers").inc()
        _tm.fleet.mark_clock()


def distributed_optimizer(optimizer, strategy=None):
    """Wrap an Optimizer so minimize() also prepares the distributed
    sharding plan (ref fleet.distributed_optimizer)."""
    cfg = strategy or DistributeTranspilerConfig()

    class _Wrapped:
        def __init__(self, inner):
            self._inner = inner
            self.transpiler = None

        def minimize(self, loss, **kw):
            result = self._inner.minimize(loss, **kw)
            t = DistributeTranspiler(cfg)
            t.transpile(program=loss.block.program)
            self.transpiler = t
            _state["transpiler"] = t
            return result

        def __getattr__(self, k):
            return getattr(self._inner, k)

    return _Wrapped(optimizer)
