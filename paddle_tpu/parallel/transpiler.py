"""DistributeTranspiler — SPMD program rewriting.

Parity: python/paddle/fluid/transpiler/distribute_transpiler.py. The
reference rewrites a Program into trainer+pserver programs wired with
gRPC send/recv or NCCL allreduce. On TPU there is ONE SPMD program: the
transpiler instead decides the Mesh and the sharding of every feed /
param / optimizer-state var, and the jit'ed step gets those shardings —
XLA inserts the collectives (grad psum ≙ NCCL allreduce; ZeRO opt-state
sharding ≙ pserver ownership of param blocks).
"""
import re

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..distribute_lookup_table import find_distributed_lookup_table
from .mesh import make_mesh, local_mesh
from .sharding import ShardingRules, megatron_rules, zero_stage

# optimizer accumulator kinds (optimizer.py _add_accumulator callers) —
# used to EXACTLY match a table's moment vars by name, never a
# coincidentally-prefixed parameter
_ACCUM_KINDS = ("moment1", "moment2", "moment", "velocity", "inf_norm",
                "mean_square", "mean_grad", "squared", "linear",
                "avg_squared_grad", "avg_squared_update")

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]


class DistributeTranspilerConfig:
    """ref transpiler config (slice_var_up etc. → sharding knobs)."""

    def __init__(self):
        # "collective" | "zero" (opt-state sharded over dp — the
        # pserver analog) | "zero3" (params AND opt state sharded over
        # dp on dim 0; XLA GSPMD inserts the use-site all-gathers and
        # grad reduce-scatters — full-parameter memory scaling)
        self.mode = "collective"
        self.dp = None                  # default: all devices
        self.tp = 1
        self.sp = 1
        self.pp = 1
        self.tp_rules = None            # ShardingRules for tensor parallel
        self.sp_feed_axes = {}          # feed name -> sp axis (None: exempt)
        self.min_block_size = 8192      # parity knob (unused: XLA tiles)


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self.mesh = None
        self._shardings = None

    def transpile(self, trainer_id=0, program=None, pservers=None,
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint=None):
        """Build the mesh + sharding table for `program`.

        trainers/pservers args are accepted for API parity; device count
        comes from the JAX runtime (ICI mesh), endpoints are meaningless
        on TPU (no gRPC plane).
        """
        from ..core.framework import default_main_program
        self.program = program or default_main_program()
        cfg = self.config
        ndev = len(jax.devices())
        dp = cfg.dp or max(1, ndev // (cfg.tp * cfg.sp * cfg.pp))
        self.mesh = make_mesh(dp=dp, tp=cfg.tp, sp=cfg.sp, pp=cfg.pp)
        pvars = list(self.program.persistable_vars())
        shapes = {v.name: tuple(int(s) for s in (v.shape or ()))
                  for v in pvars}
        names = list(shapes)
        repl = NamedSharding(self.mesh, P())
        shardings = {n: repl for n in names}

        def fits(name, spec):
            """Spec applies only if the var's shape tiles onto the mesh
            axes (the reference's slice_variable analog: a param that
            can't split stays replicated). Tuple entries mean a dim
            sharded over SEVERAL axes (their sizes multiply)."""
            shape = shapes[name]
            if len(shape) < len(spec):
                return False
            for dim, ax in zip(shape, spec):
                if ax is None:
                    continue
                axes = ax if isinstance(ax, tuple) else (ax,)
                n = 1
                for a in axes:
                    n *= self.mesh.shape[a]
                if dim % n != 0:
                    return False
            return True

        if cfg.tp > 1:
            rules = cfg.tp_rules or megatron_rules()
            for n in names:
                spec = rules.spec(n)
                if spec != P() and fits(n, spec):
                    shardings[n] = NamedSharding(self.mesh, spec)
        if cfg.mode == "zero":
            for n, sh in zero_stage(self.mesh, names, axis="dp").items():
                if sh.spec == P() or fits(n, sh.spec):
                    shardings[n] = sh
        elif cfg.mode == "zero3":
            # dim-0 shard everything replicated so far whose leading
            # dim tiles on dp (params, moments, accumulators alike);
            # non-tiling vars and scalars (lr, beta pows) replicate —
            # same fallback contract as the tp rules above
            for n in names:
                if shardings[n].spec != P():
                    continue  # tp/table rules take precedence
                shape = shapes[n]
                if not shape:
                    continue
                spec = P("dp", *([None] * (len(shape) - 1)))
                if fits(n, spec):
                    shardings[n] = NamedSharding(self.mesh, spec)
        # the distributed lookup table (ref distribute_lookup_table.py →
        # pserver row partitioning): row-shard the table AND its
        # optimizer accumulators over as many axes as divide the vocab
        # — (dp, tp) combined when possible, else whichever fits; XLA
        # SPMD partitions the lookup gather and the (sparse) update
        # scatter — HBM per chip holds vocab/N rows, the ICI gather
        # replaces the pserver prefetch RPC.
        table = find_distributed_lookup_table(self.program)
        if table is not None and table in shapes:
            tail = [None] * (len(shapes[table]) - 1)
            candidates = [P(("dp", "tp"), *tail)] if cfg.tp > 1 else []
            candidates += [P("tp", *tail)] if cfg.tp > 1 else []
            candidates += [P("dp", *tail)]
            spec = next((s for s in candidates if fits(table, s)), None)
            if spec is not None:
                accum_re = re.compile(
                    re.escape(table) + "_(" + "|".join(_ACCUM_KINDS)
                    + r")_\d+$")
                shardings[table] = NamedSharding(self.mesh, spec)
                for n in names:
                    # row-shaped accumulators follow the table; scalars
                    # (beta pows) stay replicated via the shape check
                    if accum_re.fullmatch(n) and shapes[n] == \
                            shapes[table]:
                        shardings[n] = NamedSharding(self.mesh, spec)
        self._shardings = shardings
        return self

    def get_trainer_program(self):
        """The SPMD program IS the trainer program (no pserver split)."""
        return self.program

    def get_pserver_program(self, endpoint=None):
        raise NotImplementedError(
            "No pserver role on TPU: optimizer-state sharding over the dp "
            "axis (config.mode='zero') provides the same memory scaling; "
            "see SURVEY §6")

    def get_startup_program(self, endpoint=None, pserver_program=None):
        from ..core.framework import default_startup_program
        return default_startup_program()

    # ------------------------------------------------------------------
    def shardings(self):
        if self._shardings is None:
            raise RuntimeError("call transpile() first")
        return dict(self._shardings)

    def feed_sharding(self, shape, name=None):
        """THE feed-sharding policy (ParallelExecutor delegates here):
        axis 0 over dp; with sp>1 configured, axis 1 over sp when
        divisible — sequence feeds keep their time axis sharded so
        activations stay T-sharded through elementwise/ffn ops (XLA
        gathers where attention needs cross-shard keys; numerics are
        layout-independent). Non-sequence feeds whose axis 1 happens to
        divide sp only pay an extra gather — exempt them via
        config.sp_feed_axes[name] = None."""
        shape = tuple(shape)
        ndim = len(shape)
        if ndim == 0:
            return NamedSharding(self.mesh, P())
        # a batch that doesn't tile onto dp stays replicated (the
        # reference's slice_variable remainder handling analog) — an
        # uneven device_put would hard-error. Loud: silently disabled
        # data parallelism is an n-times throughput loss. Multi-host:
        # the shape is the host-LOCAL batch; dp must divide the global
        # batch (nproc local batches concatenated).
        dp = self.mesh.shape.get("dp", 1)
        dp_ok = (shape[0] * jax.process_count()) % dp == 0
        if not dp_ok and dp > 1:
            if jax.process_count() > 1:
                # replication cannot represent divergent per-host
                # batches (see ParallelExecutor._feed_sharding)
                raise RuntimeError(
                    f"feed batch {shape[0]} x {jax.process_count()} "
                    f"hosts does not divide dp={dp}; pad the local "
                    "batch (multi-host feeds cannot fall back to "
                    "replication)")
            import warnings
            warnings.warn(
                f"feed batch {shape[0]} does not divide dp={dp}; "
                "replicating this feed (no data parallelism for it)")
        axes = ["dp" if dp_ok else None] + [None] * (ndim - 1)
        sp = self.mesh.shape.get("sp", 1)
        override = getattr(self.config, "sp_feed_axes", {}) or {}
        if name is not None and name in override:
            ax = override[name]
            if ax is not None:
                axes[ax] = "sp"
        elif sp > 1 and ndim >= 2 and shape[1] % sp == 0:
            axes[1] = "sp"
        return NamedSharding(self.mesh, P(*axes))
