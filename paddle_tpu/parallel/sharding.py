"""Parameter/activation sharding rules.

TPU-native replacement for the reference's distribute_transpiler param
splitting (python/paddle/fluid/transpiler/distribute_transpiler.py:
slice_variable → pserver blocks). Rules produce PartitionSpecs per
parameter name for Megatron-style tensor parallel and ZeRO-style
optimizer-state sharding — XLA moves the bytes over ICI.
"""
import re

from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ShardingRules", "megatron_rules", "zero_stage", "spec_for"]


class ShardingRules:
    """Ordered (regex → PartitionSpec) table with a default."""

    def __init__(self, rules=None, default=P()):
        self.rules = list(rules or [])
        self.default = default

    def add(self, pattern, spec):
        self.rules.append((re.compile(pattern), spec))
        return self

    def spec(self, name, ndim=None):
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        return self.default

    def shardings(self, mesh, names):
        return {n: NamedSharding(mesh, self.spec(n)) for n in names}


def megatron_rules(tp_axis="tp"):
    """Column-parallel first FF / QKV, row-parallel second FF / out-proj,
    vocab-parallel embedding — the standard Megatron layout."""
    r = ShardingRules()
    r.add(r"(_q|_k|_v|ffn1|fc1|col)\S*\.w", P(None, tp_axis))
    r.add(r"(_o|ffn2|fc2|row)\S*\.w", P(tp_axis, None))
    r.add(r"embedding\S*\.w", P(tp_axis, None))
    return r


def zero_stage(mesh, names, axis="dp"):
    """ZeRO-1 layout: optimizer accumulators sharded along dp — the
    TPU-native pserver analog (each dp member owns a param shard's
    state, like each pserver owned a param block in the reference)."""
    specs = {}
    for n in names:
        if any(t in n for t in ("moment", "velocity", "_acc", "beta",
                                "mean_square", "inf_norm")):
            specs[n] = NamedSharding(mesh, P(axis))
        else:
            specs[n] = NamedSharding(mesh, P())
    return specs


def spec_for(var_name, rules, mesh):
    return NamedSharding(mesh, rules.spec(var_name))
