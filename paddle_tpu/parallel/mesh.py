"""Device mesh helpers.

The reference scales by spawning one CUDA stream/process per GPU and
wiring NCCL rings (paddle/fluid/framework/details/*_ssa_graph*); here a
single SPMD program spans a jax.sharding.Mesh. Axis conventions:
    dp — data parallel (batch)
    tp — tensor/model parallel (Megatron-style)
    sp — sequence/context parallel (ring/Ulysses attention)
    pp — pipeline stages
    ep — expert parallel (switch-MoE, parallel/moe.py)
Multi-host: the same Mesh API spans hosts after
jax.distributed.initialize(); dp/pp map naturally onto DCN, tp/sp/ep
onto ICI (scaling-book layout; ep sits between dp and sp so expert
all-to-alls stay on-host).
"""
import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

__all__ = ["make_mesh", "local_mesh", "axis_size", "device_slices",
           "P", "NamedSharding", "Mesh"]

P = PartitionSpec


def make_mesh(dp=1, tp=1, sp=1, pp=1, ep=1, devices=None):
    """Create a Mesh with the canonical axis order (pp, dp, ep, sp, tp).

    tp/sp innermost → neighboring devices (fastest ICI links) carry the
    highest-bandwidth collectives; ep between dp and sp so expert
    all-to-alls stay within a host; dp outermost → gradient all-reduce
    can cross DCN on multi-host."""
    devices = list(devices if devices is not None else jax.devices())
    need = dp * tp * sp * pp * ep
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(pp, dp, ep, sp, tp)
    return Mesh(arr, axis_names=("pp", "dp", "ep", "sp", "tp"))


def local_mesh(axis="dp", devices=None):
    """1-D mesh over all local devices (the ParallelExecutor default —
    the analog of the reference's use_cuda=True all-GPU setup)."""
    devices = list(devices if devices is not None else jax.devices())
    arr = np.asarray(devices)
    return Mesh(arr, axis_names=(axis,))


def axis_size(mesh, name):
    return mesh.shape[name] if name in mesh.shape else 1


def device_slices(n, devices=None, reserve=0):
    """Partition the local devices into `reserve` dedicated head
    devices plus `n` DISJOINT contiguous slices — the placement
    primitive of serving replica groups (`serving.farm`): each decode
    replica owns one slice, the reserved heads carry disaggregated
    prefill executables.

    Contiguity matters for the same reason make_mesh puts tp/sp
    innermost: a replica's devices stay ICI neighbors, so any future
    intra-replica sharding rides the fastest links. Returns
    ``(reserved, slices)`` with ``len(slices) == n``.

    When there are fewer devices than ``reserve + n`` the slices wrap
    around and SHARE devices (single-device CPU fallback — every
    "slice" aliases the same physical device; placement becomes a
    no-op but the replica topology still exercises end-to-end).
    Leftover devices after an even split are appended to the last
    slice rather than idling."""
    devices = list(devices if devices is not None else jax.devices())
    if n < 1 or reserve < 0:
        raise ValueError(f"need n >= 1 slices (got {n}) and "
                         f"reserve >= 0 (got {reserve})")
    if not devices:
        raise ValueError("no devices to slice")
    if len(devices) < reserve + n:        # wrap-around sharing
        reserved = [devices[i % len(devices)] for i in range(reserve)]
        slices = [[devices[(reserve + i) % len(devices)]]
                  for i in range(n)]
        return reserved, slices
    reserved = devices[:reserve]
    rest = devices[reserve:]
    per = len(rest) // n
    slices = [rest[i * per:(i + 1) * per] for i in range(n)]
    slices[-1].extend(rest[n * per:])
    return reserved, slices
