"""Device mesh helpers.

The reference scales by spawning one CUDA stream/process per GPU and
wiring NCCL rings (paddle/fluid/framework/details/*_ssa_graph*); here a
single SPMD program spans a jax.sharding.Mesh. Axis conventions:
    dp — data parallel (batch)
    tp — tensor/model parallel (Megatron-style)
    sp — sequence/context parallel (ring attention)
    pp — pipeline stages
Multi-host: the same Mesh API spans hosts after
jax.distributed.initialize(); dp/pp map naturally onto DCN, tp/sp onto
ICI (scaling-book layout).
"""
import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

__all__ = ["make_mesh", "local_mesh", "axis_size", "P", "NamedSharding",
           "Mesh"]

P = PartitionSpec


def make_mesh(dp=1, tp=1, sp=1, pp=1, devices=None):
    """Create a Mesh with the canonical axis order (pp, dp, sp, tp).

    tp/sp innermost → neighboring devices (fastest ICI links) carry the
    highest-bandwidth collectives, dp outermost → gradient all-reduce can
    cross DCN on multi-host."""
    devices = list(devices if devices is not None else jax.devices())
    need = dp * tp * sp * pp
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(pp, dp, sp, tp)
    return Mesh(arr, axis_names=("pp", "dp", "sp", "tp"))


def local_mesh(axis="dp", devices=None):
    """1-D mesh over all local devices (the ParallelExecutor default —
    the analog of the reference's use_cuda=True all-GPU setup)."""
    devices = list(devices if devices is not None else jax.devices())
    arr = np.asarray(devices)
    return Mesh(arr, axis_names=(axis,))


def axis_size(mesh, name):
    return mesh.shape[name] if name in mesh.shape else 1
