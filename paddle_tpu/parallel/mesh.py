"""Device mesh helpers.

The reference scales by spawning one CUDA stream/process per GPU and
wiring NCCL rings (paddle/fluid/framework/details/*_ssa_graph*); here a
single SPMD program spans a jax.sharding.Mesh. Axis conventions:
    dp — data parallel (batch)
    tp — tensor/model parallel (Megatron-style)
    sp — sequence/context parallel (ring/Ulysses attention)
    pp — pipeline stages
    ep — expert parallel (switch-MoE, parallel/moe.py)
Multi-host: the same Mesh API spans hosts after
jax.distributed.initialize(); dp/pp map naturally onto DCN, tp/sp/ep
onto ICI (scaling-book layout; ep sits between dp and sp so expert
all-to-alls stay on-host).
"""
import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

__all__ = ["make_mesh", "local_mesh", "axis_size", "P", "NamedSharding",
           "Mesh"]

P = PartitionSpec


def make_mesh(dp=1, tp=1, sp=1, pp=1, ep=1, devices=None):
    """Create a Mesh with the canonical axis order (pp, dp, ep, sp, tp).

    tp/sp innermost → neighboring devices (fastest ICI links) carry the
    highest-bandwidth collectives; ep between dp and sp so expert
    all-to-alls stay within a host; dp outermost → gradient all-reduce
    can cross DCN on multi-host."""
    devices = list(devices if devices is not None else jax.devices())
    need = dp * tp * sp * pp * ep
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(pp, dp, ep, sp, tp)
    return Mesh(arr, axis_names=("pp", "dp", "ep", "sp", "tp"))


def local_mesh(axis="dp", devices=None):
    """1-D mesh over all local devices (the ParallelExecutor default —
    the analog of the reference's use_cuda=True all-GPU setup)."""
    devices = list(devices if devices is not None else jax.devices())
    arr = np.asarray(devices)
    return Mesh(arr, axis_names=(axis,))


def axis_size(mesh, name):
    return mesh.shape[name] if name in mesh.shape else 1
