"""Device mesh helpers.

The reference scales by spawning one CUDA stream/process per GPU and
wiring NCCL rings (paddle/fluid/framework/details/*_ssa_graph*); here a
single SPMD program spans a jax.sharding.Mesh. Axis conventions:
    dp — data parallel (batch)
    tp — tensor/model parallel (Megatron-style)
    sp — sequence/context parallel (ring/Ulysses attention)
    pp — pipeline stages
    ep — expert parallel (switch-MoE, parallel/moe.py)
Multi-host: the same Mesh API spans hosts after
jax.distributed.initialize(); dp/pp map naturally onto DCN, tp/sp/ep
onto ICI (scaling-book layout; ep sits between dp and sp so expert
all-to-alls stay on-host).
"""
import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

__all__ = ["make_mesh", "local_mesh", "axis_size", "device_slices",
           "SliceAllocator", "P", "NamedSharding", "Mesh"]

P = PartitionSpec


def make_mesh(dp=1, tp=1, sp=1, pp=1, ep=1, devices=None):
    """Create a Mesh with the canonical axis order (pp, dp, ep, sp, tp).

    tp/sp innermost → neighboring devices (fastest ICI links) carry the
    highest-bandwidth collectives; ep between dp and sp so expert
    all-to-alls stay within a host; dp outermost → gradient all-reduce
    can cross DCN on multi-host."""
    devices = list(devices if devices is not None else jax.devices())
    need = dp * tp * sp * pp * ep
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(pp, dp, ep, sp, tp)
    return Mesh(arr, axis_names=("pp", "dp", "ep", "sp", "tp"))


def local_mesh(axis="dp", devices=None):
    """1-D mesh over all local devices (the ParallelExecutor default —
    the analog of the reference's use_cuda=True all-GPU setup)."""
    devices = list(devices if devices is not None else jax.devices())
    arr = np.asarray(devices)
    return Mesh(arr, axis_names=(axis,))


def axis_size(mesh, name):
    return mesh.shape[name] if name in mesh.shape else 1


def device_slices(n, devices=None, reserve=0):
    """Partition the local devices into `reserve` dedicated head
    devices plus `n` DISJOINT contiguous slices — the placement
    primitive of serving replica groups (`serving.farm`): each decode
    replica owns one slice, the reserved heads carry disaggregated
    prefill executables.

    Contiguity matters for the same reason make_mesh puts tp/sp
    innermost: a replica's devices stay ICI neighbors, so any future
    intra-replica sharding rides the fastest links. Returns
    ``(reserved, slices)`` with ``len(slices) == n``.

    When there are fewer devices than ``reserve + n`` the slices wrap
    around and SHARE devices (single-device CPU fallback — every
    "slice" aliases the same physical device; placement becomes a
    no-op but the replica topology still exercises end-to-end).
    Leftover devices after an even split are appended to the last
    slice rather than idling."""
    devices = list(devices if devices is not None else jax.devices())
    if n < 1 or reserve < 0:
        raise ValueError(f"need n >= 1 slices (got {n}) and "
                         f"reserve >= 0 (got {reserve})")
    if not devices:
        raise ValueError("no devices to slice")
    if len(devices) < reserve + n:        # wrap-around sharing
        reserved = [devices[i % len(devices)] for i in range(reserve)]
        slices = [[devices[(reserve + i) % len(devices)]]
                  for i in range(n)]
        return reserved, slices
    reserved = devices[:reserve]
    rest = devices[reserve:]
    per = len(rest) // n
    slices = [rest[i * per:(i + 1) * per] for i in range(n)]
    slices[-1].extend(rest[n * per:])
    return reserved, slices


class SliceAllocator:
    """Ownership ledger over a device pool: `device_slices` with a
    free/re-allocation API — the placement bookkeeping autoscaling
    (`serving.scale`) needs that a one-shot partition cannot provide.

    `alloc(width)` hands out a slice of `width` devices: EXCLUSIVE
    while the free pool covers it, falling back to the same
    wrap-around SHARING as `device_slices` when the pool is exhausted
    (every device already owned — the single-device CPU shape).
    `free(slc)` returns a slice's devices for reuse.

    The subtlety free() must get right (the bug this class exists to
    fix, regression-pinned in tests): a *shared* slice's devices are
    aliases of devices some exclusive owner still holds. A naive
    ledger that pushed them back into the free pool would let the next
    `alloc` — especially one at a DIFFERENT width than the freed slice
    — hand out a device twice, once "exclusively". Here sharing is
    tracked per allocation: freeing a shared slice never feeds the
    free pool, while freeing an exclusive slice returns exactly its
    devices (original pool order preserved, so re-allocation keeps ICI
    neighbors contiguous even at a different width)."""

    def __init__(self, devices=None, reserve=0):
        devices = list(devices if devices is not None
                       else jax.devices())
        if reserve < 0 or reserve > len(devices):
            raise ValueError(
                f"reserve={reserve} outside [0, {len(devices)}]")
        self.reserved = devices[:reserve]
        self.pool = devices[reserve:]
        if not self.pool:
            raise ValueError("no devices left to allocate after "
                             f"reserve={reserve}")
        self._order = {id(d): i for i, d in enumerate(self.pool)}
        self._free = list(self.pool)
        self._exclusive = []     # [set(id(dev))] live exclusive slices
        self._shared = []        # [frozenset ids] live shared slices
        self._wrap = 0           # rotation cursor for shared slices

    # ------------------------------------------------------ accounting
    def free_count(self):
        """Devices available for an exclusive allocation."""
        return len(self._free)

    def can_alloc(self, width=1, shared_ok=False):
        """Would `alloc(width)` succeed without sharing? (With
        `shared_ok`, alloc never fails — this is the planner's device
        ceiling probe.)"""
        return shared_ok or len(self._free) >= max(1, int(width))

    # ------------------------------------------------------ allocation
    def alloc(self, width=1, shared_ok=False):
        """Take `width` devices. Exclusive when the free pool covers
        the request; wrap-around shared when it doesn't and
        `shared_ok` — otherwise RuntimeError (the device ceiling)."""
        width = max(1, int(width))
        if len(self._free) >= width:
            slc = self._free[:width]
            del self._free[:width]
            self._exclusive.append({id(d) for d in slc})
            return slc
        if not shared_ok:
            raise RuntimeError(
                f"device ceiling: want {width} device(s), "
                f"{len(self._free)} free of {len(self.pool)}")
        slc = [self.pool[(self._wrap + i) % len(self.pool)]
               for i in range(width)]
        self._wrap = (self._wrap + width) % len(self.pool)
        self._shared.append(frozenset(id(d) for d in slc))
        return slc

    def adopt(self, slc):
        """Register a slice allocated elsewhere (`device_slices` at
        group construction) so this ledger can later free it. Devices
        already owned mark the adoption shared — a wrapped
        `device_slices` layout adopts as all-shared, so freeing it
        never pollutes the pool."""
        ids = {id(d) for d in slc}
        if any(i not in self._order for i in ids):
            raise ValueError("adopted slice holds devices outside "
                             "this allocator's pool")
        free_ids = {id(d) for d in self._free}
        if ids <= free_ids:
            self._free = [d for d in self._free if id(d) not in ids]
            self._exclusive.append(ids)
        else:
            self._shared.append(frozenset(ids))
        return slc

    def free(self, slc):
        """Release a slice. Exclusive devices rejoin the free pool in
        stable pool order (reusable at any width); shared aliases are
        just forgotten. Unknown slices raise — double-free is a bug,
        not a no-op."""
        ids = {id(d) for d in slc}
        fids = frozenset(ids)
        if fids in self._shared:
            self._shared.remove(fids)
            return 0
        for owned in self._exclusive:
            if owned == ids:
                self._exclusive.remove(owned)
                self._free.extend(slc)
                self._free.sort(key=lambda d: self._order[id(d)])
                return len(slc)
        raise ValueError("free() of a slice this allocator never "
                         "allocated (or already freed)")
