"""gradsync — bucketed, quantized, and overlapped gradient synchronization.

Parity: the reference's BuildStrategy.fuse_all_reduce_ops +
fuse_grad_size_in_MB (NCCL fused all-reduce) and the DGC/fp16 allreduce
strategies, rebuilt as a TPU-native policy layer (ROADMAP item 2,
EQuARX in PAPERS.md).

Without a policy, dp gradient sync is implicit: ParallelExecutor jits
the step over a dp-sharded batch and XLA inserts one fp32 all-reduce
per parameter gradient behind the whole backward pass. With a policy,
the executor runs the SAME traced step under shard_map over the dp
axis, so gradients come out of value_and_grad as per-member partials
and the sync becomes an explicit, controllable sequence of collectives
with three composable levers:

- **bucketing**: gradients are flattened and concatenated into
  fixed-size fusion buffers (default 4 MiB) in reverse-topological
  (last-layer-first) order, so N params cost ceil(total/bucket)
  collectives instead of N.
- **quantization**: `bf16` cast-reduce-cast, or `int8` blockwise
  quantized all-reduce (per-block fp32 scales, accumulation in fp32
  after dequantize) with **error feedback** — the quantization residual
  is carried as persistable per-member state in the scope (one
  `gradsync.ef.<bucket>` var per bucket, dp-sharded) so it rides the
  executor's existing donate/sharding path.
- **overlap**: each bucket's collective depends only on that bucket's
  gradients, so XLA's async collectives can overlap bucket N's sync
  with the rest of the step; `overlap=0` chains buckets through
  optimization barriers to serialize them (the A/B baseline).

Selection: `ParallelExecutor(grad_sync="int8")`, the
`PADDLE_TPU_GRAD_SYNC` env var, or `optimizer.minimize(loss,
grad_sync=...)`. Spec grammar: `mode[:k=v,...]` with mode one of
fp32/bf16/int8 and knobs `bucket_mb`/`bucket_kb`/`bucket_bytes`,
`block` (int8 block size), `ef` (0/1 error feedback), `overlap` (0/1),
`reduce` (mean/sum — must match how the loss reduces over the batch;
`mean` matches `layers.mean(...)` losses and the implicit-sync
numerics). Unset/"off" keeps today's implicit path bit-identical.

Numerics contract: the explicit path assumes pure data parallelism
(replicated params; rejected when a transpiler shards them) and a
batch-`mean` (or `sum`) loss. `fp32` is exact up to summation order;
`bf16`/`int8` are lossy by design, with error feedback keeping the
*accumulated* update unbiased (residuals are re-fed into the next
step's quantizer).

Telemetry (trace-time, like collective.*): `gradsync.buckets`,
`gradsync.raw_bytes` / `gradsync.wire_bytes` counters and the
`gradsync.compression_ratio` gauge — surfaced per rank in
`tpustat --fleet`.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry as _tm
from . import collective as C

__all__ = ["GradSyncPolicy", "parse_policy", "resolve_policy",
           "plan_buckets", "state_entries", "ef_footprint_bytes",
           "sync_gradients",
           "make_grad_transform", "make_probe_transform",
           "quantize_int8_blockwise", "dequantize_int8_blockwise",
           "EF_PREFIX"]

EF_PREFIX = "gradsync.ef."
ENV_VAR = "PADDLE_TPU_GRAD_SYNC"

_MODES = ("fp32", "bf16", "int8")


class GradSyncPolicy:
    """One resolved gradient-sync policy (see module docstring)."""

    def __init__(self, mode="fp32", bucket_bytes=4 << 20, block_size=256,
                 error_feedback=None, overlap=True, reduce="mean",
                 axis_name="dp"):
        if mode not in _MODES:
            raise ValueError(f"grad_sync mode {mode!r} not in {_MODES}")
        if reduce not in ("mean", "sum"):
            raise ValueError(f"grad_sync reduce {reduce!r} not in "
                             "('mean', 'sum')")
        if bucket_bytes < 1024:
            raise ValueError(f"grad_sync bucket_bytes {bucket_bytes} "
                             "too small (min 1024)")
        if block_size < 1:
            raise ValueError("grad_sync block size must be >= 1")
        self.mode = mode
        self.bucket_bytes = int(bucket_bytes)
        self.block_size = int(block_size)
        # error feedback defaults on only where the wire is lossy enough
        # to need it (int8); bf16 can opt in
        self.error_feedback = (mode == "int8") if error_feedback is None \
            else bool(error_feedback)
        if mode == "fp32":
            self.error_feedback = False
        self.overlap = bool(overlap)
        self.reduce = reduce
        self.axis_name = axis_name

    def key(self):
        """Hashable identity for the executor's compile cache."""
        return ("gradsync", self.mode, self.bucket_bytes,
                self.block_size, self.error_feedback, self.overlap,
                self.reduce, self.axis_name)

    def __repr__(self):
        return (f"GradSyncPolicy(mode={self.mode!r}, "
                f"bucket_bytes={self.bucket_bytes}, "
                f"block_size={self.block_size}, "
                f"error_feedback={self.error_feedback}, "
                f"overlap={self.overlap}, reduce={self.reduce!r})")


def parse_policy(spec):
    """Parse a policy spec (string / GradSyncPolicy / None) — returns a
    GradSyncPolicy or None for off. Grammar: `mode[:k=v,...]`."""
    if spec is None or isinstance(spec, GradSyncPolicy):
        return spec
    s = str(spec).strip().lower()
    if s in ("", "0", "off", "none", "false"):
        return None
    mode, _, opts = s.partition(":")
    kw = {}
    for item in filter(None, (t.strip() for t in opts.split(","))):
        k, eq, v = item.partition("=")
        if not eq:
            raise ValueError(f"grad_sync option {item!r} is not k=v")
        if k == "bucket_mb":
            kw["bucket_bytes"] = int(float(v) * (1 << 20))
        elif k == "bucket_kb":
            kw["bucket_bytes"] = int(float(v) * 1024)
        elif k == "bucket_bytes":
            kw["bucket_bytes"] = int(v)
        elif k == "block":
            kw["block_size"] = int(v)
        elif k == "ef":
            kw["error_feedback"] = v not in ("0", "false", "off")
        elif k == "overlap":
            kw["overlap"] = v not in ("0", "false", "off")
        elif k == "reduce":
            kw["reduce"] = v
        else:
            raise ValueError(f"unknown grad_sync option {k!r}")
    return GradSyncPolicy(mode=mode, **kw)


def resolve_policy(arg=None, program=None):
    """Executor-side resolution: explicit arg (including "off") beats
    the PADDLE_TPU_GRAD_SYNC env var beats the program's minimize-time
    hint. Returns GradSyncPolicy or None."""
    if arg is not None:
        return parse_policy(arg)
    env = os.environ.get(ENV_VAR)
    if env is not None and env.strip():
        return parse_policy(env)
    hint = getattr(program, "_grad_sync", None)
    if hint is not None:
        return parse_policy(hint)
    return None


# --------------------------------------------------------------- buckets

class Bucket:
    """One fusion buffer: `entries` = [(name, shape, n_elems)] in sync
    order, `n_elems` their total, `padded` the flat length rounded up
    to the quantization block."""

    def __init__(self, index, dtype, block_size):
        self.index = index
        self.dtype = dtype
        self.block_size = block_size
        self.entries = []
        self.n_elems = 0

    @property
    def padded(self):
        b = self.block_size
        return max(-(-self.n_elems // b) * b, b)

    def add(self, name, shape, n):
        self.entries.append((name, tuple(shape), int(n)))
        self.n_elems += int(n)


def plan_buckets(named_shapes, bucket_bytes=4 << 20, block_size=256):
    """Partition params into buckets. `named_shapes` is
    [(name, shape, dtype)] in FORWARD declaration order; buckets are
    built over the REVERSED list (reverse-topological: the backward
    pass produces last-declared grads first, so bucket 0 can start
    syncing while earlier layers' grads are still being computed).
    Buckets are dtype-homogeneous; a param larger than `bucket_bytes`
    gets a bucket of its own."""
    buckets = []
    cur = None
    for name, shape, dtype in reversed(list(named_shapes)):
        dt = np.dtype(jnp.dtype(dtype).name if hasattr(dtype, "name")
                      else dtype)
        n = int(np.prod(shape)) if len(tuple(shape)) else 1
        nbytes = n * dt.itemsize
        if (cur is None or cur.dtype != dt
                or (cur.n_elems * dt.itemsize + nbytes > bucket_bytes
                    and cur.entries)):
            cur = Bucket(len(buckets), dt, block_size)
            buckets.append(cur)
        cur.add(name, shape, n)
    return buckets


def state_entries(plan, policy):
    """[(name, local_len)] of the error-feedback residual buffers this
    policy carries (empty for fp32 / ef=off). The executor stores each
    as a dp-sharded persistable of global shape (dp * local_len,)."""
    if policy is None or not policy.error_feedback:
        return []
    return [(EF_PREFIX + str(b.index), b.padded) for b in plan]


def ef_footprint_bytes(plan, policy, dp=1):
    """Analytic device bytes of the error-feedback state this policy
    carries (fp32 residual per bucket element, dp members). The memory
    ledger's gradsync_ef bucket should reconcile against this — the
    runtime analog of meshlint's static gradsync_ef floor."""
    if policy is None or not policy.error_feedback:
        return 0
    return sum(b.padded for b in plan) * 4 * max(1, int(dp))


# ---------------------------------------------------------- quantization
#
# The scheme (per-block absmax/127 fp32 scales, zero blocks keep unit
# scale, codes clipped to ±127 int8) moved to ops/kern/quant.py — ONE
# implementation shared by these buckets, the decode KV cache, and the
# collective wire, with a fused Pallas kernel behind the registry.
# These names stay importable (public API + the KV cache imports them).

def quantize_int8_blockwise(flat, block_size=256):
    """flat fp32 [padded] -> (q int8 [n_blocks, block], scales fp32
    [n_blocks, 1]) with per-block absmax/127 scales (zero blocks get a
    unit scale so the codes stay 0)."""
    from ..ops.kern.quant import quantize_int8_blockwise as impl
    return impl(flat, block_size)


def dequantize_int8_blockwise(q, scales):
    from ..ops.kern.quant import dequantize_int8_blockwise as impl
    return impl(q, scales)


# ----------------------------------------------------------------- sync

def _flatten(grads, bucket):
    parts = [grads[name].reshape(-1) for name, _, _ in bucket.entries]
    flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    pad = bucket.padded - bucket.n_elems
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def _unflatten(flat, bucket):
    out = {}
    off = 0
    for name, shape, n in bucket.entries:
        out[name] = flat[off:off + n].reshape(shape)
        off += n
    return out


def _tie(x, token):
    """Serialize: make x depend on the previous bucket's result so its
    collective cannot be hoisted to overlap (the overlap=0 baseline)."""
    if token is None:
        return x
    barrier = getattr(lax, "optimization_barrier", None)
    if barrier is None:        # very old jax: no barrier, stay overlapped
        return x
    x, _ = barrier((x, token))
    return x


def sync_gradients(grads, env, policy, plan=None, dp=None):
    """Synchronize `grads` (name -> per-member partial gradient) over
    the policy's mesh axis. MUST run inside shard_map with the axis
    bound. `env` supplies the error-feedback residuals under
    `gradsync.ef.<bucket>` (absent -> residual treated as zero and not
    carried). Returns (synced_grads, new_state)."""
    if plan is None:
        plan = plan_buckets([(n, g.shape, g.dtype)
                             for n, g in grads.items()],
                            policy.bucket_bytes, policy.block_size)
    axis = policy.axis_name
    if dp is None:
        dp = jax.lax.axis_size(axis)
    out = {}
    new_state = {}
    raw_bytes = wire_bytes = 0
    token = None
    for b in plan:
        flat = _tie(_flatten(grads, b), token)
        raw_bytes += b.n_elems * b.dtype.itemsize
        if policy.mode == "fp32":
            wire_bytes += b.padded * 4
            total = C.all_reduce(flat.astype(jnp.float32), op="sum",
                                 axis_name=axis)
        else:
            work = flat.astype(jnp.float32)
            ef_name = EF_PREFIX + str(b.index)
            carry = policy.error_feedback and ef_name in env
            if carry:
                work = work + env[ef_name]
            if policy.mode == "bf16":
                wire_bytes += b.padded * 2
                total = C.all_reduce_bf16(work, axis_name=axis)
                if carry:
                    new_state[ef_name] = \
                        work - work.astype(jnp.bfloat16).astype(
                            jnp.float32)
            else:  # int8
                q, scales = quantize_int8_blockwise(work, b.block_size)
                wire_bytes += b.padded + scales.size * 4
                total = C.all_reduce_int8_blockwise(
                    q, scales, axis_name=axis).reshape(-1)
                if carry:
                    new_state[ef_name] = \
                        work - dequantize_int8_blockwise(q, scales)
        if policy.reduce == "mean":
            total = total / dp
        total = total.astype(flat.dtype)
        out.update(_unflatten(total, b))
        token = total[0]
    if _tm.enabled():
        _tm.counter("gradsync.sync_count").inc()
        _tm.gauge("gradsync.buckets").set(len(plan))
        _tm.counter("gradsync.raw_bytes").inc(raw_bytes)
        _tm.counter("gradsync.wire_bytes").inc(wire_bytes)
        if wire_bytes:
            _tm.gauge("gradsync.compression_ratio").set(
                raw_bytes / wire_bytes)
    return out, new_state


def make_grad_transform(policy, plan, dp, sparse_taps=()):
    """The build_step_fn grad_transform hook: (grads, env) ->
    (synced_grads, extra_persist).

    Dense grads (the ones `plan` buckets) sync through the policy's
    bucketed/quantized collectives. `sparse_taps` names the is_sparse
    row-grad taps this policy must NOT bucket but still make globally
    consistent: each tap's per-member row grads and its ids are
    all-gathered over the dp axis (scaled 1/dp for `mean` losses), so
    the replicated table's row-sparse tail update computes the SAME
    merged update on every member — sparse grads skip the quantized
    wire (they belong to the sparse engine; a ShardedTable handles its
    own taps and is excluded from this list)."""
    def transform(grads, env):
        synced, state = sync_gradients(grads, env, policy, plan=plan,
                                       dp=dp)
        for tap in sparse_taps:
            g = C.all_gather(grads[tap["delta"]],
                             axis_name=policy.axis_name, axis=0,
                             tiled=True)
            if policy.reduce == "mean":
                g = g / dp
            synced[tap["delta"]] = g
            env[tap["ids"]] = C.all_gather(env[tap["ids"]],
                                           axis_name=policy.axis_name,
                                           axis=0, tiled=True)
        return synced, state
    return transform


def make_probe_transform(policy, plan, dp, sparse_taps=()):
    """Axis-free shape twin of make_grad_transform for jax.eval_shape
    (the executor's fetch-classification probe): dense grads pass
    through, error-feedback state is zeros of the planned sizes, and
    the sparse-tap all-gathers become dp-fold tiles."""
    ef_entries = state_entries(plan, policy)

    def tile(x):
        return jnp.concatenate([x] * dp, axis=0) if dp > 1 else x

    def transform(grads, env):
        out = {}
        for tap in sparse_taps:
            out[tap["delta"]] = tile(grads[tap["delta"]])
            env[tap["ids"]] = tile(env[tap["ids"]])
        return out, {n: jnp.zeros((l,), jnp.float32)
                     for n, l in ef_entries}
    return transform
