"""All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

Complements parallel/ring_attention.py: instead of rotating K/V blocks
around the ring, two lax.all_to_all collectives re-shard the tensors
head-wise (each sp member holds H/sp heads with the FULL sequence),
run ordinary dense attention locally, and shard back sequence-wise.
Preferable when H >= sp and the per-device full-sequence scores fit in
HBM; ring attention covers the longer-sequence regime. Replaces the
reference's NCCL all-to-all path (paddle/fluid/operators/distributed)
with XLA ICI collectives.
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ulysses_attention"]


def ulysses_attention(mesh, q, k, v, causal=False, scale=None,
                      axis_name="sp"):
    """q/k/v: GLOBAL [B, H, T, D] (sharded or replicated — jit moves
    them); returns [B, H, T, D] attention output sequence-sharded over
    `axis_name`. H must divide by the sp axis size."""
    sp = mesh.shape[axis_name]
    B, H, T, D = q.shape
    if H % sp:
        raise ValueError(f"heads {H} must divide sp={sp}")
    if T % sp:
        raise ValueError(f"sequence {T} must divide sp={sp}")
    scale = scale if scale is not None else D ** -0.5
    # kern-registry seam (ops.registry.accel): no module-level Pallas
    # import; the shared try_flash policy still decides per call
    from ..ops.registry import accel
    fused = accel("flash_attention")

    def local(ql, kl, vl):
        # local [B, H, T/sp, D] → all_to_all → [B, H/sp, T, D]
        ql = lax.all_to_all(ql, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
        kl = lax.all_to_all(kl, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
        vl = lax.all_to_all(vl, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
        # full sequence is local after the all-to-all — the shared
        # try_flash policy decides kernel vs fused-XLA exactly as for
        # single-device attention
        out = fused(ql, kl, vl, causal=causal, scale=scale) \
            if fused is not None else None
        if out is None:
            s = jnp.einsum("bhqd,bhkd->bhqk", ql, kl).astype(jnp.float32)
            s = s * scale
            if causal:
                cm = jnp.tril(jnp.ones((T, T), dtype=bool))
                s = jnp.where(cm, s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(ql.dtype)
            out = jnp.einsum("bhqk,bhkd->bhqd", p, vl)
        # back: [B, H/sp, T, D] → [B, H, T/sp, D]
        return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    seq_spec = P(None, None, axis_name, None)
    fn = jax.jit(jax.shard_map(local, mesh=mesh,
                               in_specs=(seq_spec, seq_spec, seq_spec),
                               out_specs=seq_spec, check_vma=False),
                 in_shardings=NamedSharding(mesh, seq_spec),
                 out_shardings=NamedSharding(mesh, seq_spec))
    return fn(q, k, v)
