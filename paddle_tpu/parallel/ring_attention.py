"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

First-class long-context support (SURVEY §2.4): Q/K/V are sharded along
the sequence dim across `sp` devices; K/V blocks rotate around the ring
via ppermute while each device merges its queries' output in
(out, logsumexp) space — the online-softmax invariant. Peak memory per
device is O(T/sp * T/sp) per block instead of O(T^2); comm rides
neighbor ICI links.

The per-block engine is selected by size: the Pallas flash kernel
(ops/pallas/flash_attention.py, via its lse-returning custom_vjp entry)
when the local block is at/above the measured crossover, else the fused
XLA path. Under causal masking, blocks strictly above the diagonal are
skipped entirely via lax.switch (≈2x fewer FLOPs), the diagonal block
runs the causal kernel, and blocks below run the full kernel.

Public entry: ring_attention(mesh, q, k, v, causal=...) — call with
GLOBAL [B, H, T, D] arrays; returns global output. Inside it shard_maps
over sp. (Ring Attention, Liu et al. 2023 — reimplemented from the
paper's algorithm, not from any reference code.)
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax import shard_map

from ..ops.pallas import flash_attention as _fa

__all__ = ["ring_attention", "ring_attention_local"]

_NEG_INF = -1e30


def _block_jnp(q, k, v, causal, scale):
    """Fused-XLA block attention → (normalized out, lse)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((T, S), dtype=bool), k=S - T)
        s = jnp.where(cm, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, _NEG_INF)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    out = out.astype(jnp.float32) / jnp.maximum(l, 1e-20)
    lse = (m + jnp.log(jnp.maximum(l, 1e-20)))[..., 0]     # [B,H,t]
    return out, lse


def _block_engine(q, k, v, scale):
    """Pick the per-block attention fn (causal: bool) → (out_f32, lse)."""
    def run(causal):
        res = _fa.try_flash(q, k, v, causal=causal, scale=scale,
                            with_lse=True)
        if res is None:
            return _block_jnp(q, k, v, causal, scale)
        out, lse = res
        return out.astype(jnp.float32), lse
    return run


def ring_attention_local(q, k, v, axis_name="sp", causal=False, scale=None):
    """Per-shard body: q/k/v are the LOCAL sequence blocks [B,H,t,D].

    Must run inside shard_map with `axis_name` bound."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    scale = scale if scale is not None else (q.shape[-1] ** -0.5)
    B, H, t_q = q.shape[0], q.shape[1], q.shape[2]
    DV = v.shape[-1]
    if causal and q.shape[2] != k.shape[2]:
        # the full/diag/skip block classification below assumes the global
        # diagonal lines up with equal shard lengths; unequal q/kv shards
        # under causal would silently mis-mask (cross-attention rings are
        # never causal in practice)
        raise NotImplementedError(
            "causal ring attention requires equal q and k/v shard lengths "
            f"(got {q.shape[2]} vs {k.shape[2]})")

    def step(carry, _):
        out, lse, kk, vv, src = carry
        run = _block_engine(q, kk, vv, scale)
        if causal:
            def full(_):
                return run(False)

            def diag(_):
                return run(True)

            def skip(_):
                return (jnp.zeros((B, H, t_q, DV), jnp.float32),
                        jnp.full((B, H, t_q), _NEG_INF, jnp.float32))

            branch = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
            o2, lse2 = lax.switch(branch, (full, diag, skip), None)
        else:
            o2, lse2 = run(False)
        # online-softmax merge in (out, lse) space
        m = jnp.maximum(lse, lse2)
        a1 = jnp.exp(lse - m)
        a2 = jnp.exp(lse2 - m)
        out = out * a1[..., None] + o2 * a2[..., None]
        denom = a1 + a2
        out = out / jnp.maximum(denom, 1e-20)[..., None]
        lse_new = m + jnp.log(jnp.maximum(denom, 1e-20))
        # re-normalized running out ↔ running lse: keep the invariant that
        # `out` is the softmax-normalized result over all blocks seen so far
        # rotate k/v one hop around the ring (neighbor ICI link)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        src = (src - 1) % n
        return (out, lse_new, kk, vv, src), None

    out0 = jnp.zeros((B, H, t_q, DV), jnp.float32)
    lse0 = jnp.full((B, H, t_q), _NEG_INF, jnp.float32)
    (out, lse, _, _, _), _ = lax.scan(
        step, (out0, lse0, k, v, idx), None, length=n)
    return out.astype(q.dtype)


def ring_attention(mesh, q, k, v, causal=False, scale=None, axis_name="sp"):
    """Global entry: q/k/v [B,H,T,D] sharded (or shardable) on T over sp."""
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
