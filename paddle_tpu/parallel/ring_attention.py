"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

First-class long-context support (SURVEY §2.4): Q/K/V are sharded along
the sequence dim across `sp` devices; K/V blocks rotate around the ring
via ppermute while each device merges its queries' output in
(out, logsumexp) space — the online-softmax invariant. Peak memory per
device is O(T/sp * T/sp) per block instead of O(T^2); comm rides
neighbor ICI links.

The per-block engine is selected by size: the Pallas flash kernel
(ops/pallas/flash_attention.py, via its lse-returning custom_vjp entry)
when the local block is at/above the measured crossover, else the fused
XLA path. Under causal masking, blocks strictly above the diagonal are
skipped entirely via lax.switch (≈2x fewer FLOPs), the diagonal block
runs the causal kernel, and blocks below run the full kernel.

Public entry: ring_attention(mesh, q, k, v, causal=...) — call with
GLOBAL [B, H, T, D] arrays; returns global output. Inside it shard_maps
over sp. (Ring Attention, Liu et al. 2023 — reimplemented from the
paper's algorithm, not from any reference code.)

Causal load balancing — `striped=True` (Striped Attention, Brandon et
al. 2023, same reimplementation caveat): with CONTIGUOUS shards the
causal mask makes device 0 compute 1 real block and device n-1 compute
n, but the ring runs in SPMD lockstep, so every one of the n hops costs
a full block anyway — causal saves FLOPs, not wall time. Striping
assigns token g to device g % n instead: every (device, hop) pair then
sees a triangular block — inclusive diagonal when the incoming stripe
index <= ours, strict (offset -1) otherwise — so all devices do ~half a
block of work each hop, ~2x faster causal rings. The permutation is a
reshape/transpose applied at the global entry (and inverted on the
output), so callers keep contiguous semantics.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax import shard_map

__all__ = ["ring_attention", "ring_attention_local"]

_NEG_INF = -1e30


def _block_jnp(q, k, v, causal, scale, causal_offset=0):
    """Fused-XLA block attention → (normalized out, lse)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((T, S), dtype=bool),
                      k=S - T + causal_offset)
        s = jnp.where(cm, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, _NEG_INF)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    out = out.astype(jnp.float32) / jnp.maximum(l, 1e-20)
    lse = (m + jnp.log(jnp.maximum(l, 1e-20)))[..., 0]     # [B,H,t]
    return out, lse


def _block_engine(q, k, v, scale):
    """Pick the per-block attention fn (causal: bool) → (out_f32, lse).
    Flash is reached through the kern-registry seam (ops.registry.accel)
    so this module loads no Pallas code until a block actually runs."""
    from ..ops.registry import accel
    fused = accel("flash_attention")

    def run(causal, causal_offset=0):
        res = fused(q, k, v, causal=causal, scale=scale, with_lse=True,
                    causal_offset=causal_offset) \
            if fused is not None else None
        if res is None:
            return _block_jnp(q, k, v, causal, scale, causal_offset)
        out, lse = res
        return out.astype(jnp.float32), lse
    return run


def ring_attention_local(q, k, v, axis_name="sp", causal=False, scale=None,
                         striped=False):
    """Per-shard body: q/k/v are the LOCAL sequence blocks [B,H,t,D] —
    contiguous shards, or stripes (token g on device g % n) with
    `striped=True`, which load-balances the causal mask (see module
    docstring).

    Must run inside shard_map with `axis_name` bound."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    scale = scale if scale is not None else (q.shape[-1] ** -0.5)
    B, H, t_q = q.shape[0], q.shape[1], q.shape[2]
    DV = v.shape[-1]
    if causal and q.shape[2] != k.shape[2]:
        # the full/diag/skip block classification below assumes the global
        # diagonal lines up with equal shard lengths; unequal q/kv shards
        # under causal would silently mis-mask (cross-attention rings are
        # never causal in practice)
        raise NotImplementedError(
            "causal ring attention requires equal q and k/v shard lengths "
            f"(got {q.shape[2]} vs {k.shape[2]})")

    def step(carry, _):
        out, lse, kk, vv, src = carry
        run = _block_engine(q, kk, vv, scale)
        if causal and striped:
            # stripe s_q row p_q holds token p_q*n + s_q: vs stripe src,
            # token p_k*n + src is visible iff p_k <= p_q (src <= s_q)
            # or p_k < p_q (src > s_q) — a triangular block either way,
            # so every device works every hop (the load balance)
            def diag_incl(_):
                return run(True, 0)

            def diag_strict(_):
                return run(True, -1)

            branch = jnp.where(src <= idx, 0, 1)
            o2, lse2 = lax.switch(branch, (diag_incl, diag_strict), None)
        elif causal:
            def full(_):
                return run(False)

            def diag(_):
                return run(True)

            def skip(_):
                return (jnp.zeros((B, H, t_q, DV), jnp.float32),
                        jnp.full((B, H, t_q), _NEG_INF, jnp.float32))

            branch = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
            o2, lse2 = lax.switch(branch, (full, diag, skip), None)
        else:
            o2, lse2 = run(False)
        # online-softmax merge in (out, lse) space
        m = jnp.maximum(lse, lse2)
        a1 = jnp.exp(lse - m)
        a2 = jnp.exp(lse2 - m)
        out = out * a1[..., None] + o2 * a2[..., None]
        denom = a1 + a2
        out = out / jnp.maximum(denom, 1e-20)[..., None]
        lse_new = m + jnp.log(jnp.maximum(denom, 1e-20))
        # re-normalized running out ↔ running lse: keep the invariant that
        # `out` is the softmax-normalized result over all blocks seen so far
        # rotate k/v one hop around the ring (neighbor ICI link)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        src = (src - 1) % n
        return (out, lse_new, kk, vv, src), None

    out0 = jnp.zeros((B, H, t_q, DV), jnp.float32)
    lse0 = jnp.full((B, H, t_q), _NEG_INF, jnp.float32)
    (out, lse, _, _, _), _ = lax.scan(
        step, (out0, lse0, k, v, idx), None, length=n)
    return out.astype(q.dtype)


def _stripe(x, n):
    """Permute [B,H,T,D] so contiguous shards of the result are stripes
    of the input: result position s*t + p holds token p*n + s."""
    B, H, T, D = x.shape
    t = T // n
    return x.reshape(B, H, t, n, D).swapaxes(2, 3).reshape(B, H, T, D)


def _unstripe(x, n):
    B, H, T, D = x.shape
    t = T // n
    return x.reshape(B, H, n, t, D).swapaxes(2, 3).reshape(B, H, T, D)


def ring_attention(mesh, q, k, v, causal=False, scale=None, axis_name="sp",
                   striped=False, pre_striped=False):
    """Global entry: q/k/v [B,H,T,D] sharded (or shardable) on T over sp.

    `striped=True` load-balances causal masks (Striped Attention). By
    default the stripe permutation and its inverse are applied HERE so
    the caller keeps contiguous token order — but that is a cross-device
    relayout of q/k/v (and the output) per call, roughly doubling comm
    volume vs the K/V ring itself. Long-context training should stripe
    ONCE at the data boundary and pass `pre_striped=True` (inputs and
    output then live in the striped layout; positional encodings etc.
    must already be applied or also striped)."""
    n = mesh.shape[axis_name]
    do_permute = striped and causal and not pre_striped
    if striped and causal and (q.shape[2] % n or k.shape[2] % n):
        raise ValueError("striped ring attention needs T % sp == 0")
    if do_permute:
        q, k, v = _stripe(q, n), _stripe(k, n), _stripe(v, n)
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale,
                          striped=striped and causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    out = fn(q, k, v)
    if do_permute:
        out = _unstripe(out, n)
    return out
