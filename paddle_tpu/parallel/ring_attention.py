"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

First-class long-context support (SURVEY §2.4): Q/K/V are sharded along
the sequence dim across `sp` devices; K/V blocks rotate around the ring
via ppermute while each device accumulates its queries' output with an
online (flash-style) softmax. Peak memory per device is O(T/sp * T/sp)
per block instead of O(T^2); comm rides neighbor ICI links.

Public entry: ring_attention(mesh, q, k, v, causal=...) — call with
GLOBAL [B, H, T, D] arrays; returns global output. Inside it shard_maps
over sp. (Ring Attention, Liu et al. 2023 — reimplemented from the
paper's algorithm, not from any reference code.)
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = ["ring_attention", "ring_attention_local"]

_NEG_INF = -1e30


def _block_attn(q, k, v, bias=None):
    """Unnormalized block attention: returns (acc, row_sum, row_max)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, _NEG_INF)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    return acc.astype(jnp.float32), l, m


def _merge(acc1, l1, m1, acc2, l2, m2):
    """Online-softmax merge of two partial attention results."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return acc1 * a1 + acc2 * a2, l1 * a1 + l2 * a2, m


def ring_attention_local(q, k, v, axis_name="sp", causal=False, scale=None):
    """Per-shard body: q/k/v are the LOCAL sequence blocks [B,H,t,D].

    Must run inside shard_map with `axis_name` bound."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    scale = scale if scale is not None else (q.shape[-1] ** -0.5)
    qs = (q * scale).astype(q.dtype)
    t_q = q.shape[2]
    t_k = k.shape[2]

    def causal_bias(q_block, k_block):
        # global positions of this device's queries vs the rotating k block
        q_pos = q_block * t_q + jnp.arange(t_q)
        k_pos = k_block * t_k + jnp.arange(t_k)
        allowed = q_pos[:, None] >= k_pos[None, :]
        return jnp.where(allowed, 0.0, _NEG_INF)[None, None]

    def step(carry, _):
        acc, l, m, kk, vv, src = carry
        bias = causal_bias(idx, src) if causal else None
        acc2, l2, m2 = _block_attn(qs, kk, vv, bias)
        acc, l, m = _merge(acc, l, m, acc2, l2, m2)
        # rotate k/v one hop around the ring (neighbor ICI link)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        src = (src - 1) % n
        return (acc, l, m, kk, vv, src), None

    B, H = q.shape[0], q.shape[1]
    acc0 = jnp.zeros((B, H, t_q, v.shape[-1]), jnp.float32)
    l0 = jnp.zeros((B, H, t_q, 1), jnp.float32)
    m0 = jnp.full((B, H, t_q, 1), _NEG_INF, jnp.float32)
    (acc, l, m, _, _, _), _ = lax.scan(
        step, (acc0, l0, m0, k, v, idx), None, length=n)
    out = acc / jnp.maximum(l, 1e-20)
    return out.astype(q.dtype)


def ring_attention(mesh, q, k, v, causal=False, scale=None, axis_name="sp"):
    """Global entry: q/k/v [B,H,T,D] sharded (or shardable) on T over sp."""
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
