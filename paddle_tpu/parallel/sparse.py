"""tpusparse — mesh-sharded embedding tables (the pserver heritage).

Parity: the reference's `operators/distributed/` pserver stack existed
for ONE workload — recommender embedding tables too big for a single
device. `distribute_lookup_table.py` found the distributed table,
DistributeTranspiler row-partitioned it over pservers, trainers
prefetch'd rows over gRPC and pushed sparse updates back. Here the
same program markup (`embedding(is_sparse=True, is_distributed=True)`)
lowers to a TPU-native engine (ROADMAP item 5):

- **placement**: a `ShardedTable` holds `ceil(vocab/N)` rows per mesh
  device, **mod-sharded** (row r lives on device `r % N` at local index
  `r // N`) so power-law-popular low ids spread across the mesh instead
  of hammering shard 0 — the reference's hash-bucketed pserver
  partitioning, not the block split.
- **dedup**: each step, the batch's ids collapse through a
  `jnp.unique`-style static-shape dedup (`unique_static`: padded
  unique-ids buffer + inverse indices + carried count — the
  static-shapes discipline), so the wire and the update both move
  O(unique ids), not O(batch): the EQuARX lesson from the gradsync PR
  applied to gather/update traffic.
- **exchange**: ONE all-to-all each way moves the deduped row requests
  to their owners and the rows back (`operators/distributed/` prefetch
  RPC ≙ `lax.all_to_all` over the dp axis). Request buckets are
  per-owner static buffers (`cap` knob, default = worst case so no id
  is ever dropped; smaller caps trade wire for a counted overflow —
  see `tpusparse.stats.*`).
- **local fused lookup+pool**: the gathered unique rows expand to the
  program's [B, F, D] output through the kern registry's fused lookup
  kernel (`ops.registry.accel("lookup_pool")`) when its capability
  probe accepts, else the identical jnp gather.
- **update**: the backward's is_sparse row-grad taps give per-position
  row gradients; they dedup locally (`dedup_rows`), exchange to their
  owner shards (one all-to-all), merge across members, and apply the
  SAME row-update formulas the sparse_sgd/sparse_adam kernels use
  (ops/kernels_optim.py row helpers) on the owner's shard + moment
  shards — update bandwidth O(unique ids), the SelectedRows push.
- **async/stale** (`stale=k`): the grad exchange+apply for step N runs
  inside step N+k's graph, where it depends only on persisted state —
  XLA overlaps it with the forward pass (the gradsync overlap
  machinery's dependency discipline). Lookups read the pre-apply
  table, mirroring AsyncExecutor's stale-read semantics; `stale=0`
  (default) applies synchronously in the tail, numerics matching the
  dense path.

Selection mirrors gradsync: `ParallelExecutor(sparse="shard")`, the
`PADDLE_TPU_SPARSE` env var, grammar `shard[:stale=K,cap=N]`. Off (the
default) leaves every existing path — plain Executor dense gather,
transpiler SPMD row-sharding — byte-for-byte untouched, and this
module is never even imported (pinned by tests/test_bench_contract).

Telemetry: `embed.<table>.rows` (local rows/shard) and
`embed.<table>.exchange_bytes` (trace-time wire accounting, like
collective.*) plus the runtime `embed.<table>.unique_ratio` gauge read
back from the in-graph `tpusparse.stats.<table>` accumulator —
surfaced per rank in `tpustat --fleet`.
"""
import os
import re

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import telemetry as _tm
from . import collective as C
from ..ops.kernels_optim import dedup_rows, adam_row_update, sgd_row_update

__all__ = ["SparsePolicy", "parse_policy", "resolve_policy",
           "discover_tables", "unique_static", "SparseEngine",
           "ShardedTable", "strip_table_init", "STATS_PREFIX",
           "PEND_PREFIX"]

ENV_VAR = "PADDLE_TPU_SPARSE"
STATS_PREFIX = "tpusparse.stats."
PEND_PREFIX = "tpusparse.pend."

# optimizer accumulator kinds row-shaped accumulators are named with
# (optimizer.py _add_accumulator) — same vocabulary the transpiler's
# table rule matches
_ACCUM_KINDS = ("moment1", "moment2", "moment", "velocity", "inf_norm",
                "mean_square", "mean_grad", "squared", "linear",
                "avg_squared_grad", "avg_squared_update")


class SparsePolicy:
    """One resolved sparse-engine policy. `stale_steps=k` defers each
    step's row updates by k steps (AsyncExecutor semantics — the
    exchange overlaps the next step's forward); `capacity` caps the
    per-owner exchange buckets (None = worst case, exact); `kernel`
    gates the Pallas fused-lookup dispatch (on by default; the probe
    still decides)."""

    def __init__(self, mode="shard", stale_steps=0, capacity=None,
                 kernel=True, axis_name="dp"):
        if mode != "shard":
            raise ValueError(f"sparse mode {mode!r} not in ('shard',)")
        if stale_steps < 0:
            raise ValueError("sparse stale_steps must be >= 0")
        if capacity is not None and capacity < 1:
            raise ValueError("sparse cap must be >= 1")
        self.mode = mode
        self.stale_steps = int(stale_steps)
        self.capacity = None if capacity is None else int(capacity)
        self.kernel = bool(kernel)
        self.axis_name = axis_name

    def key(self):
        return ("tpusparse", self.mode, self.stale_steps, self.capacity,
                self.kernel, self.axis_name)

    def __repr__(self):
        return (f"SparsePolicy(mode={self.mode!r}, "
                f"stale_steps={self.stale_steps}, "
                f"capacity={self.capacity}, kernel={self.kernel})")


def parse_policy(spec):
    """`spec` → SparsePolicy or None for off. Grammar (the gradsync
    grammar): `shard[:stale=K,cap=N,kernel=0/1]`; "on"/"1" ≙ "shard"."""
    if spec is None or isinstance(spec, SparsePolicy):
        return spec
    s = str(spec).strip().lower()
    if s in ("", "0", "off", "none", "false"):
        return None
    if s in ("1", "on", "true"):
        s = "shard"
    mode, _, opts = s.partition(":")
    kw = {}
    for item in filter(None, (t.strip() for t in opts.split(","))):
        k, eq, v = item.partition("=")
        if not eq:
            raise ValueError(f"sparse option {item!r} is not k=v")
        if k in ("stale", "stale_steps"):
            kw["stale_steps"] = int(v)
        elif k in ("cap", "capacity"):
            kw["capacity"] = int(v)
        elif k == "kernel":
            kw["kernel"] = v not in ("0", "false", "off")
        else:
            raise ValueError(f"unknown sparse option {k!r}")
    return SparsePolicy(mode=mode, **kw)


def resolve_policy(arg=None):
    """Explicit arg (including "off") beats PADDLE_TPU_SPARSE."""
    if arg is not None:
        return parse_policy(arg)
    env = os.environ.get(ENV_VAR)
    if env is not None and env.strip():
        return parse_policy(env)
    return None


def discover_tables(program):
    """All distributed lookup tables in `program`, sorted.

    Generalizes distribute_lookup_table.find_distributed_lookup_table
    (which enforces the reference's at-most-ONE-table rule for the
    transpiler) to several tables — DeepFM carries two ([V, 1] first
    order + [V, D] factors). The per-table consistency check is the
    same: every lookup on a distributed table must be distributed."""
    ops = [op for op in program.global_block().ops
           if op.type == "lookup_table"]
    dist = {op.inputs["W"][0] for op in ops
            if op.attrs.get("is_distributed")}
    for op in ops:
        if op.inputs["W"][0] in dist and \
                not op.attrs.get("is_distributed"):
            raise RuntimeError(
                "lookup_table_ops on the same table must all be "
                "distributed")
    return sorted(dist)


# ------------------------------------------------------- static dedup

def unique_static(flat_ids):
    """`jnp.unique`-style dedup with static shapes: flat_ids [M] int32
    (all >= 0) → (uids [M], inv [M], count) where uids[:count] are the
    distinct ids ascending, uids[count:] == -1 (the carried-count
    padding), and flat_ids[i] == uids[inv[i]]."""
    flat = flat_ids.reshape(-1).astype(jnp.int32)
    m = flat.shape[0]
    order = jnp.argsort(flat)
    sid = jnp.take(flat, order)
    first = jnp.concatenate([jnp.ones((1,), jnp.int32),
                             (sid[1:] != sid[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(first) - 1                 # unique slot per sorted pos
    uids = jnp.full((m,), -1, jnp.int32).at[seg].set(sid)
    inv = jnp.zeros((m,), jnp.int32).at[order].set(seg)
    return uids, inv, seg[-1] + 1


def _owner_buckets(uids, n, cap):
    """Route the deduped ids to their mod-sharding owners: uids [M]
    (-1 padded) → (req [n, cap] int32 (-1 padded), owner [M], pos [M],
    overflow). Entry i goes to bucket (owner[i] = uids[i] % n) at slot
    pos[i] (its rank within the bucket); entries past `cap` are counted
    in `overflow` and dropped (cap = M never overflows)."""
    valid = uids >= 0
    owner = jnp.where(valid, uids % n, n)
    onehot = (owner[:, None] == jnp.arange(n + 1)[None, :]).astype(
        jnp.int32)
    pos = jnp.take_along_axis(jnp.cumsum(onehot, axis=0) - 1,
                              owner[:, None], axis=1)[:, 0]
    overflow = jnp.sum(((pos >= cap) & valid).astype(jnp.int32))
    req = jnp.full((n, cap), -1, jnp.int32)
    # out-of-range (owner == n padding, pos >= cap overflow) drop
    req = req.at[owner, pos].set(jnp.where(valid, uids, -1), mode="drop")
    return req, owner, pos, overflow


class _Axis:
    """The engine's collective surface over one mesh axis. `fake=True`
    is the shape-probe mode (ParallelExecutor's axis-free eval_shape):
    every collective becomes a shape-preserving identity."""

    def __init__(self, name, size, fake=False):
        self.name = name
        self.size = int(size)
        self.fake = fake

    def all_to_all(self, x):
        if self.fake:
            return x
        return C.all_to_all(x, axis_name=self.name, split_axis=0,
                            concat_axis=0)

    def psum(self, x):
        return x if self.fake else lax.psum(x, self.name)


class ShardedTable:
    """Static description + per-run plan of ONE mod-sharded table."""

    def __init__(self, name, vocab, dim, dtype, n):
        self.name = name
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.dtype = dtype
        self.n = int(n)
        self.local_rows = -(-self.vocab // self.n)
        self.moments = {}          # accumulator kind suffix -> var name
        self.m_ids = None          # flattened id count per member (plan)
        self.cap = None            # per-owner exchange capacity (plan)

    @property
    def physical_shape(self):
        return (self.n * self.local_rows, self.dim)

    @property
    def stats_name(self):
        return STATS_PREFIX + self.name

    def pend_names(self):
        return (PEND_PREFIX + self.name + ".ids",
                PEND_PREFIX + self.name + ".g")


def strip_table_init(startup_program, names):
    """Remove `names`' initializer ops from a startup program so huge
    sharded tables are never materialized host-side — pair with
    SparseEngine.init_shards, which seeds the scope shard-wise."""
    names = set(names)
    blk = startup_program.global_block()
    blk.ops[:] = [op for op in blk.ops
                  if not (set(op.output_names()) & names)]
    for n in names:
        blk.vars.pop(n, None)       # no init op -> not a startup output
    return startup_program


class SparseEngine:
    """The trace-time sparse engine ParallelExecutor attaches when a
    program carries distributed lookup tables and a SparsePolicy is
    active. One instance per executor; `exec()` handles the owned
    lookup_table / sparse_sgd / sparse_adam ops inside the traced step
    (which MUST run under shard_map with the dp axis bound — the
    probe_clone() variant runs axis-free for shape inference)."""

    def __init__(self, program, policy, mesh, reduce="mean",
                 table_names=None, _fake_axis=False):
        self.program = program
        self.policy = policy
        self.mesh = mesh
        self.reduce = reduce
        axis = policy.axis_name
        if axis not in mesh.shape:
            raise ValueError(
                f"sparse engine needs a {axis!r} axis on the mesh")
        self.n = int(mesh.shape[axis])
        self.axis = _Axis(axis, self.n, fake=_fake_axis)
        names = table_names if table_names is not None \
            else discover_tables(program)
        if not names:
            raise ValueError(
                "sparse engine: program has no distributed lookup "
                "table (embedding(..., is_distributed=True))")
        block = program.global_block()
        self.tables = {}
        for name in names:
            var = block.vars.get(name)
            if var is None or len(var.shape or ()) < 2:
                raise ValueError(
                    f"sparse engine: table {name!r} not a [vocab, dim] "
                    "var in the program")
            self.tables[name] = ShardedTable(
                name, var.shape[0], var.shape[-1],
                str(var.dtype or "float32"), self.n)
        # the engine's update path rides the is_sparse row-grad taps —
        # a dense-gradient distributed table would densify [V, D] on
        # every member, the exact thing sharding exists to avoid
        for op in block.ops:
            if op.type == "lookup_table" and \
                    op.inputs["W"][0] in self.tables and \
                    not op.attrs.get("is_sparse"):
                raise ValueError(
                    f"sparse engine: distributed table "
                    f"{op.inputs['W'][0]!r} must use "
                    "embedding(is_sparse=True) — the engine applies "
                    "row-sparse updates through the SparseDelta taps")
            if op.attrs.get("is_optimizer_op") and \
                    op.inputs.get("Param") and \
                    op.inputs["Param"][0] in self.tables and \
                    op.type not in ("sparse_sgd", "sparse_adam"):
                raise NotImplementedError(
                    f"sparse engine: {op.type!r} update on sharded "
                    f"table {op.inputs['Param'][0]!r}; use Adam or SGD")
        # row-shaped accumulators (lazy-Adam moments) shard with their
        # table — matched EXACTLY like the transpiler's table rule
        accum_re = {
            t: re.compile(re.escape(t) + "_(" + "|".join(_ACCUM_KINDS)
                          + r")_\d+$")
            for t in self.tables}
        for v in program.persistable_vars():
            for t, rx in accum_re.items():
                spec = self.tables[t]
                if rx.fullmatch(v.name) and \
                        tuple(v.shape) == (spec.vocab, spec.dim):
                    spec.moments[v.name] = v.name
        self._row_sharding = None   # set in prepare_persist
        self._physical = set()      # names known to hold mod-layout arrays

    # ------------------------------------------------------ identity
    def key(self):
        """Compile-cache identity (joins the executor ckey only when
        the engine is active)."""
        plan = tuple(sorted((t.name, t.m_ids, t.cap)
                            for t in self.tables.values()))
        return self.policy.key() + (self.reduce, plan)

    @property
    def row_var_names(self):
        """Every persistable the engine stores mod-sharded (tables +
        their row-shaped accumulators)."""
        out = []
        for t in self.tables.values():
            out.append(t.name)
            out.extend(t.moments)
        return out

    def owner_table(self, name):
        """The ShardedTable a row var (table or row-shaped accumulator)
        belongs to, or None for names the engine does not manage."""
        for t in self.tables.values():
            if name == t.name or name in t.moments:
                return t
        return None

    def probe_clone(self):
        """Axis-free twin for jax.eval_shape (collectives → identity)."""
        eng = SparseEngine(self.program, self.policy, self.mesh,
                          reduce=self.reduce,
                          table_names=list(self.tables), _fake_axis=True)
        for name, t in self.tables.items():
            eng.tables[name].m_ids = t.m_ids
            eng.tables[name].cap = t.cap
        return eng

    # ------------------------------------------------------ placement
    def _phys_perm(self, t):
        """Physical row p = d * L + l holds logical row l * n + d."""
        d, l = np.divmod(np.arange(t.n * t.local_rows), t.local_rows)
        return l * t.n + d                     # physical -> logical id

    def to_physical(self, t, logical):
        """Logical [V, D] host array → mod-permuted [n*L, D] np array
        (pad rows zero)."""
        logical = np.asarray(logical)
        ids = self._phys_perm(t)
        out = np.zeros(t.physical_shape, logical.dtype)
        ok = ids < t.vocab
        out[ok] = logical[ids[ok]]
        return out

    def to_logical(self, t, physical):
        """Inverse of to_physical (tests / checkpoint export)."""
        if isinstance(t, str):
            t = self.tables[t]
        physical = np.asarray(physical)
        r = np.arange(t.vocab)
        return physical[(r % t.n) * t.local_rows + r // t.n]

    def prepare_persist(self, persist, persist_sh, scope):
        """Place every engine-managed row var: host logical arrays are
        permuted to the mod layout and sharded P(dp); arrays that are
        ALREADY physical (a previous step's donated output, or
        init_shards' shard-wise build) pass through untouched."""
        sh = NamedSharding(self.mesh, P(self.policy.axis_name, None))
        self._row_sharding = sh
        for t in self.tables.values():
            for name in [t.name] + list(t.moments):
                val = scope.get(name)
                if val is None:
                    raise RuntimeError(
                        f"sharded table var {name!r} not initialized; "
                        "run the startup program, or for tables too "
                        "big to materialize use "
                        "sparse.strip_table_init + engine.init_shards")
                # an array is physical iff THIS engine produced it (a
                # prior step's sharded output, or init_shards) — the
                # mod permutation is invisible in shape/dtype, so a
                # sharding check alone could double-permute (jit
                # outputs normalize P("dp", None) to P("dp",))
                physical = (name in self._physical
                            and isinstance(val, jax.Array)
                            and tuple(val.shape) == t.physical_shape)
                if not physical:
                    phys = self.to_physical(t, val)
                    val = jax.make_array_from_callback(
                        t.physical_shape, sh,
                        lambda idx, _p=phys: _p[idx])
                    self._physical.add(name)
                persist_sh[name] = sh
                persist[name] = val
            if _tm.enabled():
                _tm.gauge(f"embed.{t.name}.rows").set(t.local_rows)

    def install_shards(self, scope, name, make_rows):
        """Install ONE engine row var shard-WISE: `make_rows(d)` returns
        the [local_rows, dim] host rows for mesh member d in the mod
        layout (local row l of member d holds logical id l*n + d — the
        _phys_perm bijection). No host copy of the full [V, D] ever
        exists — each device's callback materializes 1/N. The array is
        marked physical so prepare_persist passes it through untouched.
        Both init_shards (seeding) and the elastic r%N → r%M checkpoint
        restore (resilience/elastic.py) enter here."""
        t = self.owner_table(name)
        if t is None:
            raise KeyError(
                f"install_shards: {name!r} is not an engine row var")
        sh = NamedSharding(self.mesh, P(self.policy.axis_name, None))
        L = t.local_rows

        def cb(idx, _L=L, _t=t):
            rows = np.asarray(make_rows(idx[0].start // _L))
            if rows.shape != (_L, _t.dim):
                raise ValueError(
                    f"install_shards({name!r}): shard builder returned "
                    f"{rows.shape}, want {(_L, _t.dim)}")
            return rows

        arr = jax.make_array_from_callback(t.physical_shape, sh, cb)
        scope.set(name, arr)
        self._physical.add(name)
        if _tm.memledger_enabled():
            # creation site of a table shard: attribute the physical
            # [N*local_rows, dim] array so OOM post-mortems name the
            # table, not an anonymous buffer
            from ..telemetry import memledger as _ml
            _ml.register("sparse_table", name, arr,
                         rows=t.local_rows, dim=t.dim)

    def init_shards(self, scope, seed=0, scale=0.02):
        """Seed every engine table shard-WISE (no host copy of the full
        [V, D] ever exists): normal(0, scale) rows per shard, zero
        moments. The giant-vocab entry path — pair with
        strip_table_init on the startup program."""
        for t in self.tables.values():

            def mk(d, _t=t):
                rng = np.random.RandomState(
                    (seed * 131071 + hash(_t.name) % 65521 + d)
                    % (2 ** 31 - 1))
                rows = rng.standard_normal((_t.local_rows, _t.dim)).astype(
                    np.dtype(_t.dtype)) * scale
                # pad rows (logical id >= vocab) zero
                lg = np.arange(_t.local_rows) * _t.n + d
                rows[lg >= _t.vocab] = 0
                return rows

            self.install_shards(scope, t.name, mk)
            for m in t.moments:
                self.install_shards(
                    scope, m, lambda d, _t=t: np.zeros(
                        (_t.local_rows, _t.dim), np.dtype(_t.dtype)))

    def export_shards(self, scope):
        """Host snapshots of every engine row var currently in the
        PHYSICAL mod layout, one np array per mesh member — the
        topology-independent checkpoint writer's entry (io.py). Returns
        (layout, files): `layout[name]` is the manifest record (kind,
        world, vocab, dim, local_rows, dtype, per-shard file names) and
        `files[filename]` the shard rows (an explicit host COPY, 1/N of
        the table each — on multi-host every process snapshots only its
        addressable shards, never the gathered [V, D]). Row vars whose
        scope value is not a physical engine array (e.g. a logical host
        array before the first step) are omitted — the caller saves
        those logically like any dense persistable."""
        layout, files = {}, {}
        for t in self.tables.values():
            for name in [t.name] + list(t.moments):
                val = scope.get(name)
                if not (name in self._physical
                        and isinstance(val, jax.Array)
                        and tuple(val.shape) == t.physical_shape):
                    continue
                safe = name.replace("/", "__")
                rec = {"kind": "mod_shard", "world": t.n,
                       "vocab": t.vocab, "dim": t.dim,
                       "local_rows": t.local_rows,
                       "dtype": str(val.dtype), "files": {}}
                seen = set()
                for shard in val.addressable_shards:
                    d = (shard.index[0].start or 0) // t.local_rows
                    if d in seen:
                        continue          # replicated copy of a shard
                    seen.add(d)
                    fn = f"{safe}.shard{d}of{t.n}.npy"
                    rec["files"][str(d)] = fn
                    files[fn] = np.array(shard.data, copy=True)
                layout[name] = rec
        return layout, files

    # ------------------------------------------------------ run plan
    def plan_run(self, feed_local_shapes):
        """Compute per-table static sizes for THIS feed signature:
        m_ids (flattened ids per member per step across the table's
        lookups) and the per-owner exchange capacity. Needs every
        lookup's Ids to be a feed (stale>0 additionally persists
        m_ids-shaped ring buffers)."""
        block = self.program.global_block()
        for t in self.tables.values():
            m = 0
            for op in block.ops:
                if op.type != "lookup_table" or \
                        op.inputs["W"][0] != t.name:
                    continue
                ids_name = op.inputs["Ids"][0]
                shape = feed_local_shapes.get(ids_name)
                if shape is None:
                    raise ValueError(
                        f"sparse engine: lookup ids {ids_name!r} for "
                        f"table {t.name!r} is not a feed; feed the ids "
                        "directly (derived-id programs are not "
                        "supported by the sharded engine)")
                shape = tuple(shape)
                if shape and shape[-1] == 1:
                    shape = shape[:-1]
                cnt = 1
                for s in shape:
                    cnt *= int(s)
                m += cnt
            t.m_ids = m
            t.cap = min(self.policy.capacity or m, m)

    def state_entries(self):
        """[(name, global_shape, dtype, partition_spec, fill)] of the
        engine's non-program persistables: the replicated stats
        accumulator per table, plus the dp-sharded pending-update ring
        (ids filled with -1 = empty) when stale_steps > 0."""
        out = []
        k = self.policy.stale_steps
        ax = self.policy.axis_name
        for t in self.tables.values():
            out.append((t.stats_name, (4,), np.float32, P(), 0.0))
            if k > 0:
                pid, pg = t.pend_names()
                out.append((pid, (self.n, k, t.m_ids), np.int32,
                            P(ax, None, None), -1))
                out.append((pg, (self.n, k, t.m_ids, t.dim), np.float32,
                            P(ax, None, None, None), 0.0))
        return out

    def out_spec(self, name):
        """Partition spec of one engine output in the explicit
        shard_map path (ParallelExecutor out_specs)."""
        if name.startswith(STATS_PREFIX):
            return P()
        ax = self.policy.axis_name
        if name.startswith(PEND_PREFIX):
            return P(ax, None, None) if name.endswith(".ids") \
                else P(ax, None, None, None)
        return P(ax, None)          # tables + row accumulators

    @property
    def state_names(self):
        return [e[0] for e in self.state_entries()]

    # ------------------------------------------------------ trace ops
    def owns(self, op):
        if op.type == "lookup_table":
            return op.inputs["W"][0] in self.tables
        if op.type in ("sparse_sgd", "sparse_adam"):
            return op.inputs["Param"][0] in self.tables
        return False

    def exec(self, env, op):
        if op.type == "lookup_table":
            self._exec_lookup(env, op)
        else:
            self._exec_update(env, op)

    def _bump_stats(self, env, t, n_ids, n_unique, overflow):
        prev = env.get(t.stats_name)
        if prev is None:
            prev = jnp.zeros((4,), jnp.float32)
        upd = jnp.stack([jnp.asarray(n_ids, jnp.float32),
                         n_unique.astype(jnp.float32),
                         overflow.astype(jnp.float32),
                         jnp.asarray(1.0, jnp.float32)])
        env[t.stats_name] = prev + lax.stop_gradient(
            self.axis.psum(upd) / self.n)

    def _exchange_rows(self, t, shard, uids):
        """One all-to-all round trip: deduped uids [M] (-1 padded) →
        their rows [M, D] fetched from the owner shards."""
        n, cap, D = self.n, t.cap, t.dim
        req, owner, pos, overflow = _owner_buckets(uids, n, cap)
        recv = self.axis.all_to_all(req)              # ids wanted from me
        valid_r = recv >= 0
        lidx = jnp.clip(jnp.where(valid_r, recv // n, 0), 0,
                        t.local_rows - 1)
        rows = jnp.take(shard, lidx, axis=0) \
            * valid_r[..., None].astype(shard.dtype)
        got = self.axis.all_to_all(rows)              # [n, cap, D]
        ok = (uids >= 0) & (pos < cap)
        u_rows = got[jnp.clip(owner, 0, n - 1),
                     jnp.clip(pos, 0, cap - 1)] \
            * ok[:, None].astype(shard.dtype)
        if _tm.enabled():
            _tm.counter(f"embed.{t.name}.exchange_bytes").inc(
                req.size * 4 + rows.size
                * np.dtype(shard.dtype).itemsize)
        return u_rows, overflow

    def _exec_lookup(self, env, op):
        """The lowered distributed lookup: dedup → one all-to-all row
        exchange → fused local lookup (+ the is_sparse delta tap and
        padding mask, in the dense kernel's exact order)."""
        t = self.tables[op.inputs["W"][0]]
        shard = env[t.name]                           # [L, D] local
        ids = env[op.inputs["Ids"][0]].astype(jnp.int32)
        if ids.ndim >= 1 and ids.shape[-1] == 1:
            ids = jnp.squeeze(ids, -1)
        clipped = jnp.clip(ids, 0, t.vocab - 1)
        uids, inv, count = unique_static(clipped.reshape(-1))
        u_rows, overflow = self._exchange_rows(t, shard, uids)
        out = None
        if self.policy.kernel:
            from ..ops.registry import accel
            fused = accel("lookup_pool")
            if fused is not None:
                out = fused(u_rows, inv[:, None], None, "sum")
        if out is None:
            out = jnp.take(u_rows, inv, axis=0)
        out = out.reshape(ids.shape + (t.dim,))
        if op.inputs.get("SparseDelta"):
            out = out + env[op.inputs["SparseDelta"][0]]
        padding_idx = op.attrs.get("padding_idx", -1)
        if padding_idx is not None and padding_idx >= 0:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros_like(out), out)
        env[op.outputs["Out"][0]] = out
        self._bump_stats(env, t, inv.shape[0], count, overflow)

    def _exec_update(self, env, op):
        """The sparse tail op on a sharded table. Sync (stale=0):
        dedup → exchange → apply now. Stale (k>0): apply the k-steps-old
        ring head (depends only on persisted state, so XLA overlaps the
        exchange with this step's forward), push the current deduped
        grads onto the ring."""
        t = self.tables[op.inputs["Param"][0]]
        ids = jnp.concatenate(
            [jnp.clip(i.astype(jnp.int32), 0, t.vocab - 1).reshape(-1)
             for i in env_list(env, op.inputs["Ids"])])
        grads = jnp.concatenate(
            [g.reshape(-1, t.dim).astype(jnp.float32)
             for g in env_list(env, op.inputs["Grad"])])
        if self.reduce == "mean":
            # member grads differentiate the member-MEAN loss; the
            # global mean's row grad is 1/n of each contribution
            grads = grads / self.n
        uids, gsum = dedup_rows(ids, grads, t.vocab)
        uids = jnp.where(uids < t.vocab, uids, -1)    # carried-count pad
        k = self.policy.stale_steps
        if k == 0:
            self._exchange_apply(env, op, t, uids, gsum)
            return
        pid, pg = t.pend_names()
        pend_i, pend_g = env[pid], env[pg]            # [1, k, M(,D)] local
        self._exchange_apply(env, op, t, pend_i[0, 0], pend_g[0, 0])
        env[pid] = jnp.concatenate(
            [pend_i[:, 1:], uids[None, None]], axis=1)
        env[pg] = jnp.concatenate(
            [pend_g[:, 1:], gsum[None, None].astype(jnp.float32)],
            axis=1)

    def _exchange_apply(self, env, op, t, uids, gsum):
        """Scatter-back: route deduped row grads to their owners (one
        all-to-all pair), merge duplicates ACROSS members, and apply
        the shared sparse_sgd/sparse_adam row formulas on the local
        shard. Writes the op's outputs into env."""
        n, cap, D, L = self.n, t.cap, t.dim, t.local_rows
        req, owner, pos, overflow = _owner_buckets(uids, n, cap)
        gbuf = jnp.zeros((n, cap, D), jnp.float32).at[owner, pos].set(
            gsum.astype(jnp.float32), mode="drop")
        rid = self.axis.all_to_all(req).reshape(-1)
        rg = self.axis.all_to_all(gbuf).reshape(-1, D)
        if _tm.enabled():
            _tm.counter(f"embed.{t.name}.exchange_bytes").inc(
                req.size * 4 + gbuf.size * 4)
        # merge the same row's grads from several members (SelectedRows
        # MergeAdd across trainers); invalid entries sort to sentinel L
        key_ids = jnp.where(rid >= 0, rid // n, L)
        order = jnp.argsort(key_ids)
        sid = jnp.take(key_ids, order)
        sg = jnp.take(rg, order, axis=0)
        first = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             (sid[1:] != sid[:-1]).astype(jnp.int32)])
        seg = jnp.cumsum(first)
        merged = jax.ops.segment_sum(sg, seg, num_segments=sid.shape[0])
        ulidx = jnp.full((sid.shape[0],), L, jnp.int32).at[seg].set(sid)
        valid = ulidx < L
        safe = jnp.where(valid, ulidx, 0)
        shard = env[t.name]
        p_rows = jnp.take(shard, safe, axis=0)
        kw = dict(mode="drop", indices_are_sorted=True)
        scatter_idx = jnp.where(valid, ulidx, L)
        a = op.attrs
        if op.type == "sparse_sgd":
            lr = env[op.inputs["LearningRate"][0]].astype(
                jnp.float32).reshape(())
            new_rows = sgd_row_update(p_rows, merged, lr)
            env[op.outputs["ParamOut"][0]] = shard.at[scatter_idx].set(
                new_rows.astype(shard.dtype), **kw)
        else:                                         # sparse_adam
            lr = env[op.inputs["LearningRate"][0]].astype(
                jnp.float32).reshape(())
            m = env[op.inputs["Moment1"][0]]
            v = env[op.inputs["Moment2"][0]]
            b1p = env[op.inputs["Beta1Pow"][0]]
            b2p = env[op.inputs["Beta2Pow"][0]]
            b1 = a.get("beta1", 0.9)
            b2 = a.get("beta2", 0.999)
            eps = a.get("epsilon", 1e-8)
            b1p_new, b2p_new = b1p * b1, b2p * b2
            p_new, m_new, v_new = adam_row_update(
                p_rows, jnp.take(m, safe, axis=0),
                jnp.take(v, safe, axis=0), merged, lr, b1, b2, eps,
                b1p_new, b2p_new)
            env[op.outputs["ParamOut"][0]] = shard.at[scatter_idx].set(
                p_new.astype(shard.dtype), **kw)
            env[op.outputs["Moment1Out"][0]] = m.at[scatter_idx].set(
                m_new.astype(m.dtype), **kw)
            env[op.outputs["Moment2Out"][0]] = v.at[scatter_idx].set(
                v_new.astype(v.dtype), **kw)
            env[op.outputs["Beta1PowOut"][0]] = b1p_new
            env[op.outputs["Beta2PowOut"][0]] = b2p_new
        t_stats = env.get(t.stats_name)
        if t_stats is not None:
            env[t.stats_name] = t_stats + lax.stop_gradient(
                self.axis.psum(
                    jnp.array([0.0, 0.0, 1.0, 0.0], jnp.float32)
                    * overflow.astype(jnp.float32)) / self.n)

    def collect(self, env):
        """Engine persistables currently in env → extra_persist."""
        return {n: env[n] for n in self.state_names if n in env}

    # ------------------------------------------------------ telemetry
    def update_gauges(self, scope):
        """Post-step host-side gauges from the in-graph stats var
        (only called when telemetry is on — costs one small readback)."""
        for t in self.tables.values():
            val = scope.get(t.stats_name)
            if val is None:
                continue
            ids_total, uniq, overflow, _steps = (
                float(x) for x in np.asarray(val))
            if ids_total > 0:
                _tm.gauge(f"embed.{t.name}.unique_ratio").set(
                    uniq / ids_total)
            if overflow:
                _tm.gauge(f"embed.{t.name}.overflow").set(overflow)


def env_list(env, names):
    return [env[n] for n in names]
