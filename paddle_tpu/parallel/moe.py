"""Expert-parallel Mixture-of-Experts FFN (`ep` mesh axis).

Switch-Transformer-style top-1 routing with a STATIC per-expert capacity
(TPU-friendly: no data-dependent shapes — overflow tokens are dropped,
like the reference switch implementations). Dispatch/combine are einsums
against one-hot capacity matrices, and expert weights/buffers carry
`with_sharding_constraint(P("ep", ...))` so XLA inserts the expert
all-to-alls over ICI — the "annotate shardings, let the compiler place
collectives" recipe, not hand-written NCCL (the reference era's
distributed FFN would be pserver sharding, paddle/fluid/operators/
distributed/).

The `ep` axis completes the mesh story: dp (batch) x tp (Megatron) x
sp (ring/Ulysses sequence) x pp (GPipe) x ep (experts) — all dryrun-
compiled by __graft_entry__.dryrun_multichip.

Beyond-reference capability: v1.2-era Paddle has no MoE; this exists so
the sharding design covers expert parallelism from the start (the task's
dryrun contract names ep explicitly).
"""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["moe_ffn", "init_moe_params", "switch_load_balance_loss"]


def init_moe_params(key, d_model, d_hidden, num_experts, dtype=jnp.float32):
    """(gate [D,E], w1 [E,D,H], b1 [E,H], w2 [E,H,D], b2 [E,D])."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = (2.0 / d_model) ** 0.5
    s2 = (2.0 / d_hidden) ** 0.5
    return {
        "gate": jax.random.normal(k1, (d_model, num_experts), dtype) * s1,
        "w1": jax.random.normal(k2, (num_experts, d_model, d_hidden),
                                dtype) * s1,
        "b1": jnp.zeros((num_experts, d_hidden), dtype),
        "w2": jax.random.normal(k3, (num_experts, d_hidden, d_model),
                                dtype) * s2,
        "b2": jnp.zeros((num_experts, d_model), dtype),
    }


def switch_load_balance_loss(gate_probs, expert_one_hot):
    """Switch aux loss: E * Σ_e (fraction routed to e) * (mean prob of e).

    Minimized (=1) at a uniform expert load; add `alpha *` this to the
    task loss when training a router."""
    E = gate_probs.shape[-1]
    f = jnp.mean(expert_one_hot, axis=0)       # fraction of tokens per e
    p = jnp.mean(gate_probs, axis=0)           # mean router prob per e
    return E * jnp.sum(f * p)


def moe_ffn(x, params, capacity_factor=1.25, mesh=None, axis_name="ep",
            activation=jax.nn.relu):
    """Top-1 MoE FFN. x: [B, T, D] (or [N, D]) → same shape, plus the
    load-balance aux loss.

    With `mesh` given, expert-indexed tensors are sharding-constrained to
    P(axis_name, ...) so each ep member owns E/ep experts and XLA routes
    token blocks between them. Fully differentiable (router gradients via
    the combine weights, straight-through-free)."""
    orig_shape = x.shape
    D = x.shape[-1]
    xt = x.reshape(-1, D)                       # [N, D]
    N = xt.shape[0]
    E = params["gate"].shape[-1]
    C = max(1, int(N / E * capacity_factor))

    logits = xt.astype(jnp.float32) @ params["gate"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)     # [N, E]
    expert = jnp.argmax(probs, axis=-1)         # [N]
    one_hot = jax.nn.one_hot(expert, E, dtype=jnp.float32)      # [N, E]
    gate_val = jnp.sum(probs * one_hot, axis=-1)                # [N]

    # position of each token within its expert's queue; beyond-capacity
    # tokens are dropped (static shapes — the switch formulation)
    pos = jnp.cumsum(one_hot, axis=0) * one_hot                 # [N, E]
    keep = (pos <= C).astype(jnp.float32) * one_hot
    pos_idx = jnp.clip(pos - 1.0, 0, C - 1).astype(jnp.int32)
    cap_oh = jax.nn.one_hot(pos_idx, C, dtype=jnp.float32)      # [N, E, C]
    dispatch = cap_oh * keep[..., None]                         # [N, E, C]
    combine = dispatch * gate_val[:, None, None]                # [N, E, C]

    def ep_constrain(t, spec):
        if mesh is not None and axis_name in mesh.shape \
                and mesh.shape[axis_name] > 1:
            return jax.lax.with_sharding_constraint(
                t, jax.sharding.NamedSharding(mesh, spec))
        return t

    # [E, C, D] token buffers, experts sharded over ep → XLA inserts the
    # dispatch all-to-all here
    exp_in = jnp.einsum("nec,nd->ecd", dispatch.astype(xt.dtype), xt)
    exp_in = ep_constrain(exp_in, P(axis_name, None, None))
    w1 = ep_constrain(params["w1"], P(axis_name, None, None))
    b1 = ep_constrain(params["b1"], P(axis_name, None))
    w2 = ep_constrain(params["w2"], P(axis_name, None, None))
    b2 = ep_constrain(params["b2"], P(axis_name, None))
    h = activation(jnp.einsum("ecd,edh->ech", exp_in, w1) + b1[:, None, :])
    exp_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    exp_out = ep_constrain(exp_out, P(axis_name, None, None))
    # combine all-to-all back to token order
    out = jnp.einsum("ecd,nec->nd", exp_out, combine.astype(exp_out.dtype))
    aux = switch_load_balance_loss(probs, one_hot)
    return out.reshape(orig_shape).astype(x.dtype), aux
