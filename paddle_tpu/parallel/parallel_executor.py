"""ParallelExecutor — distributed training over the local mesh.

Parity: python/paddle/fluid/parallel_executor.py. The reference builds a
multi-GPU SSA graph with NCCL all-reduce nodes per gradient; here the
SAME traced step function is jitted with sharded inputs over the mesh —
XLA keeps global-batch semantics (loss/grads identical to single device)
and inserts the collectives over ICI itself.

Beyond plain dp, a DistributeTranspiler (parallel/transpiler.py — the
distribute_transpiler.py analog) can be attached: its sharding table is
applied to params AND optimizer state, giving Megatron tensor parallel
(tp axis) and ZeRO-style optimizer-state sharding (mode="zero", the
pserver analog) THROUGH this executor — the scope then holds genuinely
sharded jax.Arrays between steps.

Multi-host (after fleet.init → jax.distributed.initialize): the mesh
spans every process's devices; each host feeds its LOCAL batch (the
reference's per-trainer readers) and the feeds are assembled into
global arrays (host_local_array_to_global_array), so the global batch
is the concatenation over hosts on the dp axis; params materialize
shard-wise from each host's identically-seeded full copy. Tested by
tests/test_multihost.py::test_two_process_data_parallel_training
(2-process dp == single-process global-batch numerics).
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.framework import default_main_program
from ..core.scope import global_scope
from ..core.trace import build_step_fn
from ..core.dtypes import as_jnp_dtype
from .. import telemetry as _tm
from .mesh import local_mesh

from ..core.compiler import BuildStrategy, ExecutionStrategy  # noqa: F401

__all__ = ["ParallelExecutor", "BuildStrategy", "ExecutionStrategy"]


class ParallelExecutor:
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None, mesh=None, use_tpu=None, transpiler=None):
        self.program = main_program or default_main_program()
        self.loss_name = loss_name
        self.scope = scope or global_scope()
        self.transpiler = transpiler
        if transpiler is not None:
            if transpiler.mesh is None:
                transpiler.transpile(program=self.program)
            self.mesh = transpiler.mesh
            self._shardings = transpiler.shardings()
        else:
            self.mesh = mesh if mesh is not None else local_mesh("dp")
            self._shardings = {}
        self._cache = {}
        self._step = 0
        self._replicated = NamedSharding(self.mesh, P())

    @property
    def device_count(self):
        return int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names]))

    def _feed_sharding(self, arr, name=None):
        """Sharding for one HOST-LOCAL feed array (multi-process: the
        global batch is nproc local batches, which is what dp must
        divide)."""
        if arr.ndim == 0 or "dp" not in self.mesh.shape:
            return self._replicated
        if self.transpiler is not None:
            # single source of truth: the transpiler's policy (dp batch
            # axis + sp time axis; see transpiler.feed_sharding)
            return self.transpiler.feed_sharding(arr.shape, name=name)
        dp = self.mesh.shape.get("dp", 1)
        dp_ok = (arr.shape[0] * jax.process_count()) % dp == 0
        if not dp_ok and dp > 1:
            if jax.process_count() > 1:
                # replication can't represent divergent per-host
                # batches — assembling them as "replicated" would make
                # hosts silently compute different gradients
                raise RuntimeError(
                    f"feed batch {arr.shape[0]} x "
                    f"{jax.process_count()} hosts does not divide "
                    f"dp={dp}; pad the local batch (multi-host feeds "
                    "cannot fall back to replication)")
            import warnings
            warnings.warn(
                f"feed batch {arr.shape[0]} does not divide dp={dp}; "
                "replicating this feed (no data parallelism for it)")
        return NamedSharding(self.mesh, P("dp" if dp_ok else None,
                                          *([None] * (arr.ndim - 1))))

    def _param_sharding(self, name):
        return self._shardings.get(name, self._replicated)

    def _feed_to_global(self, arr, sharding):
        """Place one host-side feed array. Single-process: plain
        device_put. Multi-process: `arr` is this HOST's local batch;
        assemble the global array (global batch = hosts' batches
        concatenated along the sharded axes — the per-trainer reader
        semantics). Feeds whose sharding is fully replicated must be
        host-identical (e.g. constants); that is the caller's contract,
        like the reference's broadcast-once parameters."""
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        from jax.experimental import multihost_utils
        return multihost_utils.host_local_array_to_global_array(
            np.asarray(arr), self.mesh, sharding.spec)

    def _param_to_global(self, val, sharding):
        """Place one persistable. Multi-process: every host holds an
        identically-seeded full copy; each materializes only its
        addressable shards."""
        if jax.process_count() == 1:
            return jax.device_put(val, sharding)
        if isinstance(val, jax.Array) and not val.is_fully_addressable:
            return val
        v = np.asarray(val)
        return jax.make_array_from_callback(v.shape, sharding,
                                            lambda idx: v[idx])

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True, is_test=False):
        feed = dict(feed or feed_dict or {})
        fetch_names = [f.name if hasattr(f, "name") else f
                       for f in (fetch_list or [])]
        program = self.program
        # per-rank telemetry (one flag check when off): pexe.* metrics
        # carry the process-index label via the registry default-labels
        # hook fleet.init installs — same metric names on every rank
        tm_on = _tm.enabled()
        t_run0 = time.perf_counter()

        seed = program.random_seed
        key = jax.random.fold_in(jax.random.PRNGKey(seed), self._step)
        self._step += 1

        feed_arrays = {}
        feed_sh = {}
        for k, v in feed.items():
            if isinstance(v, jax.Array) and not v.is_fully_addressable:
                # already a global array (e.g. a return_numpy=False
                # fetch): pass through with its own sharding
                feed_arrays[k] = v
                feed_sh[k] = v.sharding
                continue
            var = program.global_block().vars.get(k)
            dt = as_jnp_dtype(var.dtype) if var is not None else None
            # stay on host until placement — a jnp cast here would add
            # a device->host round-trip before the global assembly
            arr = np.asarray(v)
            if dt is not None and arr.dtype != np.dtype(dt):
                arr = arr.astype(dt)
            # single-process non-divisible batches fall back to
            # replication inside feed_sharding (slice_variable
            # remainder analog); multi-process they raise there
            sh = self._feed_sharding(arr, name=k)
            feed_sh[k] = sh
            feed_arrays[k] = self._feed_to_global(arr, sh)

        persist = {}
        persist_sh = {}
        for v in program.persistable_vars():
            val = self.scope.get(v.name)
            if val is None:
                raise RuntimeError(
                    f"persistable var {v.name!r} not initialized; run the "
                    f"startup program on a plain Executor first")
            sh = self._param_sharding(v.name)
            persist_sh[v.name] = sh
            persist[v.name] = self._param_to_global(val, sh)

        sig = tuple(sorted((k, v.shape, str(v.dtype))
                           for k, v in feed_arrays.items()))
        from ..core import trace as _trace
        ckey = (id(program), program._version, sig, tuple(fetch_names),
                bool(is_test), _trace.FUSE_OPTIMIZER_TAIL,
                _trace.FUSE_MAX_ELEMS)
        fn = self._cache.get(ckey)
        if fn is None:
            if tm_on:
                _tm.counter("pexe.compile_count").inc()
                _tm.gauge("pexe.device_count").set(self.device_count)
            step_fn = build_step_fn(program, fetch_names, is_test, None)

            def wrapped(persist_in, feed_in, key_in, _step=step_fn,
                        _sh=dict(persist_sh)):
                fetches, new_persist = _step(persist_in, feed_in, key_in)
                # pin state outputs to their input layout so the scope
                # keeps genuinely sharded arrays between steps (tp/ZeRO)
                new_persist = {
                    n: jax.lax.with_sharding_constraint(v, _sh[n])
                    if n in _sh else v
                    for n, v in new_persist.items()}
                return fetches, new_persist

            fn = jax.jit(
                wrapped,
                in_shardings=(persist_sh, dict(feed_sh),
                              self._replicated),
                donate_argnums=(0,))
            self._cache[ckey] = fn
        elif tm_on:
            _tm.counter("pexe.cache_hit_count").inc()

        with _tm.span("pexe.step", step=self._step - 1,
                      devices=self.device_count):
            fetches, new_persist = fn(persist, feed_arrays, key)
        for name, val in new_persist.items():
            self.scope.set(name, val)
        if tm_on:
            dt = time.perf_counter() - t_run0
            _tm.counter("pexe.steps").inc()
            _tm.histogram("pexe.step_seconds").observe(dt)
            _tm.fleet.on_step(dt)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches
