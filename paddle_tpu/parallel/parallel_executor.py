"""ParallelExecutor — distributed training over the local mesh.

Parity: python/paddle/fluid/parallel_executor.py. The reference builds a
multi-GPU SSA graph with NCCL all-reduce nodes per gradient; here the
SAME traced step function is jitted with sharded inputs over the mesh —
XLA keeps global-batch semantics (loss/grads identical to single device)
and inserts the collectives over ICI itself.

Beyond plain dp, a DistributeTranspiler (parallel/transpiler.py — the
distribute_transpiler.py analog) can be attached: its sharding table is
applied to params AND optimizer state, giving Megatron tensor parallel
(tp axis) and ZeRO-style optimizer-state sharding (mode="zero", the
pserver analog) THROUGH this executor — the scope then holds genuinely
sharded jax.Arrays between steps.

Multi-host (after fleet.init → jax.distributed.initialize): the mesh
spans every process's devices; each host feeds its LOCAL batch (the
reference's per-trainer readers) and the feeds are assembled into
global arrays (host_local_array_to_global_array), so the global batch
is the concatenation over hosts on the dp axis; params materialize
shard-wise from each host's identically-seeded full copy. Tested by
tests/test_multihost.py::test_two_process_data_parallel_training
(2-process dp == single-process global-batch numerics).
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.framework import default_main_program
from ..core.scope import global_scope
from ..core.trace import build_step_fn
from ..core.dtypes import as_jnp_dtype
from .. import telemetry as _tm
from ..resilience import chaos as _chaos
from .mesh import local_mesh

from ..core.compiler import BuildStrategy, ExecutionStrategy  # noqa: F401

__all__ = ["ParallelExecutor", "BuildStrategy", "ExecutionStrategy"]


class ParallelExecutor:
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None, mesh=None, use_tpu=None, transpiler=None,
                 grad_sync=None, sparse=None):
        self.program = main_program or default_main_program()
        self.loss_name = loss_name
        self.scope = scope or global_scope()
        self.transpiler = transpiler
        if transpiler is not None:
            if transpiler.mesh is None:
                transpiler.transpile(program=self.program)
            self.mesh = transpiler.mesh
            self._shardings = transpiler.shardings()
        else:
            self.mesh = mesh if mesh is not None else local_mesh("dp")
            self._shardings = {}
        # gradient-sync policy (parallel/gradsync.py): explicit arg >
        # PADDLE_TPU_GRAD_SYNC > minimize(grad_sync=...) program hint.
        # None keeps the implicit-XLA-all-reduce path bit-identical
        # (zero new fetches, state, collectives, or compile-key
        # entries — pinned by tests/test_gradsync.py).
        from . import gradsync as _gradsync
        self.grad_sync = _gradsync.resolve_policy(grad_sync,
                                                  program=self.program)
        # sparse-engine policy (parallel/sparse.py): only a program
        # that actually carries a distributed lookup table AND an
        # explicit opt-in (arg or PADDLE_TPU_SPARSE) ever imports the
        # engine — pinned by tests/test_bench_contract.py. The engine
        # runs the step under explicit shard_map, so it brings a
        # default fp32 gradsync policy for the dense params when none
        # is set.
        self.sparse_engine = None
        dist_tables = [
            op.inputs["W"][0]
            for op in self.program.global_block().ops
            if op.type == "lookup_table"
            and op.attrs.get("is_distributed")]
        if dist_tables:
            import os as _os
            spec = sparse if sparse is not None \
                else _os.environ.get("PADDLE_TPU_SPARSE")
            if spec is not None and str(spec).strip().lower() not in \
                    ("", "0", "off", "none", "false"):
                from . import sparse as _sparse
                pol = _sparse.parse_policy(spec)
                if transpiler is not None:
                    raise ValueError(
                        "the sparse engine owns its tables' sharding; "
                        "drop the DistributeTranspiler (its SPMD "
                        "row-sharding is the engine-off path) or the "
                        "sparse= policy")
                if self.grad_sync is None:
                    self.grad_sync = _gradsync.GradSyncPolicy("fp32")
                self.sparse_engine = _sparse.SparseEngine(
                    self.program, pol, self.mesh,
                    reduce=self.grad_sync.reduce)
        elif sparse is not None and str(sparse).strip().lower() not in \
                ("", "0", "off", "none", "false"):
            raise ValueError(
                "sparse= engine requested but the program has no "
                "distributed lookup table; build the embedding with "
                "is_distributed=True (and is_sparse=True)")
        if self.grad_sync is not None:
            if transpiler is not None:
                raise ValueError(
                    "grad_sync policies require pure data parallelism; "
                    "a DistributeTranspiler shards params/optimizer "
                    "state, which the explicit shard_map sync path "
                    "does not support — drop grad_sync or the "
                    "transpiler")
            if "dp" not in self.mesh.shape:
                raise ValueError(
                    "grad_sync policies need a 'dp' axis on the mesh")
        self._cache = {}
        self._step = 0
        # recompile-explainer state (telemetry on only): named fields
        # of every compile key seen, plus the latest explanation
        self._seen_fields = []
        self.last_recompile = None
        self._replicated = NamedSharding(self.mesh, P())
        # asynchronous step pipeline (tpupipe): same bounded in-flight
        # window as Executor.run(async_steps=k), over the shard_map /
        # SPMD path — run() returns PendingStep handles and defers the
        # global-fetch readback. 0/None keeps today's synchronous path.
        self.async_steps = None
        self._async_pipe = None

    @property
    def device_count(self):
        return int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names]))

    def _feed_sharding(self, arr, name=None):
        """Sharding for one HOST-LOCAL feed array (multi-process: the
        global batch is nproc local batches, which is what dp must
        divide)."""
        if arr.ndim == 0 or "dp" not in self.mesh.shape:
            return self._replicated
        if self.transpiler is not None:
            # single source of truth: the transpiler's policy (dp batch
            # axis + sp time axis; see transpiler.feed_sharding)
            return self.transpiler.feed_sharding(arr.shape, name=name)
        dp = self.mesh.shape.get("dp", 1)
        dp_ok = (arr.shape[0] * jax.process_count()) % dp == 0
        if not dp_ok and dp > 1:
            if jax.process_count() > 1:
                # replication can't represent divergent per-host
                # batches — assembling them as "replicated" would make
                # hosts silently compute different gradients
                raise RuntimeError(
                    f"feed batch {arr.shape[0]} x "
                    f"{jax.process_count()} hosts does not divide "
                    f"dp={dp}; pad the local batch (multi-host feeds "
                    "cannot fall back to replication)")
            import warnings
            warnings.warn(
                f"feed batch {arr.shape[0]} does not divide dp={dp}; "
                "replicating this feed (no data parallelism for it)")
        return NamedSharding(self.mesh, P("dp" if dp_ok else None,
                                          *([None] * (arr.ndim - 1))))

    def _param_sharding(self, name):
        return self._shardings.get(name, self._replicated)

    def _feed_to_global(self, arr, sharding):
        """Place one host-side feed array. Single-process: plain
        device_put. Multi-process: `arr` is this HOST's local batch;
        assemble the global array (global batch = hosts' batches
        concatenated along the sharded axes — the per-trainer reader
        semantics). Feeds whose sharding is fully replicated must be
        host-identical (e.g. constants); that is the caller's contract,
        like the reference's broadcast-once parameters."""
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        from jax.experimental import multihost_utils
        return multihost_utils.host_local_array_to_global_array(
            np.asarray(arr), self.mesh, sharding.spec)

    def _param_to_global(self, val, sharding):
        """Place one persistable. Multi-process: every host holds an
        identically-seeded full copy; each materializes only its
        addressable shards."""
        if jax.process_count() == 1:
            return jax.device_put(val, sharding)
        if isinstance(val, jax.Array) and not val.is_fully_addressable:
            return val
        v = np.asarray(val)
        return jax.make_array_from_callback(v.shape, sharding,
                                            lambda idx: v[idx])

    def _gradsync_prepare(self, program, persist, persist_sh):
        """Bucket plan + error-feedback state for the active grad_sync
        policy, plus the is_sparse tap list. Seeds `gradsync.ef.<bucket>`
        residuals (zeros) in the scope on first use and adds them to the
        persist set with dp sharding, so they ride the executor's
        existing donate/sharding path like any other state.

        Sparse row grads are SKIPPED by the bucketed/quantized wire —
        they belong to the sparse engine. Engine-owned tables handle
        their own exchange; any remaining (replicated) is_sparse table
        gets its taps returned so the grad transform can all-gather
        ids+row-grads over dp, keeping the tail's row-sparse update
        identical on every member."""
        from . import gradsync
        policy = self.grad_sync
        bops = [op for op in program.global_block().ops
                if op.type == "backward_macro"]
        if not bops:
            return [], []
        bop = bops[0]
        engine_tables = set(self.sparse_engine.tables) \
            if self.sparse_engine is not None else set()
        sparse_taps = [
            {"ids": tap["ids"], "delta": tap["delta"]}
            for spec in bop.attrs.get("sparse_params", [])
            if spec["param"] not in engine_tables
            for tap in spec["taps"]]
        named = [(n, tuple(persist[n].shape), persist[n].dtype)
                 for n in bop.attrs["param_names"]]
        plan = gradsync.plan_buckets(named, policy.bucket_bytes,
                                     policy.block_size)
        dp = self.mesh.shape.get("dp", 1)
        sh = NamedSharding(self.mesh, P("dp"))
        for name, local_len in gradsync.state_entries(plan, policy):
            val = self.scope.get(name)
            if val is None or tuple(val.shape) != (dp * local_len,):
                val = np.zeros((dp * local_len,), np.float32)
                self.scope.set(name, val)
            persist_sh[name] = sh
            persist[name] = self._param_to_global(val, sh)
            if _tm.memledger_enabled():
                # creation site of the error-feedback residuals — the
                # per-step classify keeps them attributed as they are
                # donated/recreated, this seeds the first sample
                from ..telemetry import memledger as _ml
                _ml.register("gradsync_ef", name, persist[name],
                             mode=policy.mode)
        return plan, sparse_taps

    def _build_gradsync_fn(self, program, fetch_names, is_test,
                           feed_arrays, feed_sh, persist, persist_sh,
                           plan, sparse_taps=()):
        """The explicit-sync path: the SAME traced step runs under
        shard_map over the dp axis (per-member local compute) and
        gradsync.sync_gradients performs the dp reduction with
        explicit — bucketed / quantized / overlappable — collectives.
        When the sparse engine is active it rides the same shard_map:
        its lookup/update ops dispatch through the engine
        (build_step_fn sparse_engine hook) and its sharded tables /
        stale rings keep their dp layout through out_specs.

        Fetch semantics: fetches whose leading dim is the local batch
        stay dp-sharded (reassembling to the global batch axis, exactly
        like the implicit path); other fetches are globalized with
        pmean for floats (exact for the batch-`mean` losses this path
        assumes — set reduce=sum in the policy for sum losses) and psum
        for integers (count-like fetches). Per-member RNG is
        decorrelated by folding the dp index into the step key (the
        reference's per-trainer seeds)."""
        from . import gradsync
        policy = self.grad_sync
        engine = self.sparse_engine
        mesh = self.mesh
        dp = mesh.shape.get("dp", 1)

        step = build_step_fn(
            program, fetch_names, is_test, None,
            grad_transform=gradsync.make_grad_transform(
                policy, plan, dp, sparse_taps=sparse_taps),
            sparse_engine=engine)

        persist_specs = {n: persist_sh[n].spec for n in persist}
        feed_specs = {k: feed_sh[k].spec for k in feed_arrays}

        def local_aval(arr, spec):
            shape = list(arr.shape)
            for i, ax in enumerate(spec):
                if ax is None:
                    continue
                for nm in (ax if isinstance(ax, tuple) else (ax,)):
                    shape[i] //= mesh.shape[nm]
            return jax.ShapeDtypeStruct(tuple(shape), arr.dtype)

        la_persist = {n: local_aval(persist[n], persist_specs[n])
                      for n in persist}
        la_feed = {k: local_aval(feed_arrays[k], feed_specs[k])
                   for k in feed_arrays}

        # classify fetches via an axis-free structural probe: the real
        # transform's collectives need the dp axis bound, so eval_shape
        # runs with shape-preserving stand-ins instead (identity
        # collectives in both the gradsync transform and the engine)
        probe = build_step_fn(
            program, fetch_names, is_test, None,
            grad_transform=gradsync.make_probe_transform(
                policy, plan, dp, sparse_taps=sparse_taps),
            sparse_engine=engine.probe_clone() if engine else None)
        f_avals, p_avals = jax.eval_shape(probe, la_persist, la_feed,
                                          jax.random.PRNGKey(0))

        batch_dims = set()
        for k in feed_arrays:
            ents = list(feed_specs[k])
            if ents and ents[0] is not None and "dp" in (
                    ents[0] if isinstance(ents[0], tuple)
                    else (ents[0],)):
                batch_dims.add(la_feed[k].shape[0])
        fetch_specs = []
        fetch_kind = []
        for av in f_avals:
            if av.ndim >= 1 and av.shape[0] in batch_dims:
                fetch_specs.append(P(*(["dp"] + [None] * (av.ndim - 1))))
                fetch_kind.append("batch")
            elif jnp.issubdtype(av.dtype, jnp.floating):
                fetch_specs.append(P())
                fetch_kind.append("mean")
            else:
                fetch_specs.append(P())
                fetch_kind.append("sum")
        def persist_out_spec(n):
            if n.startswith(gradsync.EF_PREFIX):
                return P("dp")
            if engine is not None and (
                    n in engine.row_var_names
                    or n in engine.state_names):
                return engine.out_spec(n) if n not in persist_specs \
                    else persist_specs[n]
            return P()

        out_persist_specs = {n: persist_out_spec(n) for n in p_avals}

        def mapped(persist_in, feed_in, key_in):
            key_in = jax.random.fold_in(key_in,
                                        jax.lax.axis_index("dp"))
            fetches, new_persist = step(persist_in, feed_in, key_in)
            out = []
            for f, kind in zip(fetches, fetch_kind):
                if kind == "mean":
                    f = jax.lax.pmean(f, "dp")
                elif kind == "sum" and f.dtype != jnp.bool_:
                    f = jax.lax.psum(f, "dp")
                out.append(f)
            return out, new_persist

        sm = jax.shard_map(mapped, mesh=mesh,
                           in_specs=(persist_specs, feed_specs, P()),
                           out_specs=(fetch_specs, out_persist_specs),
                           check_vma=False)
        return jax.jit(sm, donate_argnums=(0,))

    # ------------------------------------------------ async pipeline
    def drain(self):
        """Materialize every in-flight async step (see Executor.drain)."""
        if self._async_pipe is not None:
            self._async_pipe.drain()
        return self

    def discard_pending(self):
        """Abandon in-flight async steps without materializing them."""
        if self._async_pipe is not None:
            return self._async_pipe.discard()
        return 0

    @property
    def inflight(self):
        return len(self._async_pipe) if self._async_pipe is not None \
            else 0

    def _finalize_record(self, rec):
        """Deferred tail of an async pexe step: block, read the global
        fetches back, and emit the completion-side telemetry (fleet
        heartbeat, sparse-engine gauges) with that step's numbers."""
        fetches = rec["fetches"]
        if rec["deferred"]:
            t_w = time.perf_counter()
            with _tm.span("pexe.pending_wait", step=rec["step"]):
                jax.block_until_ready(fetches)
            if rec["tm_on"]:
                _tm.histogram("pexe.pending_wait_seconds").observe(
                    time.perf_counter() - t_w)
                _tm.fleet.on_step(rec["dt"])
                if rec["engine"] is not None:
                    rec["engine"].update_gauges(self.scope)
        if rec["return_numpy"]:
            return [np.asarray(f) for f in fetches]
        return fetches

    # ------------------------------------------------------------------
    def _mesh_context(self, fetch_names=(), feed_names=(),
                      memory_cap_bytes=None):
        """This executor's config as a meshlint MeshLintContext — the
        object verify() lints and tools/tpulint.py serializes. Imports
        meshlint, so only validate-on paths may call it (bench pin)."""
        from ..analysis.meshlint import MeshLintContext
        import jax as _jax
        param_specs = {n: tuple(sh.spec)
                       for n, sh in self._shardings.items()}
        return MeshLintContext(
            self.mesh,
            program=self.program,
            fetch_names=fetch_names,
            feed_names=feed_names,
            donate_state=True,        # donate_argnums=(0,) below
            async_steps=self.async_steps,
            grad_sync=self.grad_sync,
            sparse=(self.sparse_engine.policy
                    if self.sparse_engine is not None else None),
            processes=_jax.process_count(),
            backend=_jax.default_backend(),
            param_specs=param_specs,
            memory_cap_bytes=memory_cap_bytes,
            label="ParallelExecutor")

    def verify(self, fetch_list=None, feed_names=(), passes=None,
               raise_on_error=True, memory_cap_bytes=None):
        """Static pre-trace verification of this executor's sharded
        config: proglint over the Program (use-before-def, shapes,
        hazards) plus the meshlint passes (mesh-spec API-capability
        verdicts, collective consistency, donation aliasing, device
        footprint, recompile hazards). Runs automatically on each
        compile when PADDLE_TPU_VALIDATE=1 (or run(validate=True));
        callable directly for lint-only flows (tools/tpulint.py).
        Returns the combined diagnostics list."""
        from ..analysis import run_passes as _run_prog
        from ..analysis.diagnostics import ProgramVerificationError
        from ..analysis.meshlint import run_mesh_passes
        fetch_names = tuple(f.name if hasattr(f, "name") else f
                            for f in (fetch_list or ()))
        diags = list(_run_prog(self.program, fetch_list=fetch_names,
                               feed_names=feed_names))
        diags += run_mesh_passes(self._mesh_context(
            fetch_names=fetch_names, feed_names=feed_names,
            memory_cap_bytes=memory_cap_bytes), passes=passes)
        if raise_on_error and any(d.severity == "error" for d in diags):
            raise ProgramVerificationError(
                [d for d in diags if d.severity == "error"])
        return diags

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True, is_test=False, async_steps=None,
            validate=None):
        from ..core.executor import resolve_async_steps
        k_async = resolve_async_steps(async_steps, self.async_steps)
        feed = dict(feed or feed_dict or {})
        fetch_names = [f.name if hasattr(f, "name") else f
                       for f in (fetch_list or [])]
        program = self.program
        # per-rank telemetry (one flag check when off): pexe.* metrics
        # carry the process-index label via the registry default-labels
        # hook fleet.init installs — same metric names on every rank
        tm_on = _tm.enabled()
        t_run0 = time.perf_counter()

        seed = program.random_seed
        key = jax.random.fold_in(jax.random.PRNGKey(seed), self._step)
        self._step += 1
        # chaos: the SAME executor.step injection point the plain
        # Executor honors (step_fail / rank_lost / resize fire under
        # SPMD training too — the elastic selftest's kill target).
        # One cached-bool check when disarmed.
        if _chaos.armed():
            _chaos.check("executor.step",
                         detail=f"pexe step {self._step - 1}",
                         step=self._step - 1)

        feed_arrays = {}
        feed_sh = {}
        for k, v in feed.items():
            if isinstance(v, jax.Array) and not v.is_fully_addressable:
                # already a global array (e.g. a return_numpy=False
                # fetch): pass through with its own sharding
                feed_arrays[k] = v
                feed_sh[k] = v.sharding
                continue
            var = program.global_block().vars.get(k)
            dt = as_jnp_dtype(var.dtype) if var is not None else None
            # stay on host until placement — a jnp cast here would add
            # a device->host round-trip before the global assembly
            arr = np.asarray(v)
            if dt is not None and arr.dtype != np.dtype(dt):
                arr = arr.astype(dt)
            # single-process non-divisible batches fall back to
            # replication inside feed_sharding (slice_variable
            # remainder analog); multi-process they raise there
            sh = self._feed_sharding(arr, name=k)
            feed_sh[k] = sh
            feed_arrays[k] = self._feed_to_global(arr, sh)

        engine = self.sparse_engine
        engine_rows = set(engine.row_var_names) if engine else ()
        persist = {}
        persist_sh = {}
        for v in program.persistable_vars():
            if v.name in engine_rows:
                continue           # mod-sharded by the engine below
            val = self.scope.get(v.name)
            if val is None:
                raise RuntimeError(
                    f"persistable var {v.name!r} not initialized; run the "
                    f"startup program on a plain Executor first")
            sh = self._param_sharding(v.name)
            persist_sh[v.name] = sh
            persist[v.name] = self._param_to_global(val, sh)
        if engine is not None:
            dp = self.mesh.shape.get("dp", 1)

            def local_shape(k):
                shape = list(feed_arrays[k].shape)
                spec = tuple(feed_sh[k].spec)
                if shape and spec and spec[0] is not None:
                    shape[0] //= dp
                return tuple(shape)

            engine.plan_run({k: local_shape(k) for k in feed_arrays})
            engine.prepare_persist(persist, persist_sh, self.scope)
            for name, gshape, dt, spec, fill in engine.state_entries():
                sh = NamedSharding(self.mesh, spec)
                val = self.scope.get(name)
                if val is None or tuple(val.shape) != tuple(gshape):
                    val = np.full(gshape, fill, dt)
                    self.scope.set(name, val)
                persist_sh[name] = sh
                persist[name] = self._param_to_global(val, sh)

        policy = self.grad_sync
        gs_plan = gs_taps = None
        if policy is not None:
            gs_plan, gs_taps = self._gradsync_prepare(program, persist,
                                                      persist_sh)

        sig = tuple(sorted((k, v.shape, str(v.dtype))
                           for k, v in feed_arrays.items()))
        from ..core import trace as _trace
        ckey = (id(program), program._version, sig, tuple(fetch_names),
                bool(is_test), _trace.FUSE_OPTIMIZER_TAIL,
                _trace.FUSE_MAX_ELEMS)
        if policy is not None:
            # only the policy-on path may grow the compile key (the
            # off path stays byte-for-byte the historical tuple)
            ckey = ckey + (policy.key(),)
        if engine is not None:
            ckey = ckey + (engine.key(),)
        fn = self._cache.get(ckey)
        if fn is None:
            # opt-in pre-trace verification gate (same tri-state as
            # Executor.run: validate= arg > PADDLE_TPU_VALIDATE env):
            # proglint + meshlint once per compile, so a bad spec or a
            # capability the active jax rejects surfaces as a
            # ProgramVerificationError with a named pass instead of a
            # _SpecError stack from inside the trace. Cache hits (and
            # the default validate-off path) never import meshlint.
            from ..core.executor import Executor as _Exec
            if _Exec._validate_requested(validate):
                self.verify(fetch_list=fetch_names,
                            feed_names=list(feed_arrays))
            if tm_on:
                _tm.counter("pexe.compile_count").inc()
                _tm.gauge("pexe.device_count").set(self.device_count)
                # tpuscope recompile explainer: name the ckey
                # component (shape bucket, grad_sync policy, engine
                # key, ...) that busted the cache
                from ..telemetry import attribution as _attr
                fields = _attr.pexe_ckey_fields(
                    ckey,
                    policy_key=policy.key() if policy else None,
                    engine_key=engine.key() if engine else None)
                if self._seen_fields:
                    self.last_recompile = _attr.explain_recompile(
                        "pexe", fields, self._seen_fields,
                        step=self._step - 1)
                self._seen_fields.append(fields)
            if policy is not None:
                fn = self._build_gradsync_fn(
                    program, fetch_names, is_test, feed_arrays, feed_sh,
                    persist, persist_sh, gs_plan,
                    sparse_taps=gs_taps or ())
                self._cache[ckey] = fn
            else:
                step_fn = build_step_fn(program, fetch_names, is_test,
                                        None)

                def wrapped(persist_in, feed_in, key_in, _step=step_fn,
                            _sh=dict(persist_sh)):
                    fetches, new_persist = _step(persist_in, feed_in,
                                                 key_in)
                    # pin state outputs to their input layout so the
                    # scope keeps genuinely sharded arrays between
                    # steps (tp/ZeRO)
                    new_persist = {
                        n: jax.lax.with_sharding_constraint(v, _sh[n])
                        if n in _sh else v
                        for n, v in new_persist.items()}
                    return fetches, new_persist

                fn = jax.jit(
                    wrapped,
                    in_shardings=(persist_sh, dict(feed_sh),
                                  self._replicated),
                    donate_argnums=(0,))
                self._cache[ckey] = fn
        elif tm_on:
            _tm.counter("pexe.cache_hit_count").inc()

        with _tm.span("pexe.step", step=self._step - 1,
                      devices=self.device_count):
            try:
                fetches, new_persist = fn(persist, feed_arrays, key)
            except Exception as e:
                if _tm.memledger_enabled():
                    from ..telemetry import memledger as _ml
                    _ml.handle_possible_oom(
                        e, context={"site": "pexe.step",
                                    "step": self._step - 1,
                                    "devices": self.device_count})
                raise
        if k_async > 0:
            # a fetch that is ALSO a persistable output may alias the
            # state buffer the next queued step donates — give pending
            # fetches their own buffer (async only; see Executor.run)
            fetches = [jnp.copy(f) if n in new_persist else f
                       for n, f in zip(fetch_names, fetches)]
        for name, val in new_persist.items():
            self.scope.set(name, val)
        if _tm.memledger_enabled():
            # attribute the global (sharded) state: gradsync.ef.* and
            # optimizer slots classify by name, engine rows + engine
            # state are the sparse_table bucket; feeds are transient
            from ..telemetry import memledger as _ml
            sparse_names = set(engine_rows)
            if engine is not None:
                sparse_names.update(
                    n for n, *_rest in engine.state_entries())
            for _n, _v in new_persist.items():
                cat = ("sparse_table" if _n in sparse_names
                       else _ml.classify_persist_name(_n))
                _ml.register(cat, _n, _v)
            for _n, _v in feed_arrays.items():
                _ml.register("feed", _n, _v)
            _ml.on_step(step=self._step - 1,
                        context={"site": "pexe.step",
                                 "devices": self.device_count})
        dt = time.perf_counter() - t_run0
        if tm_on:
            _tm.counter("pexe.steps").inc()
            _tm.histogram("pexe.step_seconds").observe(dt)
            # completion-side accounting (heartbeat, engine gauges)
            # defers to materialization in async mode
            if k_async == 0:
                _tm.fleet.on_step(dt)
                if engine is not None:
                    engine.update_gauges(self.scope)
        rec = {"step": self._step - 1, "fetches": fetches,
               "fetch_names": fetch_names, "return_numpy": return_numpy,
               "tm_on": tm_on, "dt": dt, "engine": engine,
               "deferred": k_async > 0}
        if k_async > 0:
            from ..core.pipeline_exec import PendingStep, StepWindow
            if tm_on:
                _tm.counter("pexe.async_steps").inc()
            pipe = self._async_pipe
            if pipe is None:
                pipe = self._async_pipe = StepWindow(
                    k_async, gauge_name="pexe.inflight")
            pipe.depth = max(1, k_async)
            return pipe.push(PendingStep(pipe, rec,
                                         self._finalize_record))
        return self._finalize_record(rec)
