"""ParallelExecutor — distributed training over the local mesh.

Parity: python/paddle/fluid/parallel_executor.py. The reference builds a
multi-GPU SSA graph with NCCL all-reduce nodes per gradient; here the
SAME traced step function is jitted with sharded inputs over the mesh —
XLA keeps global-batch semantics (loss/grads identical to single device)
and inserts the collectives over ICI itself.

Beyond plain dp, a DistributeTranspiler (parallel/transpiler.py — the
distribute_transpiler.py analog) can be attached: its sharding table is
applied to params AND optimizer state, giving Megatron tensor parallel
(tp axis) and ZeRO-style optimizer-state sharding (mode="zero", the
pserver analog) THROUGH this executor — the scope then holds genuinely
sharded jax.Arrays between steps.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.framework import default_main_program
from ..core.scope import global_scope
from ..core.trace import build_step_fn
from ..core.dtypes import as_jnp_dtype
from .mesh import local_mesh

from ..core.compiler import BuildStrategy, ExecutionStrategy  # noqa: F401

__all__ = ["ParallelExecutor", "BuildStrategy", "ExecutionStrategy"]


class ParallelExecutor:
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None, mesh=None, use_tpu=None, transpiler=None):
        self.program = main_program or default_main_program()
        self.loss_name = loss_name
        self.scope = scope or global_scope()
        self.transpiler = transpiler
        if transpiler is not None:
            if transpiler.mesh is None:
                transpiler.transpile(program=self.program)
            self.mesh = transpiler.mesh
            self._shardings = transpiler.shardings()
        else:
            self.mesh = mesh if mesh is not None else local_mesh("dp")
            self._shardings = {}
        self._cache = {}
        self._step = 0
        self._replicated = NamedSharding(self.mesh, P())

    @property
    def device_count(self):
        return int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names]))

    def _feed_sharding(self, arr, name=None):
        if arr.ndim == 0 or "dp" not in self.mesh.shape:
            return self._replicated
        if self.transpiler is not None:
            # single source of truth: the transpiler's policy (dp batch
            # axis + sp time axis; see transpiler.feed_sharding)
            return self.transpiler.feed_sharding(arr.shape, name=name)
        dp = self.mesh.shape.get("dp", 1)
        dp_ok = arr.ndim > 0 and arr.shape[0] % dp == 0
        if not dp_ok and dp > 1:
            import warnings
            warnings.warn(
                f"feed batch {arr.shape[0]} does not divide dp={dp}; "
                "replicating this feed (no data parallelism for it)")
        return NamedSharding(self.mesh, P("dp" if dp_ok else None,
                                          *([None] * (arr.ndim - 1))))

    def _param_sharding(self, name):
        return self._shardings.get(name, self._replicated)

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True, is_test=False):
        feed = dict(feed or feed_dict or {})
        fetch_names = [f.name if hasattr(f, "name") else f
                       for f in (fetch_list or [])]
        program = self.program

        seed = program.random_seed
        key = jax.random.fold_in(jax.random.PRNGKey(seed), self._step)
        self._step += 1

        feed_arrays = {}
        for k, v in feed.items():
            var = program.global_block().vars.get(k)
            dt = as_jnp_dtype(var.dtype) if var is not None else None
            arr = jnp.asarray(np.asarray(v), dtype=dt)
            # non-divisible batches fall back to replication inside
            # feed_sharding (slice_variable remainder analog) rather
            # than erroring — XLA still computes the correct math
            feed_arrays[k] = jax.device_put(
                arr, self._feed_sharding(arr, name=k))

        persist = {}
        persist_sh = {}
        for v in program.persistable_vars():
            val = self.scope.get(v.name)
            if val is None:
                raise RuntimeError(
                    f"persistable var {v.name!r} not initialized; run the "
                    f"startup program on a plain Executor first")
            sh = self._param_sharding(v.name)
            persist_sh[v.name] = sh
            persist[v.name] = jax.device_put(val, sh)

        sig = tuple(sorted((k, v.shape, str(v.dtype))
                           for k, v in feed_arrays.items()))
        from ..core import trace as _trace
        ckey = (id(program), program._version, sig, tuple(fetch_names),
                bool(is_test), _trace.FUSE_OPTIMIZER_TAIL,
                _trace.FUSE_MAX_ELEMS)
        fn = self._cache.get(ckey)
        if fn is None:
            step_fn = build_step_fn(program, fetch_names, is_test, None)

            def wrapped(persist_in, feed_in, key_in, _step=step_fn,
                        _sh=dict(persist_sh)):
                fetches, new_persist = _step(persist_in, feed_in, key_in)
                # pin state outputs to their input layout so the scope
                # keeps genuinely sharded arrays between steps (tp/ZeRO)
                new_persist = {
                    n: jax.lax.with_sharding_constraint(v, _sh[n])
                    if n in _sh else v
                    for n, v in new_persist.items()}
                return fetches, new_persist

            fn = jax.jit(
                wrapped,
                in_shardings=(
                    persist_sh,
                    {n: self._feed_sharding(feed_arrays[n], name=n)
                     for n in feed_arrays},
                    self._replicated),
                donate_argnums=(0,))
            self._cache[ckey] = fn

        fetches, new_persist = fn(persist, feed_arrays, key)
        for name, val in new_persist.items():
            self.scope.set(name, val)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches
