"""Four-axis composed training step: dp x tp x pp x sp in ONE
compiled program. The generic PipelineTrainer composes dp x pp through
the Program IR (pipeline.py); this module is the explicit-collectives
variant demonstrating all four axes with real sharded compute — the
dryrun 4-axis leg and tests/test_four_axis.py drive it.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["four_axis_train_step"]


def four_axis_train_step(mesh, params, x, y, n_microbatch,
                         lr=0.05):
    """ONE compiled program composing all four parallelism axes with
    real sharded compute on each (VERDICT r2 item 7):

    - pp: stage params stacked on the leading axis, activations hop
      stage to stage via ppermute (GPipe schedule), gradients hop back
      through the AD transpose of the same permute;
    - tp: each stage is a Megatron pair — column-parallel w1, row-
      parallel w2, one psum per stage boundary (bias-free by
      construction so the partial-sum reduce is exact);
    - dp: the microbatch batch dim is sharded; grads reduce over dp via
      the shard_map AD transpose of the replicated params;
    - sp: the sequence dim is sharded; the stage compute is
      position-wise so sp needs no collective (the attention case is
      covered by ring_attention / Ulysses on their own legs).

    params: (w1 [S, D, H], w2 [S, H, D]); x, y: [B, T, D].
    Returns (loss, new_params) after one SGD step.
    """
    S = mesh.shape["pp"]
    n_mb = n_microbatch

    def per_member(w1s, w2s, mb_x, mb_y):
        """One (pp, dp, tp, sp) member: w1 [1, D, H/tp], w2 [1, H/tp, D],
        mb_x/mb_y [n_mb, mb/dp, T/sp, D]."""
        w1, w2 = w1s[0], w2s[0]
        stage = lax.axis_index("pp")
        perm = [(i, (i + 1) % S) for i in range(S)]
        n_steps = n_mb + S - 1

        def stage_fn(h):
            # Megatron pair: col-parallel matmul, pointwise act,
            # row-parallel matmul, ONE psum over tp
            hh = jnp.maximum(h @ w1, 0.0)
            return lax.psum(hh @ w2, "tp")

        def step(carry, t):
            inflight, loss_sum = carry
            mb_idx = jnp.clip(t - stage, 0, n_mb - 1)
            my_in = jnp.where(stage == 0, mb_x[mb_idx], inflight)
            h = stage_fn(my_in)
            valid = (t >= stage) & (t - stage < n_mb)
            is_last = stage == S - 1
            local = jnp.sum((h - mb_y[mb_idx]) ** 2)
            loss_sum = loss_sum + jnp.where(valid & is_last, local, 0.0)
            return (lax.ppermute(h, "pp", perm), loss_sum), None

        (_, loss_sum), _ = lax.scan(
            step, (jnp.zeros_like(mb_x[0]), jnp.zeros((), jnp.float32)),
            jnp.arange(n_steps))
        # mean over every data element: psum over dp (batch shards) and
        # sp (sequence shards); tp already replicated by the stage psum
        total = lax.psum(loss_sum, ("pp", "dp", "sp"))
        return total

    def train_loss(params, mb_x, mb_y):
        w1s, w2s = params
        sm = jax.shard_map(
            per_member, mesh=mesh,
            in_specs=(P("pp", None, "tp"), P("pp", "tp", None),
                      P(None, "dp", "sp", None), P(None, "dp", "sp", None)),
            out_specs=P(), check_vma=False)
        return sm(w1s, w2s, mb_x, mb_y) / np.prod(mb_x.shape[:3])

    def step_fn(params, x, y, lr_t):
        mb_x = x.reshape((n_mb, x.shape[0] // n_mb) + x.shape[1:])
        mb_y = y.reshape((n_mb, y.shape[0] // n_mb) + y.shape[1:])
        loss, grads = jax.value_and_grad(train_loss)(params, mb_x, mb_y)
        new_params = jax.tree.map(lambda p, g: p - lr_t * g, params,
                                  grads)
        return loss, new_params

    # cache the jitted step per (mesh, n_mb): a fresh jax.jit wrapper
    # every call would retrace/recompile each step when driven in a
    # training loop (ADVICE r3); lr rides along as a traced scalar so
    # schedules don't recompile either
    key = (mesh, n_mb)
    jitted = _STEP_CACHE.get(key)
    if jitted is None:
        jitted = _STEP_CACHE[key] = jax.jit(step_fn)
    return jitted(params, x, y, jnp.float32(lr))


_STEP_CACHE = {}


