"""paddle_tpu — a TPU-native deep-learning framework with the
capabilities of PaddlePaddle Fluid (reference: /root/reference).

Front-end API mirrors `paddle.fluid` (Program/Executor/layers/optimizer);
execution is whole-program XLA compilation via JAX (see SURVEY.md §1 for
the design map). Usage:

    import paddle_tpu as fluid
    img = fluid.layers.data('img', shape=[784])
    ...
    exe = fluid.Executor(fluid.TPUPlace(0))
"""
import jax as _jax

# jax<0.5 compat shims (no-ops on newer jax): this codebase uses the
# current public names; older images alias them back to their
# pre-graduation homes so the package imports and runs on both.
if not hasattr(_jax, "shard_map"):
    # shard_map lived in jax.experimental, with check_rep instead of
    # the renamed check_vma kwarg
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
        import functools as _functools

        @_functools.wraps(_shard_map)
        def _shard_map_compat(*args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(*args, **kwargs)

        _jax.shard_map = _shard_map_compat
    except ImportError:
        pass
if not hasattr(_jax.lax, "axis_size"):
    # lax.axis_size(name) predates this jax; psum(1, name) is the
    # classic spelling of the same (static) quantity
    _jax.lax.axis_size = lambda axis_name: _jax.lax.psum(1, axis_name)
if not hasattr(_jax, "enable_x64"):
    try:
        from jax.experimental import enable_x64 as _enable_x64
        _jax.enable_x64 = _enable_x64
    except ImportError:
        pass
try:
    from jax.experimental.pallas import tpu as _pltpu
    if not hasattr(_pltpu, "CompilerParams") \
            and hasattr(_pltpu, "TPUCompilerParams"):
        _pltpu.CompilerParams = _pltpu.TPUCompilerParams
except ImportError:
    pass

# TPU-native PRNG: XLA's RngBitGenerator ("rbg") instead of JAX's default
# threefry. threefry lowers to a long scalar-ish VPU program that costs
# ~40% of a dropout-heavy train step on TPU; rbg is a hardware RNG
# instruction AND is partitionable — under pjit/shard_map each shard
# generates its bits locally with no cross-device dependency (the same
# reason the scaling playbook recommends it). Counter-based determinism
# per (seed, step) is preserved; bit-exact streams just aren't portable
# across backends, matching the reference's per-device cuRAND behavior.
try:
    _jax.config.update("jax_default_prng_impl", "rbg")
except Exception:  # very old jax without the option — keep threefry
    pass

# Opt-in persistent XLA compilation cache: first compiles through a TPU
# relay cost 20-40s per executable; with PADDLE_TPU_COMPILE_CACHE=<dir>
# repeat runs reload them in milliseconds. Env-gated (no surprise disk
# writes); backends that can't serialize executables just ignore it.
import os as _os
_cache_dir = _os.environ.get("PADDLE_TPU_COMPILE_CACHE")
if _cache_dir:
    try:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

from . import telemetry         # runtime metrics/spans (dep-free; first)
from . import ops               # registers all kernels
from . import unique_name
from .core.framework import (
    Program, Block, Operator, Variable, Parameter,
    default_main_program, default_startup_program, program_guard,
    name_scope,
)
from .core.place import CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace
from .core.scope import Scope, global_scope, scope_guard
from .core.executor import Executor
from .core.backward import append_backward, gradients
from . import layers
from . import initializer
from . import optimizer
from . import regularizer
from . import clip
from . import nets
from . import metrics
from .param_attr import ParamAttr, WeightNormParamAttr
from . import io
from .io import (save_params, save_persistables, load_params,
                 load_persistables, save_inference_model,
                 load_inference_model, save_checkpoint, load_checkpoint)
from . import lod
from .lod import LoDTensor, LoDTensorArray, create_lod_tensor
from . import parallel
from .parallel.parallel_executor import ParallelExecutor
from .core.compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from . import amp
from . import profiler
from .data_feeder import DataFeeder
from . import reader
from . import dataset
from . import models
from . import imperative
from . import utils
# reference import-path aliases: paddle.fluid.{framework,executor,
# parallel_executor,backward} are real modules there — expose the same
# paths so `fluid.framework.Program` / `from paddle_tpu.executor
# import Executor` work after the s/paddle.fluid/paddle_tpu/ swap
from . import framework
from . import executor
from . import parallel_executor
from . import backward
from .trainer import Trainer, Inferencer, CheckpointConfig
from . import average
from .average import WeightedAverage
from . import evaluator
from . import lod_tensor
from .lod_tensor import create_random_int_lodtensor
from . import transpiler
from .transpiler import (DistributeTranspiler, DistributeTranspilerConfig,
                         InferenceTranspiler, memory_optimize,
                         release_memory, HashName, RoundRobin)
from . import analysis
from . import diagnostics
from . import resilience
from . import contrib
from .async_executor import AsyncExecutor
from .data_feed_desc import DataFeedDesc
from . import default_scope_funcs
from . import distribute_lookup_table
from . import distributed
from . import net_drawer
from . import op
from .core import EOFException
from . import annotations
from . import compat
from . import graphviz
from . import inferencer
from . import inference
from . import serving
from .batch import batch
from . import recordio_writer
from .core import backward
# the reference's pre-layers LR-decay module name (same functions as
# layers.learning_rate_scheduler)
from .layers import learning_rate_scheduler as learning_rate_decay

# Tensor/LoDTensor aliases (ref fluid.Tensor is LoDTensor without LoD)
Tensor = LoDTensor

__version__ = "0.1.0"
