"""DataFeeder — minibatch lists → feed dict of device-ready arrays.

Parity: python/paddle/fluid/data_feeder.py. Ragged (lod_level>0) slots are
padded + get a companion `<name>_seq_len` entry (see lod.py), replacing
the reference's LoDTensor construction.
"""
import numpy as np

from .core.dtypes import as_jnp_dtype
from .lod import to_padded

__all__ = ["DataFeeder"]


def _concat_feeds(dicts):
    out = {}
    for k in dicts[0]:
        arrs = [np.asarray(d[k]) for d in dicts]
        # ragged slots were padded per-minibatch; re-pad to the common
        # max before concatenating along the batch axis
        if len({a.shape[1:] for a in arrs}) > 1:
            tgt = tuple(max(a.shape[i] for a in arrs)
                        for i in range(1, arrs[0].ndim))
            padded = []
            for a in arrs:
                pads = [(0, 0)] + [(0, t - s) for t, s in
                                   zip(tgt, a.shape[1:])]
                padded.append(np.pad(a, pads))
            arrs = padded
        out[k] = np.concatenate(arrs, axis=0)
    return out


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = feed_list
        self.place = place

    def feed(self, iterable):
        """iterable: list of samples, each a tuple matching feed_list."""
        samples = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_vars):
            name = var.name if hasattr(var, "name") else var
            column = [s[i] for s in samples]
            lod_level = getattr(var, "lod_level", 0)
            if lod_level and lod_level > 0:
                padded, lens = to_padded(column)
                dt = np.dtype(str(np.asarray(padded).dtype))
                out[name] = padded
                out[name + "_seq_len"] = lens
            else:
                arr = np.asarray(column)
                if hasattr(var, "dtype"):
                    arr = arr.astype(as_jnp_dtype(var.dtype))
                # honor declared trailing shape (e.g. label [-1, 1])
                if hasattr(var, "shape") and var.shape:
                    want = [s for s in var.shape]
                    if (len(want) == arr.ndim + 1 and want[-1] == 1):
                        arr = arr[..., None]
                out[name] = arr
        return out

    def feed_parallel(self, iterable, num_places=None):
        """ref data_feeder.py:feed_parallel — one minibatch per device.

        The reference places each minibatch on its own device; here the
        ParallelExecutor shards the batch axis over the mesh, so the
        per-device batches concatenate into one global batch (each
        device ends up with exactly its own minibatch's rows)."""
        batches = [self.feed(mb) for mb in iterable]
        if not batches:
            raise ValueError("feed_parallel got no minibatches")
        if num_places is not None and len(batches) != num_places:
            raise ValueError(
                f"feed_parallel got {len(batches)} minibatches for "
                f"{num_places} places")
        return _concat_feeds(batches)

    def _get_number_of_places_(self, num_places):
        if num_places is not None:
            return int(num_places)
        import jax
        return len(jax.devices())

    def decorate_reader(self, reader, multi_devices, num_places=None,
                        drop_last=True):
        """ref data_feeder.py:decorate_reader — wrap a sample-batch
        reader into one yielding ready feed dicts; with multi_devices,
        group num_places batches into one global feed (the mesh shards
        the batch axis, replacing per-device placement)."""

        def __reader_creator__():
            if not multi_devices:
                for item in reader():
                    yield self.feed(item)
            else:
                num = self._get_number_of_places_(num_places)
                group = []
                for batch in reader():
                    group.append(batch)
                    if len(group) == num:
                        yield self.feed_parallel(group, num)
                        group = []
                if group and not drop_last:
                    raise ValueError(
                        "The data batch which cannot fit for devices "
                        "will be dropped is not implementation. Other "
                        "strategies are not implemented")

        return __reader_creator__
