"""DataFeeder — minibatch lists → feed dict of device-ready arrays.

Parity: python/paddle/fluid/data_feeder.py. Ragged (lod_level>0) slots are
padded + get a companion `<name>_seq_len` entry (see lod.py), replacing
the reference's LoDTensor construction.
"""
import numpy as np

from .core.dtypes import as_jnp_dtype
from .lod import to_padded

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = feed_list
        self.place = place

    def feed(self, iterable):
        """iterable: list of samples, each a tuple matching feed_list."""
        samples = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_vars):
            name = var.name if hasattr(var, "name") else var
            column = [s[i] for s in samples]
            lod_level = getattr(var, "lod_level", 0)
            if lod_level and lod_level > 0:
                padded, lens = to_padded(column)
                dt = np.dtype(str(np.asarray(padded).dtype))
                out[name] = padded
                out[name + "_seq_len"] = lens
            else:
                arr = np.asarray(column)
                if hasattr(var, "dtype"):
                    arr = arr.astype(as_jnp_dtype(var.dtype))
                # honor declared trailing shape (e.g. label [-1, 1])
                if hasattr(var, "shape") and var.shape:
                    want = [s for s in var.shape]
                    if (len(want) == arr.ndim + 1 and want[-1] == 1):
                        arr = arr[..., None]
                out[name] = arr
        return out
