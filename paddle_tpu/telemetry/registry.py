"""Metrics registry: counters, gauges, fixed-bucket histograms.

The registry is process-global and thread-safe. Metrics are created
lazily at use sites (`counter(name).inc()`); instrumented hot paths
guard creation on `telemetry.enabled()`, so with telemetry off nothing
is ever registered and `snapshot()` stays `{}` — the disabled mode
costs one flag check per site, no allocation, no locking.

Deliberately dependency-free (no jax, no paddle_tpu imports): the
executor, readers, and the native predictor all import this during
package init.
"""
import math
import threading

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge",
           "histogram", "snapshot", "snapshot_with_kinds",
           "reset_metrics", "prometheus_text", "set_default_labels",
           "default_labels", "quantile_from_buckets",
           "DEFAULT_TIME_BUCKETS"]

# exponential wall-time buckets, 100µs .. 2min (seconds); the spread
# covers a cached CPU step (~1ms) through a cold TPU-relay compile
DEFAULT_TIME_BUCKETS = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

_metrics = {}           # name -> metric
_registry_lock = threading.Lock()

# Registry-level default labels (e.g. {"process_index": 3}): one hook
# tags EVERY metric this process exports without touching call sites —
# metric names stay identical across ranks (which is what makes the
# fleet merge line up), the labels ride along in the export envelope
# (telemetry.fleet.build_envelope) instead of being baked into names.
_default_labels = {}


def set_default_labels(labels):
    with _registry_lock:
        _default_labels.clear()
        _default_labels.update(
            {str(k): v for k, v in (labels or {}).items()})


def default_labels():
    with _registry_lock:
        return dict(_default_labels)


def _bucket_quantile(edges, counts, q, lo=None, hi=None):
    """Interpolated quantile over fixed buckets: find the bucket the
    q-rank falls in, interpolate linearly inside it. `counts` has one
    extra trailing slot (+Inf); the observed min/max tighten the open
    ends (first bucket's lower bound, +Inf's upper bound) and clamp
    the result so an estimate never leaves the observed range."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if rank <= cum + c or i == len(counts) - 1:
            lower = edges[i - 1] if i > 0 else \
                (lo if lo is not None else 0.0)
            upper = edges[i] if i < len(edges) else \
                (hi if hi is not None else lower)
            frac = (rank - cum) / c
            frac = 0.0 if frac < 0.0 else (1.0 if frac > 1.0 else frac)
            v = lower + (upper - lower) * frac
            if lo is not None and v < lo:
                v = lo
            if hi is not None and v > hi:
                v = hi
            return v
        cum += c
    return None


def quantile_from_buckets(value, q):
    """Quantile estimate from a histogram's snapshot form (the
    `to_value()` dict, as found in registry snapshots and the fleet
    merge). Returns None for an empty histogram."""
    if not isinstance(value, dict) or not value.get("count"):
        return None
    buckets = value.get("buckets") or {}
    # bucket keys are floats in-process but strings after a JSON round
    # trip (fleet spool files, /metrics consumers) — coerce either way
    edges = sorted(float(k) for k in buckets if k != "+Inf")
    by_edge = {float(k): v for k, v in buckets.items() if k != "+Inf"}
    counts = [by_edge[e] for e in edges] + [buckets.get("+Inf", 0)]
    return _bucket_quantile(edges, counts, q,
                            value.get("min"), value.get("max"))


class Counter:
    """Monotonically increasing count."""
    kind = "counter"
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def to_value(self):
        # lock audit (fleet merge hardening): reads go through the
        # metric lock like writes do — a bare int read is atomic in
        # CPython today, but snapshot()/flush() running concurrently
        # with inc() must stay correct by contract, not by accident
        with self._lock:
            return self._value


class Gauge:
    """Last-written value, with a set_max helper for watermarks."""
    kind = "gauge"
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v):
        with self._lock:
            self._value = v

    def set_max(self, v):
        with self._lock:
            if v > self._value:
                self._value = v

    def add(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def to_value(self):
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram: per-bucket counts plus sum/min/max.

    `buckets` are inclusive upper bounds; an implicit +Inf bucket
    catches the tail. Bucket edges are frozen at creation — a second
    `histogram(name)` call with different edges raises, so two call
    sites can never silently split one metric.
    """
    kind = "histogram"
    __slots__ = ("name", "buckets", "_lock", "_counts", "_sum",
                 "_count", "_min", "_max")

    def __init__(self, name, buckets=None):
        self.name = name
        bs = tuple(float(b) for b in (buckets or DEFAULT_TIME_BUCKETS))
        if list(bs) != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError(f"histogram {name!r}: buckets must be "
                             f"strictly increasing, got {bs}")
        self.buckets = bs
        self._lock = threading.Lock()
        self._counts = [0] * (len(bs) + 1)   # [+Inf] is the last slot
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v):
        v = float(v)
        # the bucket search reads only the immutable edge tuple, so it
        # stays outside the lock; every mutable field (_counts, _sum,
        # _count, _min, _max) is updated in ONE critical section, and
        # to_value() reads them under the same lock — a snapshot/flush
        # racing observe() therefore always sees a consistent histogram
        # (bucket totals == count), never a torn multi-field update
        i = 0
        for i, edge in enumerate(self.buckets):
            if v <= edge:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def quantile(self, q):
        """Interpolated quantile estimate from the bucket counts
        (None while empty). Exact only up to bucket resolution —
        good enough for SLO gating, not for billing."""
        with self._lock:
            return _bucket_quantile(
                self.buckets, self._counts, q,
                self._min if self._count else None,
                self._max if self._count else None)

    def to_value(self):
        with self._lock:
            d = {"count": self._count, "sum": self._sum,
                 "buckets": {le: c for le, c in
                             zip(self.buckets, self._counts)}}
            d["buckets"]["+Inf"] = self._counts[-1]
            if self._count:
                d["min"] = self._min
                d["max"] = self._max
                d["mean"] = self._sum / self._count
                d["p50"] = _bucket_quantile(
                    self.buckets, self._counts, 0.5, self._min,
                    self._max)
                d["p99"] = _bucket_quantile(
                    self.buckets, self._counts, 0.99, self._min,
                    self._max)
        return d


def _get(name, cls, **kwargs):
    m = _metrics.get(name)
    if m is None:
        with _registry_lock:
            m = _metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                _metrics[name] = m
    if not isinstance(m, cls):
        raise TypeError(f"metric {name!r} is a {m.kind}, not a "
                        f"{cls.kind}")
    if kwargs.get("buckets") is not None \
            and m.buckets != tuple(float(b) for b in kwargs["buckets"]):
        raise ValueError(f"histogram {name!r} already registered with "
                         f"buckets {m.buckets}")
    return m


def counter(name):
    return _get(name, Counter)


def gauge(name):
    return _get(name, Gauge)


def histogram(name, buckets=None):
    return _get(name, Histogram, buckets=buckets)


def snapshot():
    """{metric_name: value} — counters/gauges as numbers, histograms as
    {count, sum, min, max, mean, buckets}. Empty when nothing was ever
    recorded (the disabled-mode contract)."""
    with _registry_lock:
        metrics = list(_metrics.values())
    return {m.name: m.to_value() for m in metrics}


def snapshot_with_kinds():
    """{name: {"kind": "counter"|"gauge"|"histogram", "value": ...}} —
    the merge-safe export: a plain snapshot() can't distinguish a
    counter from a gauge, but cross-rank merge semantics differ
    (counters sum, gauges keep per-rank values), so the fleet spool
    envelope carries the kind with every value."""
    with _registry_lock:
        metrics = list(_metrics.values())
    return {m.name: {"kind": m.kind, "value": m.to_value()}
            for m in metrics}


def reset_metrics():
    with _registry_lock:
        _metrics.clear()


def _prom_name(name):
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    return out if not out[:1].isdigit() else "_" + out


def prometheus_text():
    """Prometheus text exposition of the current registry. Histogram
    buckets are emitted cumulatively with the closing `+Inf` bucket
    equal to `_count`, per the format spec."""
    with _registry_lock:
        metrics = sorted(_metrics.values(), key=lambda m: m.name)
    lines = []
    for m in metrics:
        pname = _prom_name(m.name)
        lines.append(f"# TYPE {pname} {m.kind}")
        if m.kind == "histogram":
            v = m.to_value()
            cum = 0
            for le in m.buckets:
                cum += v["buckets"][le]
                lines.append(f'{pname}_bucket{{le="{le:g}"}} {cum}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} {v["count"]}')
            lines.append(f"{pname}_sum {v['sum']:g}")
            lines.append(f"{pname}_count {v['count']}")
            if v["count"]:
                # quantile summaries alongside the raw buckets, so
                # scrape-side dashboards (and SLO rules) don't need
                # to re-derive them from _bucket counts
                lines.append(f"{pname}_p50 {v['p50']:g}")
                lines.append(f"{pname}_p99 {v['p99']:g}")
        else:
            lines.append(f"{pname} {m.to_value():g}")
    return "\n".join(lines) + ("\n" if lines else "")
