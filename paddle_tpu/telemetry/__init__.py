"""paddle_tpu.telemetry — always-on runtime metrics and spans.

proglint (PR 1) made the IR visible *before* tracing; this package
makes the runtime visible *while it runs*: the executor's compile vs
cache-hit split, feed-put and fetch-readback time, reader queue
depth/starvation, inference latency, and device-memory watermarks all
land in one process-global registry, and host spans + device op times
land on one Chrome-trace timeline.

Enablement
----------
Off by default. `PADDLE_TPU_TELEMETRY=1` (or `enable()`) turns it on;
disabled mode is the contract the hot paths are built around: every
instrumented site is gated on one flag check, no metric is ever
registered, and `snapshot()` stays `{}` (pinned by
tests/test_bench_contract.py).

Surfaces
--------
- `snapshot()` — plain dict of every metric
- `prometheus_text()` — text exposition format
- `chrome_trace()` / `write_chrome_trace(path)` — trace-event JSON;
  `merge_device_ops(profiler.device_op_times(dir))` adds device time
- `flush()` — log a summary; with `PADDLE_TPU_TELEMETRY_DIR=<dir>`
  also write metrics.json / metrics.prom / trace.json there
- `fleet` — multi-rank layer: rank labels on every export, a per-rank
  snapshot spool, coordinator-side merge (FleetCollector), straggler
  detection, and multi-rank trace stitching (`tpustat --fleet`)
- `tools/tpustat.py` — CLI: run a benchmark model N steps and print
  the table

No jax / paddle_tpu imports at module level: the executor, readers,
and the native predictor all pull this in during package init.
"""
import json
import logging
import os

from . import registry as _registry
from . import spans as _spans
from . import memory as _memory
from . import fleet
from .registry import (Counter, Gauge, Histogram, counter, gauge,
                       histogram, prometheus_text,
                       DEFAULT_TIME_BUCKETS)
from .spans import (span, iter_spans, chrome_trace, write_chrome_trace,
                    merge_device_ops, SpanRecord, append_span, now_us,
                    instant_event)
from .memory import device_memory_supported, sample_device_memory

__all__ = ["enabled", "enable", "disable", "counter", "gauge",
           "histogram", "span", "snapshot", "prometheus_text",
           "chrome_trace", "write_chrome_trace", "merge_device_ops",
           "iter_spans", "sample_device_memory",
           "device_memory_supported", "reset", "flush", "fleet",
           "append_span", "now_us", "instant_event", "Counter",
           "Gauge", "Histogram", "SpanRecord", "DEFAULT_TIME_BUCKETS",
           "attribution", "slo", "reqtrace", "reqtrace_enabled",
           "reqtrace_enable", "reqtrace_disable", "memledger",
           "memledger_enabled", "memledger_enable",
           "memledger_disable"]


def __getattr__(name):
    # attribution/slo/reqtrace/memledger load lazily: the off-path
    # contract (bench pin) is that a disabled run never even imports
    # them
    if name in ("attribution", "slo", "reqtrace", "memledger"):
        import importlib
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")

_LOG = logging.getLogger("paddle_tpu.telemetry")


def _env_truthy(val):
    return (val or "").strip().lower() not in ("", "0", "false", "off",
                                               "no")


_ENABLED = _env_truthy(os.environ.get("PADDLE_TPU_TELEMETRY"))


def enabled():
    """One-flag gate every instrumented hot path checks first."""
    return _ENABLED


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


# span()/fleet consult the same flag without importing this module back
_spans._span_enabled = enabled
fleet._enabled = enabled


_REQTRACE = _env_truthy(os.environ.get("PADDLE_TPU_REQTRACE"))


def reqtrace_enabled():
    """Gate every request-tracing seam checks before touching the
    reqtrace module: a plain bool, so `PADDLE_TPU_REQTRACE` unset costs
    one flag check and provably never imports
    paddle_tpu.telemetry.reqtrace (pinned by test_bench_contract)."""
    return _REQTRACE


def reqtrace_enable():
    global _REQTRACE
    _REQTRACE = True


def reqtrace_disable():
    global _REQTRACE
    _REQTRACE = False


_MEMLEDGER = _env_truthy(os.environ.get("PADDLE_TPU_MEMLEDGER"))


def memledger_enabled():
    """Gate every device-memory attribution seam checks before touching
    the ledger: a plain bool, so `PADDLE_TPU_MEMLEDGER` unset costs one
    flag check and provably never imports
    paddle_tpu.telemetry.memledger (pinned by test_bench_contract)."""
    return _MEMLEDGER


def memledger_enable():
    global _MEMLEDGER
    _MEMLEDGER = True


def memledger_disable():
    global _MEMLEDGER
    _MEMLEDGER = False


def snapshot():
    """{metric_name: value} — counters/gauges as numbers, histograms as
    {count, sum, min, max, mean, buckets}. Empty when nothing was ever
    recorded (the disabled-mode contract). Once a fleet rank is known
    (parallel.fleet.init / telemetry.fleet.configure), a non-empty
    snapshot also carries "process.index"/"process.count"."""
    snap = _registry.snapshot()
    if snap:
        snap.update(fleet.process_meta())
    return snap


def reset():
    """Drop all metrics, spans, and merged device events (not the
    enabled flag). Used by tpustat to scope metrics to the steady-state
    loop, and by tests."""
    _registry.reset_metrics()
    _spans.clear_spans()
    # restart the MFU/goodput accumulation window too — but only if
    # attribution was ever loaded (importing it here would defeat the
    # lazy off-path contract)
    import sys
    attr = sys.modules.get(__name__ + ".attribution")
    if attr is not None:
        attr.reset_window()


def flush(log=True):
    """Final export: log a one-line summary and, when
    PADDLE_TPU_TELEMETRY_DIR is set, write metrics.json, metrics.prom,
    and trace.json there. Returns the snapshot (None when disabled) —
    Executor.close() calls this so a run's metrics outlive it.

    Fleet mode (a rank configured): every rank also writes its spool
    envelope (fleet.write_rank_snapshot); the single-artifact files are
    written by rank 0 only, so N ranks sharing one directory don't
    clobber each other's metrics.json."""
    if not _ENABLED:
        return None
    snap = snapshot()
    n_spans = len(iter_spans())
    if log:
        _LOG.info("telemetry flush: %d metrics, %d spans", len(snap),
                  n_spans)
    r = fleet.rank()
    out_dir = os.environ.get("PADDLE_TPU_TELEMETRY_DIR")
    if out_dir and r in (None, 0):
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "metrics.json"), "w") as f:
            json.dump(snap, f, indent=2, default=str)
        with open(os.path.join(out_dir, "metrics.prom"), "w") as f:
            f.write(prometheus_text())
        write_chrome_trace(os.path.join(out_dir, "trace.json"))
        # request-trace exemplars ride the same artifact directory —
        # but only if reqtrace was ever loaded (a sys.modules probe,
        # like reset() uses for attribution, keeps the off-path pure)
        import sys
        rt = sys.modules.get(__name__ + ".reqtrace")
        if rt is not None:
            with open(os.path.join(out_dir, "traces.json"), "w") as f:
                json.dump(rt.dump(), f, indent=2, default=str)
        # the memory ledger rides along the same way — only if it was
        # ever loaded (sys.modules probe keeps the off-path pure)
        ml = sys.modules.get(__name__ + ".memledger")
        if ml is not None:
            payload = ml.snapshot_report()
            payload["timeline"] = ml.get().timeline()
            rep = ml.last_report()
            if rep is not None:
                payload["last_report"] = rep.to_dict()
            with open(os.path.join(out_dir, "memory.json"), "w") as f:
                json.dump(payload, f, indent=2, default=str)
    if r is not None and fleet.spool_dir() is not None:
        try:
            fleet.write_rank_snapshot()
        except OSError as e:
            _LOG.warning("fleet spool flush failed: %s", e)
    return snap
