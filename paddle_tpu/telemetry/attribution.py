"""tpuscope attribution: runtime MFU / goodput / step-budget / recompiles.

The registry (PR 2) records *what happened* — step counts, wall-time
histograms, spans. This layer answers *how well*: it captures each
compile key's FLOPs once at compile time via XLA's own
``cost_analysis`` (the same source bench.py trusts for its offline MFU)
and folds step wall-time into live ``perf.mfu`` and
``perf.goodput.{examples,tokens}_per_s`` gauges, decomposes each step's
time budget from the spans the executor already emits, and — when a new
compile key shows up mid-run — diffs it field-by-field against its
nearest previously-seen neighbor to say exactly which component busted
the cache (the dynamic counterpart of proglint's static
``recompile-hazard`` pass).

Never imported on the telemetry-off path: the executor pulls this in
lazily, only under ``telemetry.enabled()``, and the bench contract pins
that a disabled run neither imports this module nor calls
``cost_analysis`` (tests/test_bench_contract.py).

No jax import at module level — jax is only touched inside functions
that already run with a live backend.
"""
import logging
import os
import threading
import time

from . import registry as _registry
from . import spans as _spans

__all__ = ["peak_flops", "instrument_compile", "on_step",
           "reset_window", "explain_recompile", "executor_ckey_fields",
           "pexe_ckey_fields", "step_budget", "compile_info",
           "BUDGET_CATEGORIES"]

_LOG = logging.getLogger("paddle_tpu.telemetry.attribution")

# Peak bf16 FLOP/s per chip by device kind (scaling-book table; the
# same anchors bench.py uses for its offline MFU, so the runtime and
# offline numbers are comparable by construction).
_PEAK_BF16 = (
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5litepod", 197e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12), ("v2", 45e12),
)

_lock = threading.Lock()
# compile key -> {"flops", "examples", "tokens"}; capture happens once
# per key at compile time, cache-hit steps only do a dict lookup
_info = {}
# the accumulation window behind the perf.* gauges; starts at the end
# of the first compile step (compile time is excluded, matching
# bench.py's warmup exclusion) and resets with telemetry.reset()
_win = {"t0": None, "flops": 0.0, "examples": 0, "tokens": 0,
        "steps": 0}
# one-shot capability probe: backends whose AOT lower/compile path
# fails (or lacks cost_analysis) are never retried
_aot_ok = True


def peak_flops(device=None):
    """Peak bf16 FLOP/s for `device` (default: jax.devices()[0]).
    PADDLE_TPU_PEAK_FLOPS overrides — required for a meaningful
    perf.mfu anywhere the table has no entry (CPU runs, new chips).
    Returns None when unknown: no peak, no MFU gauge."""
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            _LOG.warning("PADDLE_TPU_PEAK_FLOPS=%r is not a number",
                         env)
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:
            return None
    kind = getattr(device, "device_kind", "").lower()
    for tag, peak in _PEAK_BF16:
        if tag in kind:
            return peak
    if getattr(device, "platform", None) in ("tpu", "axon"):
        return 197e12  # conservative default: v5e
    return None


def _feed_shape_stats(feed_arrays):
    """(examples, tokens) per step from the feed dict: examples = the
    largest leading dim (the batch axis), tokens = the largest
    integer-typed feed's element count (token-id tensors are B*T int
    arrays; dense-only models fall back to examples)."""
    examples = 0
    tokens = 0
    for v in (feed_arrays or {}).values():
        shape = getattr(v, "shape", ())
        if shape:
            examples = max(examples, int(shape[0]))
        dt = str(getattr(v, "dtype", ""))
        if dt.startswith(("int", "uint")) and shape:
            size = 1
            for d in shape:
                size *= int(d)
            tokens = max(tokens, size)
    return examples, tokens or examples


class _AotFn:
    """AOT-compiled executable with the original jit fn as a safety
    net: a same-ckey call whose avals still mismatch (e.g. a scope
    buffer swapped for one of a different dtype) permanently falls
    back to the retrace-capable jit path instead of erroring."""
    __slots__ = ("compiled", "fallback", "dead")

    def __init__(self, compiled, fallback):
        self.compiled = compiled
        self.fallback = fallback
        self.dead = False

    def __call__(self, *args):
        if not self.dead:
            try:
                return self.compiled(*args)
            except TypeError as e:
                # aval mismatch is raised before any buffer is donated
                self.dead = True
                _registry.counter("perf.aot_fallbacks").inc()
                _LOG.warning("AOT executable rejected its inputs "
                             "(%s); falling back to jit", e)
        return self.fallback(*args)


def instrument_compile(jfn, args, ckey, feed_arrays, kind="executor"):
    """Compile-time capture: AOT-lower `jfn` for `args`, read the
    executable's cost_analysis FLOPs, register per-ckey attribution
    info, and return the compiled executable (wrapped in a jit
    fallback shim) so the capture costs no second compile — bench.py's
    ``_aot_compile`` pattern. Any failure downgrades to the plain jit
    fn and disarms further attempts (capability probe)."""
    global _aot_ok
    flops = None
    fn = jfn
    if _aot_ok:
        try:
            compiled = jfn.lower(*args).compile()
            try:
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                f = ca.get("flops")
                flops = float(f) if f and f > 0 else None
            except Exception:
                pass
            fn = _AotFn(compiled, jfn)
        except Exception as e:
            _aot_ok = False
            _LOG.info("backend does not support AOT cost capture "
                      "(%s: %s); perf.mfu will be unavailable",
                      type(e).__name__, e)
    examples, tokens = _feed_shape_stats(feed_arrays)
    with _lock:
        _info[ckey] = {"flops": flops, "examples": examples,
                       "tokens": tokens, "kind": kind}
    if flops:
        _registry.gauge("perf.flops_per_step").set(flops)
    return fn


def compile_info(ckey):
    with _lock:
        return dict(_info[ckey]) if ckey in _info else None


def on_step(ckey, dt, compile_run=False, feed_arrays=None):
    """Fold one completed step into the window and refresh the perf
    gauges. Compile steps only (re)anchor the window start — their
    wall time is compile, not throughput."""
    now = time.perf_counter()
    with _lock:
        info = _info.get(ckey)
        if info is None and feed_arrays is not None:
            # compiled before telemetry was enabled: no FLOPs on
            # record, but goodput can still be attributed
            examples, tokens = _feed_shape_stats(feed_arrays)
            info = _info[ckey] = {"flops": None, "examples": examples,
                                  "tokens": tokens, "kind": "late"}
        if compile_run:
            _win["t0"] = now
            return
        if _win["t0"] is None:
            _win["t0"] = now - dt
        _win["steps"] += 1
        if info:
            if info["flops"]:
                _win["flops"] += info["flops"]
            _win["examples"] += info["examples"]
            _win["tokens"] += info["tokens"]
        elapsed = now - _win["t0"]
        flops = _win["flops"]
        examples = _win["examples"]
        tokens = _win["tokens"]
    if elapsed <= 0:
        return
    _registry.gauge("perf.goodput.examples_per_s").set(
        examples / elapsed)
    _registry.gauge("perf.goodput.tokens_per_s").set(tokens / elapsed)
    if flops:
        peak = peak_flops()
        if peak:
            _registry.gauge("perf.mfu").set(flops / elapsed / peak)


def reset_window():
    """Restart the accumulation window (telemetry.reset() calls this
    when the module is loaded, so tpustat-style 'reset after warmup'
    scoping applies to the perf gauges too). Per-ckey compile info
    survives — FLOPs don't change when metrics are scoped."""
    with _lock:
        _win.update(t0=None, flops=0.0, examples=0, tokens=0, steps=0)


def _reset_for_tests():
    global _aot_ok
    reset_window()
    with _lock:
        _info.clear()
    _aot_ok = True


# ------------------------------------------------------- recompile explainer

_EXECUTOR_CKEY_NAMES = (
    "program_id", "program_version", "feed_signature", "fetch_names",
    "is_test", "seed", "fuse_optimizer_tail", "fuse_max_elems")
_PEXE_CKEY_NAMES = (
    "program_id", "program_version", "feed_signature", "fetch_names",
    "is_test", "fuse_optimizer_tail", "fuse_max_elems")

# ckey field -> component name: ONE vocabulary shared with meshlint's
# static recompile-hazard pass (telemetry/ckey_vocab.py), so the static
# warning and the runtime explanation lead with the same words —
# regression-tested by tests/test_meshlint.py
from .ckey_vocab import (COMPONENT as _COMPONENT,
                         diff_feed_signature as _diff_feed_signature,
                         fmt_field as _fmt_field)


def executor_ckey_fields(ckey):
    """Executor.run compile key -> named fields. The historical key is
    the 8-tuple; donate_state=False appends 'nodonate' (the only way
    the default key ever grows — bench-contract pin)."""
    d = dict(zip(_EXECUTOR_CKEY_NAMES, ckey))
    d["donate"] = "nodonate" not in ckey[8:]
    return d


def pexe_ckey_fields(ckey, policy_key=None, engine_key=None):
    """ParallelExecutor compile key -> named fields. The optional
    grad_sync/engine suffixes are positional in the tuple, so the call
    site passes what it knows; historical keys keep the interpretation
    they were recorded with."""
    d = dict(zip(_PEXE_CKEY_NAMES, ckey))
    d["grad_sync"] = policy_key
    d["engine"] = engine_key
    return d


def explain_recompile(kind, fields, seen_fields, step=None):
    """A NEW compile key arrived while others were already cached —
    explain why. Diffs `fields` against its nearest neighbor (the
    previously-seen key sharing the most fields) and emits
    `<kind>.recompile.explained` naming exactly which component busted
    the cache. Returns the explanation dict (Executor.last_recompile)."""
    if not seen_fields:
        return None
    names = list(fields)

    def matches(s):
        return sum(1 for k in names if s.get(k) == fields.get(k))

    best = max(seen_fields, key=matches)
    changed = [k for k in names if best.get(k) != fields.get(k)]
    details = [_fmt_field(k, best.get(k), fields.get(k))
               for k in changed]
    components = sorted({_COMPONENT.get(k, k) for k in changed})
    detail = "; ".join(details) if details else \
        "no field differs from the nearest neighbor (hash-only miss)"
    out = {"kind": kind, "step": step, "changed": changed,
           "components": components, "detail": detail,
           "matched_fields": matches(best),
           "seen_keys": len(seen_fields)}
    _registry.counter(f"{kind}.recompile.count").inc()
    _spans.instant_event(
        f"{kind}.recompile.explained", step=step,
        changed=",".join(changed), detail=detail[:400])
    _LOG.warning("%s recompile at step %s: cache busted by %s — %s",
                 kind, step, ", ".join(components) or "nothing visible",
                 detail)
    return out


# ------------------------------------------------------------ step budgets

# span name -> budget category (the per-step time decomposition).
# "device compute" lives inside dispatch on synchronous backends (the
# donated CPU execution runs inline on the dispatching thread) and
# inside stall under async_steps (the deferred block_until_ready).
_BUDGET_SPANS = {
    "executor.feed_put": "feed_put",
    "executor.step": "dispatch",
    "executor.pending_wait": "stall",
    "executor.fetch_readback": "readback",
    "executor.finite_check": "check",
}
BUDGET_CATEGORIES = ("feed_put", "dispatch", "stall", "readback",
                     "check")


def step_budget(spans=None):
    """Roll the executor's spans up into a per-step time budget.
    Grouping is by each span's own `step` arg, so deferred readbacks
    and finite checks under async_steps land on the step that
    DISPATCHED them, not the step whose run() call materialized them.
    Returns {"steps": {step: {cat_ms}}, "totals": {cat_ms},
    "compile_steps": [...]}."""
    spans = _spans.iter_spans() if spans is None else spans
    steps = {}
    totals = {c: 0.0 for c in BUDGET_CATEGORIES}
    compile_steps = []
    for s in spans:
        cat = _BUDGET_SPANS.get(s.name)
        if cat is None:
            continue
        args = s.args or {}
        step = args.get("step")
        if step is None:
            continue
        ms = s.dur_us / 1e3
        steps.setdefault(step, dict.fromkeys(BUDGET_CATEGORIES, 0.0))
        steps[step][cat] += ms
        totals[cat] += ms
        if s.name == "executor.step" and args.get("compile_run"):
            compile_steps.append(step)
    return {"steps": steps, "totals": totals,
            "compile_steps": sorted(compile_steps)}
