"""Fleet observability: per-rank telemetry spool + coordinator merge.

PR 2's registry and spans are strictly single-process; a multihost run
(fleet.init → jax.distributed.initialize) was a black box — no rank
labels, no cross-host metric aggregation, no way to tell a straggler
host from a slow program. This module adds the missing layer, in the
spirit of the reference's per-device SSA-graph timers and pserver logs:

- `configure(rank, world, spool_dir)` tags every metric/span this
  process exports with its rank via ONE registry-level default-labels
  hook (metric names stay identical across ranks — that is what makes
  the merge line up). `parallel.fleet.init` calls `configure_from_jax`
  so real multihost runs get this for free.
- `write_rank_snapshot()` flushes an atomic (tmp + rename, so a reader
  never sees a torn file) JSON envelope — metrics WITH kinds, recent
  spans, clock info — to a spool directory; `on_step()` drives a
  periodic flush from the instrumented step loops (flush-on-step: no
  background thread to leak).
- `FleetCollector` merges the spool coordinator-side: counters sum,
  gauges keep per-rank values plus min/max, histograms merge
  bucket-wise (same edges required). Envelopes are keyed by rank, so
  re-merging the same file is idempotent.
- `detect_stragglers` flags ranks whose mean step wall-time sits more
  than k·MAD above the fleet median (small fleets, n<4 or MAD=0, fall
  back to a 1.5x-median ratio test), publishing `fleet.straggler.*`
  gauges and a tpudoctor-style hint naming the slow host.
- `stitch_traces` merges per-rank span dumps into one Chrome trace —
  one `pid` per rank, clock offsets aligned on the shared barrier
  marker (`mark_clock`, stamped by `parallel.fleet.barrier_all`), with
  a wall-clock fallback when no marker exists.

Env knobs: PADDLE_TPU_FLEET_RANK / _WORLD (configure without jax),
PADDLE_TPU_FLEET_DIR (spool; defaults to $PADDLE_TPU_TELEMETRY_DIR/
fleet once a rank is configured), PADDLE_TPU_FLEET_FLUSH_S (periodic
flush interval, default 30, 0 disables).

Everything is inert until `configure()` (or the env) names a rank, and
costs nothing at all while telemetry is disabled — the single-process
zero-cost contract of PR 2 is untouched.

No jax / paddle_tpu imports at module level (same rule as the rest of
the telemetry package); jax and distributed.helper are pulled in
lazily and best-effort.
"""
import glob
import json
import math
import os
import statistics
import threading
import time

from . import registry as _registry
from . import spans as _spans

__all__ = ["configure", "configure_from_jax", "configured", "rank",
           "world", "spool_dir", "process_meta", "mark_clock",
           "on_step", "write_rank_snapshot", "build_envelope",
           "FleetCollector", "detect_stragglers", "stitch_traces",
           "merge_histograms", "SCHEMA"]

SCHEMA = "paddle_tpu.fleet.snapshot.v1"
REPORT_SCHEMA = "paddle_tpu.fleet.report.v1"
CLOCK_MARKER = "fleet.clock_marker"

ENV_RANK = "PADDLE_TPU_FLEET_RANK"
ENV_WORLD = "PADDLE_TPU_FLEET_WORLD"
ENV_SPOOL = "PADDLE_TPU_FLEET_DIR"
ENV_FLUSH_S = "PADDLE_TPU_FLEET_FLUSH_S"

_MAX_SPANS_PER_SNAPSHOT = 20_000
_DEFAULT_FLUSH_S = 30.0


_spool_retry_policy = None


def _spool_policy():
    """Lazily built RetryPolicy for spool writes (resilience imports
    telemetry, so the policy can't be built at this module's import
    time)."""
    global _spool_retry_policy
    if _spool_retry_policy is None:
        from ..resilience.retry import RetryPolicy
        _spool_retry_policy = RetryPolicy(max_attempts=3,
                                          base_delay_s=0.05,
                                          max_delay_s=0.5,
                                          deadline_s=5.0)
    return _spool_retry_policy
_DEFAULT_K_MAD = 3.0
_RATIO_FALLBACK = 1.5

_lock = threading.Lock()
_state = {"rank": None, "world": None, "spool_dir": None,
          "marker_us": None, "marker_id": 0, "last_flush": 0.0}
_env_checked = False


def _enabled():
    # rebound by telemetry/__init__ to the real flag accessor (same
    # pattern spans.py uses); the default keeps this module importable
    # standalone
    return True


# ---------------------------------------------------------------- identity

def configure(rank, world=None, spool_dir=None):
    """Name this process's rank (and optionally fleet size + spool).
    Installs the registry default-labels hook so every metric exported
    from here on carries the process index — no call-site churn."""
    with _lock:
        _state["rank"] = int(rank)
        if world is not None:
            _state["world"] = int(world)
        if spool_dir is not None:
            _state["spool_dir"] = spool_dir
    labels = {"process_index": int(rank)}
    if _state["world"] is not None:
        labels["process_count"] = _state["world"]
    _registry.set_default_labels(labels)


def configure_from_jax():
    """configure() from the live jax.distributed world — called by
    parallel.fleet.init once the gang exists (jax is certainly
    importable there)."""
    import jax
    configure(jax.process_index(), jax.process_count())


def _maybe_env_configure():
    """Lazy one-shot env configuration (PADDLE_TPU_FLEET_RANK/_WORLD)
    so subprocess workers don't need an API call before the first
    instrumented step."""
    global _env_checked
    if _env_checked:
        return _state["rank"] is not None
    _env_checked = True
    r = os.environ.get(ENV_RANK)
    if r is not None and r.strip() != "":
        w = os.environ.get(ENV_WORLD)
        configure(int(r), int(w) if w else None,
                  os.environ.get(ENV_SPOOL))
    return _state["rank"] is not None


def configured():
    return _state["rank"] is not None


def rank():
    return _state["rank"]


def world():
    return _state["world"]


def spool_dir():
    """Resolved spool directory: explicit configure() > env > the
    `fleet/` subdir of PADDLE_TPU_TELEMETRY_DIR (only once a rank is
    configured — single-process runs never grow a spool)."""
    if _state["spool_dir"]:
        return _state["spool_dir"]
    d = os.environ.get(ENV_SPOOL)
    if d:
        return d
    base = os.environ.get("PADDLE_TPU_TELEMETRY_DIR")
    if base and _state["rank"] is not None:
        return os.path.join(base, "fleet")
    return None


def process_meta():
    """{"process.index": r, "process.count": w} once a rank is known,
    else {} — merged into telemetry.snapshot() output."""
    r = _state["rank"]
    if r is None:
        return {}
    meta = {"process.index": r}
    if _state["world"] is not None:
        meta["process.count"] = _state["world"]
    return meta


def _reset_for_tests():
    global _env_checked
    with _lock:
        _state.update(rank=None, world=None, spool_dir=None,
                      marker_us=None, marker_id=0, last_flush=0.0)
    _env_checked = False
    _registry.set_default_labels({})


# ------------------------------------------------------------ clock marker

def mark_clock():
    """Stamp a clock-alignment marker on this rank's span timeline.
    Called right after a fleet-wide barrier returns (barrier_all), the
    markers of all ranks correspond to (nearly) the same true instant —
    stitch_traces subtracts the per-rank marker timestamps to put every
    rank on one clock. Returns the local timestamp (µs)."""
    ts = _spans.now_us()
    with _lock:
        _state["marker_us"] = ts
        _state["marker_id"] += 1
        mid = _state["marker_id"]
    _spans.append_span(CLOCK_MARKER, cat="fleet", ts_us=ts, dur_us=0.0,
                       tid="fleet", args={"marker": mid})
    return ts


# -------------------------------------------------------------- rank spool

def _flush_interval():
    raw = os.environ.get(ENV_FLUSH_S)
    if raw is None or raw.strip() == "":
        return _DEFAULT_FLUSH_S
    try:
        return float(raw)
    except ValueError:
        return _DEFAULT_FLUSH_S


def on_step(dt=None):
    """Per-step hook from the instrumented step loops (executor / pexe /
    pipeline); callers gate on telemetry.enabled() so the disabled path
    never reaches here. Cheap no-op until a rank is configured; with a
    spool dir it drives the periodic rank-snapshot flush."""
    if _state["rank"] is None and not _maybe_env_configure():
        return
    interval = _flush_interval()
    if interval <= 0:
        return
    spool = spool_dir()
    if spool is None:
        return
    now = time.monotonic()
    if now - _state["last_flush"] < interval:
        return
    # stamp before writing: a persistently failing spool must not turn
    # into a write attempt on every step
    with _lock:
        _state["last_flush"] = now
    try:
        write_rank_snapshot()
    except OSError:
        pass
    except RuntimeError as e:
        # retry engine exhausted on a persistently failing spool: the
        # heartbeat is lost but the step loop must not die for it
        from ..resilience.retry import RetryError
        if not isinstance(e, RetryError):
            raise


def _host_info():
    """Best-effort host identity for the envelope — lets the straggler
    hint name the slow HOST, not just the rank number."""
    try:
        from ..distributed.helper import MPIHelper
        return MPIHelper().describe()
    except Exception:
        try:
            import socket
            return {"hostname": socket.gethostname()}
        except Exception:
            return {}


def build_envelope(rank_override=None):
    """The per-rank snapshot envelope: metrics WITH kinds (merge
    semantics need them), recent spans, rank labels, and clock info for
    stitching."""
    r = _state["rank"] if rank_override is None else int(rank_override)
    spans = _spans.iter_spans()[-_MAX_SPANS_PER_SNAPSHOT:]
    return {
        "schema": SCHEMA,
        "rank": 0 if r is None else r,
        "process_count": _state["world"],
        "labels": _registry.default_labels(),
        "host": _host_info(),
        "flush_unix_us": time.time_ns() // 1000,
        "flush_perf_us": _spans.now_us(),
        "clock_marker_us": _state["marker_us"],
        "metrics": _registry.snapshot_with_kinds(),
        "spans": [list(s) for s in spans],
    }


def write_rank_snapshot(spool=None, rank_override=None):
    """Atomically write this rank's envelope to the spool as
    rank<NNNNN>.snap.json (overwrite-in-place: the newest snapshot per
    rank is the one that counts, which also makes re-merges of the same
    spool idempotent). Returns the path."""
    spool = spool or spool_dir()
    if spool is None:
        raise ValueError(
            "no spool directory: pass one, configure(spool_dir=...), or "
            f"set {ENV_SPOOL} / PADDLE_TPU_TELEMETRY_DIR")
    env = build_envelope(rank_override)
    os.makedirs(spool, exist_ok=True)
    path = os.path.join(spool, f"rank{env['rank']:05d}.snap.json")
    # chaos: the fleet.spool injection point — a fired spool_drop
    # swallows this flush, the spool goes stale, and the liveness
    # detector (resilience.liveness) must be what notices
    from ..resilience import chaos as _chaos
    if _chaos.armed() and _chaos.hit("fleet.spool",
                                     rank=env["rank"]) is not None:
        return None

    def _put():
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(env, f, default=str)
        os.replace(tmp, path)

    # spool I/O rides shared filesystems that flake; retry briefly
    # rather than losing the heartbeat to one EAGAIN
    from ..resilience import retry as _retry
    _retry.call(_put, policy=_spool_policy(), name="fleet.spool")
    with _lock:
        _state["last_flush"] = time.monotonic()
    return path


# --------------------------------------------------------- merge semantics

def _norm_buckets(buckets):
    """JSON round-trips float dict keys to strings ('0.1'); normalize
    back to floats (plus the '+Inf' sentinel) so bucket-wise merges of
    spooled and in-memory histograms line up."""
    out = {}
    for k, v in buckets.items():
        if isinstance(k, str) and k.strip().lstrip("+") in ("Inf",
                                                            "Infinity",
                                                            "inf"):
            out["+Inf"] = int(v)
        else:
            out[float(k)] = int(v)
    return out


def _norm_hist(h):
    out = dict(h)
    out["buckets"] = _norm_buckets(h.get("buckets", {}))
    out["count"] = int(h.get("count", 0))
    out["sum"] = float(h.get("sum", 0.0))
    return out


def merge_histograms(a, b, name=""):
    """Bucket-wise merge of two histogram snapshot dicts. Edges must
    match — the same instrumented code runs on every rank, so a
    mismatch means two different metrics collided on one name."""
    a, b = _norm_hist(a), _norm_hist(b)
    ea = sorted(k for k in a["buckets"] if k != "+Inf")
    eb = sorted(k for k in b["buckets"] if k != "+Inf")
    if ea != eb:
        raise ValueError(
            f"histogram {name or '?'}: bucket edges differ across "
            f"ranks ({ea} vs {eb}); refusing a lossy merge")
    buckets = {k: a["buckets"].get(k, 0) + b["buckets"].get(k, 0)
               for k in a["buckets"]}
    out = {"count": a["count"] + b["count"],
           "sum": a["sum"] + b["sum"], "buckets": buckets}
    mins = [x["min"] for x in (a, b) if "min" in x]
    maxs = [x["max"] for x in (a, b) if "max" in x]
    if out["count"]:
        if mins:
            out["min"] = min(mins)
        if maxs:
            out["max"] = max(maxs)
        out["mean"] = out["sum"] / out["count"]
    return out


def _rank_gauge(metrics, name):
    """Scalar value of one {"kind","value"} envelope entry, or None."""
    ent = metrics.get(name)
    return ent["value"] if ent else None


def detect_stragglers(per_rank_seconds, k=_DEFAULT_K_MAD):
    """Flag ranks whose step wall-time sits > k·MAD above the fleet
    median. MAD is robust to the outliers we're hunting, but degenerates
    for tiny fleets (n<4) and perfectly uniform fleets (MAD=0) — both
    fall back to a 1.5x-median ratio test. Publishes fleet.straggler.*
    gauges when telemetry is enabled and returns the full analysis with
    a tpudoctor-style hint."""
    if not per_rank_seconds:
        return {"verdict": "no step timing data", "flagged": [],
                "method": "none"}
    ranks = sorted(per_rank_seconds)
    vals = [float(per_rank_seconds[r]) for r in ranks]
    med = statistics.median(vals)
    mad = statistics.median([abs(v - med) for v in vals])
    if len(vals) >= 4 and mad > 0:
        method = "mad"
        threshold = med + k * mad
    else:
        method = "ratio"
        threshold = _RATIO_FALLBACK * med
    flagged = [r for r, v in zip(ranks, vals) if v > threshold]
    worst = max(ranks, key=lambda r: per_rank_seconds[r])
    worst_v = float(per_rank_seconds[worst])
    skew = (worst_v / med) if med > 0 else math.inf
    out = {"method": method, "k": k, "median_seconds": med,
           "mad_seconds": mad, "threshold_seconds": threshold,
           "per_rank_seconds": {str(r): float(per_rank_seconds[r])
                                for r in ranks},
           "flagged": flagged, "worst_rank": worst,
           "skew_ratio": skew}
    if flagged:
        out["verdict"] = ("straggler: rank" +
                          ("s " if len(flagged) > 1 else " ") +
                          ", ".join(str(r) for r in flagged))
        out["hint"] = (
            f"rank {worst} mean step {worst_v * 1e3:.1f} ms is "
            f"{skew:.1f}x the fleet median {med * 1e3:.1f} ms "
            f"({method} threshold {threshold * 1e3:.1f} ms). A slow "
            "rank serializes every collective in the step — check that "
            "host's input pipeline (reader.starved_polls), shared-"
            "tenant CPU load, thermal throttling, or NIC/DCN link "
            "before blaming the program.")
    else:
        out["verdict"] = "balanced"
    if _enabled():
        _registry.gauge("fleet.straggler.count").set(len(flagged))
        _registry.gauge("fleet.straggler.threshold_seconds").set(
            threshold)
        _registry.gauge("fleet.straggler.worst_skew").set(
            0.0 if math.isinf(skew) else skew)
    return out


# ------------------------------------------------------------- trace stitch

def stitch_traces(envelopes, align="auto"):
    """Merge per-rank envelopes into ONE Chrome trace: every rank
    becomes a `pid` (named after its host), and per-rank clocks are
    aligned by subtracting each rank's barrier-marker timestamp
    (`align="marker"`). With no marker on every rank, falls back to the
    flush wall-clock (each rank's perf timeline is pinned to unix time
    at flush; coarser — NTP-level — but always available). Rank 0's
    timeline is the reference frame."""
    envs = sorted(envelopes, key=lambda e: int(e.get("rank", 0)))
    if not envs:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "fleetAlignment": "empty"}
    have_marker = all(e.get("clock_marker_us") is not None for e in envs)
    have_wall = all(e.get("flush_unix_us") is not None
                    and e.get("flush_perf_us") is not None for e in envs)
    if align == "marker" or (align == "auto" and have_marker):
        if not have_marker:
            raise ValueError("align='marker' but a rank has no "
                             "clock marker (call fleet.mark_clock / "
                             "barrier_all on every rank)")
        base = float(envs[0]["clock_marker_us"])
        offsets = {int(e["rank"]): base - float(e["clock_marker_us"])
                   for e in envs}
        method = "marker"
    elif align in ("auto", "wall") and have_wall:
        # unix time at each rank's perf-timeline origin; rebase on rank0
        origin = {int(e["rank"]):
                  float(e["flush_unix_us"]) - float(e["flush_perf_us"])
                  for e in envs}
        base = origin[int(envs[0]["rank"])]
        offsets = {r: o - base for r, o in origin.items()}
        method = "wall"
    else:
        offsets = {int(e["rank"]): 0.0 for e in envs}
        method = "none"

    events = []
    for e in envs:
        pid = int(e.get("rank", 0))
        off = offsets[pid]
        host = (e.get("host") or {}).get("hostname")
        label = f"rank {pid}" + (f" ({host})" if host else "")
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": pid, "args": {"sort_index": pid}})
        for s in e.get("spans", []):
            name, cat, ts, dur, tid, depth, args = s
            ev_args = dict(args) if args else {}
            ev_args["depth"] = depth
            ev_args["rank"] = pid
            events.append({"name": name, "cat": cat, "ph": "X",
                           "ts": float(ts) + off, "dur": float(dur),
                           "pid": pid, "tid": tid, "args": ev_args})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "fleetAlignment": method}


# ---------------------------------------------------------------- collector

class FleetCollector:
    """Coordinator-side merge of a rank spool. Envelopes are keyed by
    rank — adding the same file (or the same rank's newer snapshot)
    again replaces the previous contribution, so re-merges are
    idempotent, and a periodic spool converges to the latest state."""

    def __init__(self, k_mad=_DEFAULT_K_MAD):
        self.k_mad = k_mad
        self._ranks = {}        # rank -> envelope

    # -- ingest --------------------------------------------------------
    def add_snapshot(self, envelope):
        if envelope.get("schema") != SCHEMA:
            raise ValueError(
                f"not a fleet snapshot (schema="
                f"{envelope.get('schema')!r}, want {SCHEMA!r})")
        self._ranks[int(envelope["rank"])] = envelope
        return self

    def add_file(self, path):
        with open(path) as f:
            return self.add_snapshot(json.load(f))

    def collect(self, spool):
        paths = sorted(glob.glob(os.path.join(spool, "rank*.snap.json")))
        if not paths:
            raise FileNotFoundError(
                f"no rank*.snap.json files in {spool!r}")
        for p in paths:
            self.add_file(p)
        return self

    @property
    def ranks(self):
        return sorted(self._ranks)

    def envelope(self, rank):
        return self._ranks[rank]

    # -- merge ---------------------------------------------------------
    def merged_metrics(self):
        """{name: {"kind": ..., ...}}: counters sum into "value";
        gauges keep {"per_rank", "min", "max"}; histograms merge
        bucket-wise into "value"."""
        merged = {}
        for r in self.ranks:
            for name, ent in self._ranks[r].get("metrics", {}).items():
                kind, val = ent["kind"], ent["value"]
                slot = merged.get(name)
                if slot is None:
                    slot = merged[name] = {"kind": kind}
                    if kind == "counter":
                        slot["value"] = 0
                    elif kind == "gauge":
                        slot["per_rank"] = {}
                elif slot["kind"] != kind:
                    raise ValueError(
                        f"metric {name!r} is a {slot['kind']} on one "
                        f"rank and a {kind} on rank {r}")
                if kind == "counter":
                    slot["value"] += val
                elif kind == "gauge":
                    slot["per_rank"][str(r)] = val
                    slot["min"] = min(val, slot.get("min", val))
                    slot["max"] = max(val, slot.get("max", val))
                else:
                    slot["value"] = (_norm_hist(val)
                                     if "value" not in slot else
                                     merge_histograms(slot["value"],
                                                      val, name))
        return merged

    # -- derived views -------------------------------------------------
    _STEP_HISTS = ("executor.step_seconds", "pexe.step_seconds",
                   "pipeline.step_seconds")

    def _rank_step_hist(self, r):
        m = self._ranks[r].get("metrics", {})
        for cand in self._STEP_HISTS:
            if cand in m and m[cand]["kind"] == "histogram":
                return _norm_hist(m[cand]["value"])
        return None

    def per_rank_step_seconds(self):
        """{rank: mean step wall-time} from whichever step histogram
        each rank recorded (plain executor, ParallelExecutor, or
        PipelineTrainer)."""
        out = {}
        for r in self.ranks:
            h = self._rank_step_hist(r)
            if h and h.get("count"):
                out[r] = h["sum"] / h["count"]
        return out

    def straggler_report(self, k=None):
        return detect_stragglers(self.per_rank_step_seconds(),
                                 k=self.k_mad if k is None else k)

    def stitched_trace(self, align="auto"):
        return stitch_traces(self._ranks.values(), align=align)

    def report(self):
        """The one-command fleet view tpustat --fleet renders: per-rank
        step time / collective volume / bubble fraction / MFU+goodput,
        merged metrics, collective totals, and the straggler verdict."""
        merged = self.merged_metrics()
        per_rank = {}
        for r in self.ranks:
            env = self._ranks[r]
            m = env.get("metrics", {})
            h = self._rank_step_hist(r)
            calls = sum(int(e["value"]) for n, e in m.items()
                        if n.startswith("collective.")
                        and n.endswith(".count"))
            nbytes = sum(int(e["value"]) for n, e in m.items()
                         if n.startswith("collective.")
                         and n.endswith(".bytes"))
            coll_us = sum(float(s[3]) for s in env.get("spans", [])
                          if s[1] == "collective")
            bubble = m.get("pipeline.bubble_fraction")
            gs_raw = m.get("gradsync.raw_bytes")
            gs_wire = m.get("gradsync.wire_bytes")
            gs_ratio = m.get("gradsync.compression_ratio")
            # sharded-embedding engine (parallel/sparse.py): per-table
            # embed.<t>.{rows,unique_ratio,exchange_bytes} gauges →
            # one row/ratio/bytes rollup per rank + per-table detail
            embed_tables = {}
            for name, ent in m.items():
                if not name.startswith("embed."):
                    continue
                tname, _, what = name[len("embed."):].rpartition(".")
                if tname and what in ("rows", "unique_ratio",
                                      "exchange_bytes", "overflow"):
                    embed_tables.setdefault(tname, {})[what] = \
                        ent["value"]
            ratios = [d["unique_ratio"] for d in embed_tables.values()
                      if "unique_ratio" in d]
            # serving farm (serving/farm): per-replica
            # serving.replica.<i>.{slots_in_use,queue_depth,...}
            # gauges → a replicas table per rank + a served-tokens
            # rollup (the tpustat --fleet replica columns)
            serving_replicas = {}
            for name, ent in m.items():
                if not name.startswith("serving.replica."):
                    continue
                idx, _, what = \
                    name[len("serving.replica."):].partition(".")
                if idx and what:
                    serving_replicas.setdefault(idx, {})[what] = \
                        ent["value"]
            # guard tier (serving/guard): group-level serving.guard.*
            # counters/gauges → one flat dict per rank (ejections,
            # hedges, brownout, p99_ms, ... — the tpustat guard line);
            # per-replica guard_state rides serving_replicas above
            serving_guard = {}
            for name, ent in m.items():
                if name.startswith("serving.guard.") \
                        and ent.get("kind") != "histogram":
                    serving_guard[name[len("serving.guard."):]] = \
                        ent["value"]
            # autoscaler (serving/scale): scale.* controller gauges →
            # one flat dict per rank (target/live replicas, last
            # decision code, cooldown — the tpustat scale line)
            serving_scale = {}
            for name, ent in m.items():
                if name.startswith("scale.") \
                        and ent.get("kind") != "histogram":
                    serving_scale[name[len("scale."):]] = \
                        ent["value"]
            # request-trace exemplars (telemetry/reqtrace): the
            # serving.trace.* gauges each rank's trace_end publishes —
            # seen/kept/stored plus trigger.<name> counts (the tpustat
            # traces line). Gauges, so a re-merged spool stays stable.
            serving_traces = {}
            for name, ent in m.items():
                if name.startswith("serving.trace.") \
                        and ent.get("kind") != "histogram":
                    serving_traces[name[len("serving.trace."):]] = \
                        ent["value"]
            # device-memory ledger (telemetry/memledger): memledger.*
            # gauges (total/peak + bytes.<category>) plus the raw
            # device.<platform:id>.* watermarks → one memory dict per
            # rank (the tpustat hbm/peak columns)
            memory = {}
            for name, ent in m.items():
                if name.startswith("memledger.") \
                        and ent.get("kind") != "histogram":
                    memory[name[len("memledger."):]] = ent["value"]
            dev_in_use = [ent["value"] for name, ent in m.items()
                          if name.startswith("device.")
                          and name.endswith(".bytes_in_use")]
            dev_peak = [ent["value"] for name, ent in m.items()
                        if name.startswith("device.")
                        and name.endswith(".peak_bytes_in_use")]
            per_rank[str(r)] = {
                "steps": h["count"] if h else 0,
                "step_seconds_mean": (h["sum"] / h["count"])
                if h and h.get("count") else None,
                "step_seconds_max": h.get("max") if h else None,
                "collective_calls": calls,
                "collective_bytes": nbytes,
                "collective_host_us": coll_us,
                "bubble_fraction": bubble["value"] if bubble else None,
                # gradient-sync policy layer (parallel/gradsync.py):
                # raw grad bytes vs what the policy put on the wire
                "gradsync_raw_bytes": gs_raw["value"] if gs_raw else 0,
                "gradsync_wire_bytes": gs_wire["value"] if gs_wire
                else 0,
                "gradsync_ratio": gs_ratio["value"] if gs_ratio
                else None,
                "embed_rows": sum(int(d.get("rows", 0))
                                  for d in embed_tables.values()),
                "embed_unique_ratio": (sum(ratios) / len(ratios))
                if ratios else None,
                "embed_exchange_bytes": sum(
                    int(d.get("exchange_bytes", 0))
                    for d in embed_tables.values()),
                "embed_tables": embed_tables,
                "serving_replicas": serving_replicas,
                "serving_guard": serving_guard,
                "serving_scale": serving_scale,
                "serving_traces": serving_traces,
                "serving_tokens_total": sum(
                    int(d.get("tokens_total", 0))
                    for d in serving_replicas.values()),
                "memory": memory,
                # rank HBM truth for the fleet columns: ledger bytes
                # when the rank ran one, allocator watermarks when the
                # backend reports them, whichever is larger
                "hbm_bytes": max(
                    [int(memory.get("total_bytes", 0))]
                    + [int(v) for v in dev_in_use]) or None,
                "hbm_peak_bytes": max(
                    [int(memory.get("peak_bytes", 0))]
                    + [int(v) for v in dev_peak]) or None,
                # tpuscope attribution gauges, when the rank ran with
                # the attribution layer live
                "mfu": _rank_gauge(m, "perf.mfu"),
                "goodput_examples_per_s": _rank_gauge(
                    m, "perf.goodput.examples_per_s"),
                "goodput_tokens_per_s": _rank_gauge(
                    m, "perf.goodput.tokens_per_s"),
                "hostname": (env.get("host") or {}).get("hostname"),
                "labels": env.get("labels", {}),
            }
        collectives = {}
        for name, ent in merged.items():
            if name.startswith("collective.") and ent["kind"] == "counter":
                op, _, what = name[len("collective."):].rpartition(".")
                if op:
                    collectives.setdefault(op, {})[what] = ent["value"]
        return {
            "schema": REPORT_SCHEMA,
            "ranks": self.ranks,
            "process_count": max(
                [e.get("process_count") or 0
                 for e in self._ranks.values()] + [len(self._ranks)]),
            "per_rank": per_rank,
            "merged": merged,
            "collectives": collectives,
            "straggler": self.straggler_report(),
        }
