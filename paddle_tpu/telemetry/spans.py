"""Host-side span tracer with Chrome trace-event export.

Spans nest (a thread-local depth counter tags each record) and land in
a bounded process-global ring; `chrome_trace()` renders them as
complete-duration ("X") events that load directly in chrome://tracing
or Perfetto. Device op durations from `profiler.device_op_times()`
merge onto the same timeline via `merge_device_ops` — the xplane
decode yields durations only, so device events are laid out
back-to-back on their own synthetic track starting at the host
timeline origin.

Timestamps are perf_counter_ns relative to this module's import, in
microseconds (the trace-event format's native unit).
"""
import collections
import json
import os
import threading
import time

__all__ = ["span", "iter_spans", "clear_spans", "chrome_trace",
           "write_chrome_trace", "merge_device_ops", "SpanRecord",
           "now_us", "append_span", "instant_event", "counter_event"]

_EPOCH_NS = time.perf_counter_ns()
_MAX_SPANS = 200_000

SpanRecord = collections.namedtuple(
    "SpanRecord", ["name", "cat", "ts_us", "dur_us", "tid", "depth",
                   "args"])

_spans = collections.deque(maxlen=_MAX_SPANS)
_device_events = []          # laid-out events from merge_device_ops
_lock = threading.Lock()
_tls = threading.local()


def _now_us():
    return (time.perf_counter_ns() - _EPOCH_NS) / 1e3


def now_us():
    """Current timestamp on THIS process's span timeline (µs since
    module import). Fleet clock markers are stamped with this so
    per-rank timelines can be offset-aligned when stitched."""
    return _now_us()


def append_span(name, cat="host", ts_us=None, dur_us=0.0, tid=None,
                depth=0, args=None):
    """Record a pre-built span (no timing context) — used for synthetic
    timeline tracks (fleet clock markers, pipeline schedule cells).
    No-op when telemetry is disabled."""
    if not _span_enabled():
        return None
    rec = SpanRecord(name, cat,
                     _now_us() if ts_us is None else float(ts_us),
                     float(dur_us),
                     threading.get_ident() if tid is None else tid,
                     depth, args or None)
    with _lock:
        _spans.append(rec)
    return rec


def instant_event(name, cat="instant", **args):
    """Zero-duration marker (recompile explained, decode admit/retire)
    rendered as a Chrome instant ("i") event — a vertical tick on the
    timeline rather than a bar. No-op when telemetry is disabled."""
    return append_span(name, cat=cat, dur_us=0.0, args=args or None)


def counter_event(name, values, ts_us=None, track="memory"):
    """Sampled counter values (per-step HBM bytes by ledger category)
    rendered as a Chrome counter ("C") event — a stacked area track in
    Perfetto. `values` is {series_name: number}. No-op when telemetry
    is disabled."""
    return append_span(name, cat="counter", ts_us=ts_us, dur_us=0.0,
                       tid=track, args=dict(values))


class _Span:
    __slots__ = ("name", "cat", "args", "_t0")

    def __init__(self, name, cat, args):
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        _tls.depth = depth = getattr(_tls, "depth", 0)
        _tls.depth = depth + 1
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc):
        t1 = _now_us()
        _tls.depth -= 1
        rec = SpanRecord(self.name, self.cat, self._t0, t1 - self._t0,
                         threading.get_ident(), _tls.depth,
                         self.args or None)
        with _lock:
            _spans.append(rec)
        return False


class _NullSpan:
    """Singleton no-op context manager for the disabled path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def _span_enabled():
    # rebound by telemetry/__init__ to the real flag accessor; the
    # default keeps this module importable standalone
    return True


def span(name, cat="host", **args):
    """Context manager timing a host-side region. No-op (a shared
    singleton, no allocation) when telemetry is disabled."""
    if not _span_enabled():
        return _NULL_SPAN
    return _Span(name, cat, args)


def iter_spans():
    with _lock:
        return list(_spans)


def clear_spans():
    with _lock:
        _spans.clear()
        del _device_events[:]


def merge_device_ops(op_times, origin_us=None, track="device ops",
                     scale=1.0):
    """Lay `{op_name: seconds}` (profiler.device_op_times output) onto
    the trace as back-to-back X events on a synthetic device track.
    `scale` divides durations (pass `steps` to show per-step time);
    `origin_us` anchors the track (default: first host span, else 0).
    Returns the number of events added."""
    if origin_us is None:
        with _lock:
            origin_us = min((s.ts_us for s in _spans), default=0.0)
    t = float(origin_us)
    events = []
    for name, secs in sorted(op_times.items(), key=lambda kv: -kv[1]):
        dur = secs * 1e6 / scale
        events.append({"name": name, "cat": "device", "ph": "X",
                       "ts": t, "dur": dur, "pid": os.getpid(),
                       "tid": track,
                       "args": {"total_s": secs, "scale": scale}})
        t += dur
    with _lock:
        _device_events.extend(events)
    return len(events)


def chrome_trace():
    """The timeline as a Chrome trace-event dict:
    {"traceEvents": [...], "displayTimeUnit": "ms"} — json.dump it (or
    use write_chrome_trace) and load in chrome://tracing/Perfetto."""
    pid = os.getpid()
    with _lock:
        spans = list(_spans)
        device = list(_device_events)
    events = []
    tids = set()
    for s in spans:
        tids.add(s.tid)
        if s.cat == "counter":
            # counter ("C") events: args ARE the series values — no
            # depth key, or Perfetto would chart it as a series
            events.append({"name": s.name, "cat": s.cat, "ph": "C",
                           "ts": s.ts_us, "pid": pid, "tid": s.tid,
                           "args": dict(s.args) if s.args else {}})
            continue
        if s.cat == "instant":
            ev = {"name": s.name, "cat": s.cat, "ph": "i",
                  "ts": s.ts_us, "s": "t", "pid": pid, "tid": s.tid}
        else:
            ev = {"name": s.name, "cat": s.cat, "ph": "X",
                  "ts": s.ts_us, "dur": s.dur_us, "pid": pid,
                  "tid": s.tid}
        args = dict(s.args) if s.args else {}
        args["depth"] = s.depth
        ev["args"] = args
        events.append(ev)
    # synthetic tracks (pipeline schedule cells, fleet markers) use
    # string tids alongside integer thread idents — sort by str so the
    # mix never TypeErrors, and keep their own names as track labels
    for tid in sorted(tids, key=str):
        name = f"host thread {tid}" if isinstance(tid, int) else str(tid)
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": name}})
    events.extend(device)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path):
    trace = chrome_trace()
    with open(path, "w") as f:
        json.dump(trace, f)
    return path
