"""Shared compile-key vocabulary: one formatter for the component names
that explain a recompile.

Two consumers must name the same ckey component with the same phrasing
(regression-tested by tests/test_meshlint.py):

- the RUNTIME recompile explainer (telemetry/attribution.py,
  ``explain_recompile``), which diffs a new compile key against its
  nearest previously-seen neighbor after the cache was already busted;
- the STATIC recompile-hazard findings of meshlint
  (analysis/meshlint/recompile.py), which predict the bust before the
  first trace.

This module is dependency-free on purpose: ``telemetry.attribution`` is
pinned off the import path for telemetry-off runs (bench contract), and
``analysis.meshlint`` is pinned off the validate-off path — neither may
drag the other in, so the shared words live below both.
"""

__all__ = ["COMPONENT", "component_name", "fmt_field",
           "diff_feed_signature", "MEM_COMPONENT",
           "mem_component_phrase"]

# ckey field -> the component name the event/report/diagnostic leads with
COMPONENT = {
    "feed_signature": "shape bucket",
    "donate": "donate flag",
    "grad_sync": "grad_sync policy",
    "engine": "engine key",
    "is_test": "train/eval mode",
    "seed": "seed",
    "program_id": "program identity",
    "program_version": "program version",
    "fetch_names": "fetch set",
    "fuse_optimizer_tail": "fusion config",
    "fuse_max_elems": "fusion config",
    "async": "async window",
}


def component_name(field):
    """The human name a ckey field is reported under."""
    return COMPONENT.get(field, field)


# memory-ledger category -> the ckey field whose knob governs that
# footprint. The memledger's "what grew since the last fit" diff is
# phrased through this table so an OOM post-mortem names memory growth
# in the SAME vocabulary the recompile explainer and meshlint use for
# the knob that caused it (regression-tested by tests/test_memledger).
MEM_COMPONENT = {
    "feed": "feed_signature",
    "staging": "async",
    "gradsync_ef": "grad_sync",
    "sparse_table": "engine",
    "kv_cache": "engine",
    "optimizer": "fuse_optimizer_tail",
    "params": "program_id",
    "workspace": "program_version",
}


def mem_component_phrase(category):
    """ckey-vocab phrasing for a memory category's governing knob,
    e.g. staging -> \"async window (async)\"."""
    field = MEM_COMPONENT.get(category)
    if field is None:
        return category
    return f"{component_name(field)} ({field})"


def diff_feed_signature(old, new):
    """Human-readable diff of two _feed_signature tuples — names the
    exact feed whose shape bucket (or dtype) changed."""
    try:
        o = {name: (shape, dt) for name, shape, dt in old}
        n = {name: (shape, dt) for name, shape, dt in new}
    except (TypeError, ValueError):
        return f"{old!r} -> {new!r}"
    parts = []
    for name in sorted(set(o) | set(n)):
        if name not in o:
            parts.append(f"feed {name!r} added")
        elif name not in n:
            parts.append(f"feed {name!r} removed")
        elif o[name] != n[name]:
            what = "shape" if o[name][0] != n[name][0] else "dtype"
            ov = o[name][0] if what == "shape" else o[name][1]
            nv = n[name][0] if what == "shape" else n[name][1]
            parts.append(f"feed {name!r} {what} {ov} -> {nv}")
    return "; ".join(parts) or "identical signatures"


def fmt_field(name, old, new):
    if name == "feed_signature":
        return f"shape bucket: {diff_feed_signature(old, new)}"
    return f"{component_name(name)} ({name}): {old!r} -> {new!r}"
