"""Per-request tracing with tail-based exemplar capture.

Aggregate metrics (registry.py) answer "how slow is the p99"; this
module answers "*why was that one request* the p99": every serving seam
— http accept, batcher enqueue, router pick, hedge launch/cancel,
guard shed, crash resubmit, decode admit/step/retire, prefill and KV
handoff — emits an event into a bounded per-thread ring keyed by the
request's `X-Request-Id`. The hot path never takes a lock and never
allocates unboundedly: events go into a `deque(maxlen=...)` owned by
the emitting thread; span ids come from an atomic counter.

Tail-based capture: when a request *completes*, the trace decides
whether it is worth keeping. Triggers:

- ``p99``       latency above the live p99 of the completion window
- ``deadline``  the request missed its deadline
- ``shed``      brownout shed or queue rejection
- ``budget``    retry/hedge token-budget denial
- ``hedge``     a hedge was launched for it
- ``resubmit``  it was resubmitted after a replica death
- ``chaos``     a chaos fault hit it
- ``error``     it failed with any other error

Triggered traces are materialised into exemplars (their events are
gathered from the rings and frozen); non-triggered completions keep a
summary row only. The exemplar store is bounded by a fixed budget with
a pinned eviction order: oldest *non-triggered* rows go first, and a
triggered exemplar is never evicted while the triggered population
fits the budget.

Exemplars export as Chrome-trace JSON on the spans.py clock — one pid
per replica (pid 0 is the frontend: http/batcher/router), events
colored per request — so a hedged request renders as a causal chain
across two replica tracks.

Never imported unless `PADDLE_TPU_REQTRACE` is set (or
`telemetry.reqtrace_enable()` is called): the serving seams gate on
`telemetry.reqtrace_enabled()`, a plain bool check, before touching
this module (pinned by tests/test_bench_contract.py).
"""
import collections
import itertools
import threading

from .spans import _now_us

__all__ = [
    "trace_begin", "trace_end", "span", "span_at", "event", "leg",
    "flag", "snapshot", "get", "exemplars", "chrome_trace",
    "chrome_trace_from", "dump", "configure", "reset", "publish",
    "TRIGGERS",
]

TRIGGERS = ("p99", "deadline", "shed", "budget", "hedge", "resubmit",
            "chaos", "error")

_RING_CAP = 8192          # events per emitting thread
_BUDGET = 64              # exemplar store rows
_MAX_ACTIVE = 4096        # in-flight trace contexts
_LAT_WINDOW = 512         # completion latencies feeding the live p99
_P99_MIN_SAMPLES = 32     # below this the p99 trigger stays silent

_span_ids = itertools.count(1)           # CPython-atomic
_tls = threading.local()
_reg_lock = threading.Lock()
_rings = []                              # every thread's deque

_lock = threading.Lock()                 # trace begin/end/flag only
_active = {}                             # trace_id -> _Trace
_store = collections.OrderedDict()       # trace_id -> exemplar dict
_lat = collections.deque(maxlen=_LAT_WINDOW)

seen = 0                                 # completed traces
kept = 0                                 # triggered exemplars captured
dropped = 0                              # begins refused (active cap)
trigger_counts = collections.Counter()


class _Trace(object):
    __slots__ = ("trace_id", "t0_us", "root_id", "flags", "legs",
                 "args")

    def __init__(self, trace_id, args):
        self.trace_id = trace_id
        self.t0_us = _now_us()
        self.root_id = next(_span_ids)
        self.flags = set()
        self.legs = {}                   # replica index -> leg span id
        self.args = args


def _ring():
    r = getattr(_tls, "ring", None)
    if r is None:
        r = _tls.ring = collections.deque(maxlen=_RING_CAP)
        with _reg_lock:
            _rings.append(r)
    return r


def _emit(trace_id, name, ph, ts_us, dur_us, replica, parent_id,
          span_id, args):
    # hot path: no lock — the ring belongs to this thread
    _ring().append((trace_id, span_id, parent_id, name, ph, ts_us,
                    dur_us, replica, threading.get_ident(), args))
    return span_id


def _parent_for(trace_id, replica):
    t = _active.get(trace_id)            # GIL-atomic read
    if t is None:
        return None
    if replica is not None:
        leg_id = t.legs.get(replica)
        if leg_id is not None:
            return leg_id
    return t.root_id


# ----------------------------------------------------------- context
def trace_begin(trace_id, **args):
    """Open a trace for one request id. Idempotent: a second begin for
    a live id (a hedge leg, a resubmission) reuses the original
    context — one request keeps one trace end-to-end."""
    if not trace_id:
        return None
    with _lock:
        t = _active.get(trace_id)
        if t is not None:
            return t.root_id
        if len(_active) >= _MAX_ACTIVE:
            global dropped
            dropped += 1
            return None
        t = _active[trace_id] = _Trace(trace_id, args)
    _emit(trace_id, "request", "B", t.t0_us, 0, None, None, t.root_id,
          args or None)
    return t.root_id


def flag(trace_id, trigger):
    """Mark a capture trigger on a live trace (hedge, resubmit, shed,
    budget, deadline, chaos, error)."""
    t = _active.get(trace_id)
    if t is not None:
        t.flags.add(trigger)


def leg(trace_id, replica, kind="primary", **args):
    """Open a per-replica leg of the trace (the primary routing, a
    hedge duplicate, a resubmission). Scheduler/engine events carrying
    this replica index parent to the leg, which is what makes the
    cross-replica causal chain hang together."""
    t = _active.get(trace_id)
    if t is None:
        return None
    span_id = next(_span_ids)
    t.legs[replica] = span_id
    a = {"kind": kind, "replica": replica}
    if args:
        a.update(args)
    _emit(trace_id, "leg.%s" % kind, "i", _now_us(), 0, replica,
          t.root_id, span_id, a)
    return span_id


def event(trace_id, name, replica=None, **args):
    """Zero-duration instant on the request's timeline."""
    if not trace_id:
        return None
    return _emit(trace_id, name, "i", _now_us(), 0, replica,
                 _parent_for(trace_id, replica), next(_span_ids),
                 args or None)


class _SpanCM(object):
    __slots__ = ("trace_id", "name", "replica", "args", "t0")

    def __init__(self, trace_id, name, replica, args):
        self.trace_id = trace_id
        self.name = name
        self.replica = replica
        self.args = args

    def __enter__(self):
        self.t0 = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        t0 = self.t0
        _emit(self.trace_id, self.name, "X", t0, _now_us() - t0,
              self.replica, _parent_for(self.trace_id, self.replica),
              next(_span_ids), self.args or None)
        return False


class _NullCM(object):
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL = _NullCM()


def span(trace_id, name, replica=None, **args):
    """Duration span on the request's timeline (context manager)."""
    if not trace_id:
        return _NULL
    return _SpanCM(trace_id, name, replica, args or None)


def span_at(trace_id, name, t0_us, dur_us, replica=None, **args):
    """Record a span whose start was observed earlier (e.g. a decode
    slot's admit→retire lifetime, stamped at retire)."""
    if not trace_id:
        return None
    return _emit(trace_id, name, "X", t0_us, dur_us, replica,
                 _parent_for(trace_id, replica), next(_span_ids),
                 args or None)


# ------------------------------------------------------- completion
def _p99():
    n = len(_lat)
    if n < _P99_MIN_SAMPLES:
        return None
    vals = sorted(_lat)
    return vals[min(n - 1, int(0.99 * (n - 1) + 0.5))]


def _gather(trace_id):
    with _reg_lock:
        rings = list(_rings)
    evs = []
    for r in rings:
        # snapshot under the GIL; concurrent appends may be missed for
        # *other* traces, never for this one (its request is done)
        evs.extend(e for e in list(r) if e[0] == trace_id)
    evs.sort(key=lambda e: (e[5], e[1]))
    return [{"span_id": e[1], "parent_id": e[2], "name": e[3],
             "ph": e[4], "ts_us": e[5], "dur_us": e[6],
             "replica": e[7], "tid": e[8], "args": e[9]}
            for e in evs]


def _evict_locked():
    while len(_store) > _BUDGET:
        victim = None
        for tid, row in _store.items():
            if not row["triggers"]:
                victim = tid
                break
        if victim is None:
            # every row is triggered and we are over budget: only now
            # may a triggered exemplar go, oldest first
            victim = next(iter(_store))
        del _store[victim]


def trace_end(trace_id, status="ok", latency_s=None, **args):
    """Complete a trace: evaluate triggers, capture an exemplar when
    one fired, keep a summary row otherwise. Returns the trigger list
    (empty when the trace was not worth keeping in full)."""
    if not trace_id:
        return []
    with _lock:
        t = _active.pop(trace_id, None)
        if t is None:
            return []
        global seen, kept
        seen += 1
        now = _now_us()
        if latency_s is None:
            latency_s = (now - t.t0_us) / 1e6
        trig = set(t.flags)
        if status not in ("ok",):
            trig.add("error")
        p99 = _p99()
        if p99 is not None and latency_s > p99:
            trig.add("p99")
        _lat.append(latency_s)
        triggers = sorted(trig)
        for name in triggers:
            trigger_counts[name] += 1
        row = {"trace_id": trace_id, "status": status,
               "latency_ms": latency_s * 1000.0, "triggers": triggers,
               "t0_us": t.t0_us, "root_id": t.root_id,
               "args": dict(t.args, **args) if (t.args or args)
               else None, "events": None}
        if triggers:
            kept += 1
        _store[trace_id] = row
        _store.move_to_end(trace_id)
        _evict_locked()
    _emit(trace_id, "request", "E", now,
          int(latency_s * 1e6), None, None, t.root_id,
          {"status": status} if not args else dict(args, status=status))
    if triggers and trace_id in _store:
        # materialise outside the lock: ring scan is the slow part and
        # only triggered (tail) traces pay it
        events = _gather(trace_id)
        with _lock:
            live = _store.get(trace_id)
            if live is not None:
                live["events"] = events
    publish()
    return triggers


# --------------------------------------------------------- exports
def snapshot():
    """Counters plus summary rows for every stored trace (newest
    last). The shape behind ``GET /v1/traces`` and ``tputrace list``."""
    with _lock:
        rows = [{k: v for k, v in row.items() if k != "events"}
                for row in _store.values()]
        for row, full in zip(rows, _store.values()):
            row["captured"] = full["events"] is not None
            row["n_events"] = (len(full["events"])
                               if full["events"] else 0)
        return {"enabled": True, "seen": seen, "kept": kept,
                "dropped": dropped, "budget": _BUDGET,
                "stored": len(_store),
                "triggers": dict(trigger_counts), "traces": rows}


def get(trace_id):
    """Full exemplar (summary + events) or None."""
    with _lock:
        row = _store.get(trace_id)
        return dict(row) if row is not None else None


def exemplars():
    """Stored trace ids in insertion order (oldest first)."""
    with _lock:
        return list(_store)


_CNAMES = ("thread_state_running", "rail_response", "rail_animation",
           "rail_idle", "rail_load", "cq_build_running",
           "cq_build_passed", "thread_state_iowait", "good",
           "vsync_highlight_color", "heap_dump_stack_frame",
           "olive", "generic_work")


def chrome_trace(trace_id):
    """One exemplar as Chrome trace-event JSON: pid 0 is the frontend
    (http/batcher/router/guard), pid i+1 is replica i; all events carry
    the request's color so multiple exported traces stay tellable
    apart."""
    return chrome_trace_from(get(trace_id))


def chrome_trace_from(row):
    """Convert one exemplar row (live, or loaded back from a
    traces.json artifact) to Chrome trace-event JSON."""
    if row is None:
        return None
    trace_id = row["trace_id"]
    # color per request, stable across processes (hash() is salted)
    cname = _CNAMES[sum(trace_id.encode()) % len(_CNAMES)]
    out, pids = [], {}
    for e in row["events"] or []:
        rep = e["replica"]
        pid = 0 if rep is None else int(rep) + 1
        pids.setdefault(pid, "frontend" if rep is None
                        else "replica %d" % rep)
        ev = {"name": e["name"], "ph": "X" if e["ph"] == "X" else "i",
              "ts": e["ts_us"], "pid": pid, "tid": e["tid"],
              "cat": "reqtrace", "cname": cname,
              "args": dict(e["args"] or {}, request_id=trace_id,
                           span_id=e["span_id"],
                           parent_id=e["parent_id"])}
        if e["ph"] == "X":
            ev["dur"] = max(0, e["dur_us"])
        else:
            ev["s"] = "t"
        out.append(ev)
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": label}}
            for pid, label in sorted(pids.items())]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "metadata": {"trace_id": trace_id, "status": row["status"],
                         "latency_ms": row["latency_ms"],
                         "triggers": row["triggers"]}}


def dump():
    """Everything, events included — the traces.json artifact
    telemetry.flush writes (what `tputrace list/show --path` reads)."""
    with _lock:
        full = [dict(row) for row in _store.values()]
    return {"enabled": True, "seen": seen, "kept": kept,
            "dropped": dropped, "budget": _BUDGET,
            "triggers": dict(trigger_counts), "traces": full}


def publish():
    """Mirror the capture counters into the metrics registry so fleet
    spool rows (and `tpustat --fleet/--watch`) carry per-rank trace
    pressure. Gauges, not counters: re-publishing is idempotent and
    the fleet merge stays stable on re-merge."""
    from . import enabled, gauge
    if not enabled():
        return
    gauge("serving.trace.seen").set(seen)
    gauge("serving.trace.kept").set(kept)
    gauge("serving.trace.stored").set(len(_store))
    for name, n in trigger_counts.items():
        gauge("serving.trace.trigger.%s" % name).set(n)


# ----------------------------------------------------------- config
def configure(budget=None, ring_cap=None, p99_min_samples=None):
    """Test/ops hook. ring_cap only affects rings created after the
    call (existing per-thread rings keep their bound)."""
    global _BUDGET, _RING_CAP, _P99_MIN_SAMPLES
    if budget is not None:
        _BUDGET = int(budget)
    if ring_cap is not None:
        _RING_CAP = int(ring_cap)
    if p99_min_samples is not None:
        _P99_MIN_SAMPLES = int(p99_min_samples)


def reset():
    """Drop all traces, rings, and counters (not the config)."""
    global seen, kept, dropped
    with _lock:
        _active.clear()
        _store.clear()
        _lat.clear()
        trigger_counts.clear()
        seen = kept = dropped = 0
    with _reg_lock:
        for r in _rings:
            r.clear()
