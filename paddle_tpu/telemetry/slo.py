"""tpuscope SLO engine: declarative perf rules + history regression gate.

Rules are one-line strings — ``"step_ms.p99 < 250"``,
``"perf.mfu > 0.3"``, ``"serving.queue_depth < 64"`` — evaluated
against a registry snapshot (or the fleet merge). A trailing
``.p50/.p99/.mean/.min/.max/.count`` segment selects a histogram
statistic (quantiles interpolate from the fixed buckets via
``registry.quantile_from_buckets``); everything else reads the metric's
scalar value. Missing metrics are *skipped*, not violated — a serving
rule shouldn't fail a training run — unless ``strict=True``.

The regression gate reuses the fleet straggler detector's robust
statistics (median ± k·MAD with a small-sample ratio fallback,
fleet.py `detect_stragglers`) against the rolling ``BENCH_history.jsonl``
spine bench.py appends to: the latest record for each metric is
compared to the median of its predecessors, direction-aware (throughput
regresses down, latency regresses up).

Dependency-free beyond sibling telemetry modules — no jax — so
``tpustat --slo`` can gate in CI without touching a backend.
"""
import json
import os
import re
import statistics

from . import registry as _registry
# the straggler detector's knobs ARE the regression gate's knobs: one
# definition of "anomalously far from the median" across the repo
from .fleet import _DEFAULT_K_MAD, _RATIO_FALLBACK

__all__ = ["Rule", "RuleResult", "SloReport", "parse_rule",
           "evaluate", "evaluate_fleet", "check_regression",
           "history_gate", "load_history", "append_history",
           "DEFAULT_RULES"]

_OPS = {
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
}
_RULE_RE = re.compile(
    r"^\s*(?P<metric>[A-Za-z0-9_.:\-]+)\s*"
    r"(?P<op><=|>=|==|!=|<|>)\s*"
    r"(?P<value>[-+0-9.eE]+)\s*$")

_STATS = ("p50", "p99", "mean", "max", "min", "count", "value")

# shorthand -> (real metric, scale applied to the observed value).
# step_ms reads the step-seconds histogram in milliseconds, matching
# how every BENCH artifact and ROADMAP target quotes step time.
ALIASES = {
    "step_ms": ("executor.step_seconds", 1e3),
}

# the ruleset `tpustat --slo` applies when none is given: generous
# sanity ceilings that hold on any healthy run rather than aggressive
# targets (those belong in a per-deployment rules file)
DEFAULT_RULES = (
    "step_ms.p99 < 3600000",        # a step completes within an hour
    "serving.queue_depth < 100000",
)


class Rule:
    __slots__ = ("text", "metric", "stat", "op", "threshold", "scale")

    def __init__(self, text, metric, stat, op, threshold, scale=1.0):
        self.text = text
        self.metric = metric
        self.stat = stat
        self.op = op
        self.threshold = threshold
        self.scale = scale

    def __repr__(self):
        return f"Rule({self.text!r})"


class RuleResult:
    __slots__ = ("rule", "ok", "observed", "skipped", "reason")

    def __init__(self, rule, ok, observed=None, skipped=False,
                 reason=None):
        self.rule = rule
        self.ok = ok
        self.observed = observed
        self.skipped = skipped
        self.reason = reason

    def to_dict(self):
        return {"rule": self.rule.text, "ok": self.ok,
                "observed": self.observed, "skipped": self.skipped,
                "reason": self.reason}

    def __str__(self):
        if self.skipped:
            return f"SKIP {self.rule.text} ({self.reason})"
        tag = "PASS" if self.ok else "FAIL"
        return f"{tag} {self.rule.text} (observed {self.observed:g})"


class SloReport:
    """Typed outcome of one evaluation pass: per-rule results plus the
    rolled-up verdict. `ok` is True when no rule FAILED (skips don't
    fail — unless the evaluation ran strict, in which case skips were
    already converted to failures)."""
    __slots__ = ("results",)

    def __init__(self, results):
        self.results = list(results)

    @property
    def violations(self):
        return [r for r in self.results
                if not r.ok and not r.skipped]

    @property
    def skipped(self):
        return [r for r in self.results if r.skipped]

    @property
    def ok(self):
        return not self.violations

    def to_dict(self):
        return {"ok": self.ok,
                "violations": len(self.violations),
                "results": [r.to_dict() for r in self.results]}

    def __str__(self):
        lines = [str(r) for r in self.results]
        lines.append(f"SLO: {'OK' if self.ok else 'VIOLATED'} "
                     f"({len(self.violations)} violation(s), "
                     f"{len(self.skipped)} skipped, "
                     f"{len(self.results)} rule(s))")
        return "\n".join(lines)


def parse_rule(text):
    """'name[.stat] OP value' -> Rule. The stat suffix only splits off
    when it names a known statistic, so dotted metric names
    ('perf.mfu', 'serving.queue_depth') parse whole."""
    m = _RULE_RE.match(text)
    if not m:
        raise ValueError(
            f"bad SLO rule {text!r} (want 'metric[.stat] "
            f"{'|'.join(_OPS)} number')")
    metric = m.group("metric")
    stat = "value"
    head, dot, tail = metric.rpartition(".")
    if dot and tail in _STATS:
        metric, stat = head, tail
    scale = 1.0
    if metric in ALIASES:
        metric, scale = ALIASES[metric]
    return Rule(text.strip(), metric, stat, m.group("op"),
                float(m.group("value")), scale)


def _observe(value, stat):
    """Pull `stat` out of one snapshot entry (scalar or histogram
    dict). Returns (observed, reason): observed None means the stat
    can't be read, with the reason saying why."""
    if isinstance(value, dict) and "kind" in value and "value" in value:
        value = value["value"]            # snapshot_with_kinds entry
    if isinstance(value, dict):
        if stat == "value":
            stat = "mean"                 # bare histogram name
        if stat in ("p50", "p99"):
            q = _registry.quantile_from_buckets(value,
                                               float(stat[1:]) / 100)
            if q is None:
                return None, "empty histogram"
            return q, None
        if stat in value:
            return float(value[stat]), None
        return None, f"histogram has no {stat!r}"
    if stat not in ("value",):
        return None, f"scalar metric has no {stat!r}"
    try:
        return float(value), None
    except (TypeError, ValueError):
        return None, f"non-numeric value {value!r}"


def evaluate(rules, snap=None, strict=False):
    """Evaluate rules against a registry snapshot (default: the live
    registry). Counts violations on the `slo.violations` counter when
    telemetry is recording."""
    parsed = [r if isinstance(r, Rule) else parse_rule(r)
              for r in rules]
    if snap is None:
        snap = _registry.snapshot()
    results = []
    for rule in parsed:
        if rule.metric not in snap:
            results.append(RuleResult(
                rule, ok=not strict, skipped=not strict,
                reason=f"metric {rule.metric!r} absent"))
            continue
        observed, reason = _observe(snap[rule.metric], rule.stat)
        if observed is None:
            results.append(RuleResult(rule, ok=not strict,
                                      skipped=not strict,
                                      reason=reason))
            continue
        observed *= rule.scale
        ok = _OPS[rule.op](observed, rule.threshold)
        results.append(RuleResult(rule, ok=ok, observed=observed))
    report = SloReport(results)
    n = len(report.violations)
    if n and _registry.snapshot():
        _registry.counter("slo.violations").inc(n)
    return report


def evaluate_fleet(rules, report, strict=False):
    """Evaluate rules against a fleet merge (FleetCollector.report()):
    merged entries are {"kind", "value"} dicts, which _observe already
    unwraps."""
    merged = report.get("merged", report) or {}
    return evaluate(rules, snap=merged, strict=strict)


# ------------------------------------------------------- history gate

HISTORY_SCHEMA = "paddle_tpu.bench.history.v1"

# substrings that decide which direction is "worse" for a metric when
# the record doesn't say; throughput-ish names regress DOWN,
# latency-ish names regress UP
_HIGHER_BETTER = ("per_sec", "per_s", "_sec", "mfu", "goodput",
                  "steps_per", "tokens_per", "images_per",
                  "examples_per")
_LOWER_BETTER = ("_ms", "latency", "seconds", "step_ms", "_time")


def metric_direction(metric, unit=None):
    """'higher' | 'lower' — which way is better for this metric."""
    probe = f"{metric} {unit or ''}".lower()
    for tag in _HIGHER_BETTER:
        if tag in probe:
            return "higher"
    for tag in _LOWER_BETTER:
        if tag in probe:
            return "lower"
    return "higher"


def check_regression(history_values, current, direction="higher",
                     k=_DEFAULT_K_MAD, window=20):
    """Is `current` an outlier on the bad side of the rolling history?

    Same robust statistics as the fleet straggler detector: with >= 4
    samples and nonzero MAD the threshold is median ± k·MAD, else the
    ratio fallback (median × or ÷ 1.5). Returns a dict with
    `regressed`, `median`, `threshold`, `n`."""
    vals = [float(v) for v in history_values][-window:]
    out = {"regressed": False, "median": None, "threshold": None,
           "n": len(vals), "current": float(current),
           "direction": direction}
    if not vals:
        return out
    med = statistics.median(vals)
    mad = statistics.median([abs(v - med) for v in vals])
    if len(vals) >= 4 and mad > 0:
        delta = k * mad
    else:
        delta = (_RATIO_FALLBACK - 1.0) * abs(med)
    if direction == "higher":
        threshold = med - delta
        regressed = current < threshold
    else:
        threshold = med + delta
        regressed = current > threshold
    out.update(median=med, threshold=threshold, regressed=regressed)
    return out


def load_history(path):
    """BENCH_history.jsonl -> list of record dicts. Unparseable lines
    are skipped (the file is append-only across interrupted runs)."""
    records = []
    if not os.path.exists(path):
        return records
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec \
                    and "value" in rec:
                records.append(rec)
    return records


def append_history(path, records):
    with open(path, "a") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    return len(records)


def _normalized_prior(prior, latest, direction):
    """Prior values expressed in the LATEST record's machine units.

    Records carry `calib_ms` — the wall time of bench.py's fixed
    calibration microbenchmark on the box that produced them; the
    ratio of two stamps is the relative speed of the two boxes. A
    prior throughput measured on a box 2x faster than today's is
    halved before it joins the rolling median (a latency is doubled),
    so a spine that spans CI machine generations gates on the CODE's
    trajectory, not the hardware lottery.

    When the latest record is calibrated, uncalibrated prior records
    are EXCLUDED (no ratio exists — comparing them raw is exactly the
    cross-box bug this removes). When the latest record itself has no
    stamp, values pass through untouched (the pre-calibration
    behavior). Returns (values, n_excluded)."""
    latest_calib = latest.get("calib_ms")
    if not latest_calib:
        return [r["value"] for r in prior], 0
    vals = []
    excluded = 0
    for r in prior:
        c = r.get("calib_ms")
        if not c:
            excluded += 1
            continue
        if direction == "higher":
            # prior box faster (smaller calib_ms) -> its throughput
            # is inflated relative to this box -> scale it down
            vals.append(r["value"] * (c / latest_calib))
        else:
            vals.append(r["value"] * (latest_calib / c))
    return vals, excluded


def history_gate(records, k=_DEFAULT_K_MAD, window=20,
                 platform=None):
    """Regression-gate the newest record of each metric against the
    rolling median of its predecessors, calibration-normalized (see
    `_normalized_prior`). Records for other platforms are excluded (a
    CPU smoke run must not drag a TPU baseline).
    Returns {"ok", "checked", "regressions": [per-metric dicts]}."""
    by_metric = {}
    for rec in records:
        if platform and rec.get("platform") not in (None, platform):
            continue
        by_metric.setdefault(rec["metric"], []).append(rec)
    regressions = []
    checked = 0
    for metric, recs in sorted(by_metric.items()):
        if len(recs) < 2:
            continue                     # nothing to compare against
        *prior, latest = recs
        direction = metric_direction(metric, latest.get("unit"))
        vals, excluded = _normalized_prior(prior, latest, direction)
        if not vals:
            continue          # nothing commensurable to compare against
        checked += 1
        res = check_regression(
            vals, latest["value"],
            direction=direction, k=k, window=window)
        res["metric"] = metric
        if latest.get("calib_ms"):
            res["calib_ms"] = latest["calib_ms"]
            res["excluded_uncalibrated"] = excluded
        if res["regressed"]:
            regressions.append(res)
    return {"ok": not regressions, "checked": checked,
            "regressions": regressions}
