"""Device-memory watermark gauges.

jax exposes per-device allocator stats through `Device.memory_stats()`,
but support varies by backend, version, AND device: TPU returns a
populated dict, this image's CPU devices (jax 0.4.37) return None,
some plugin backends raise, and a mixed-platform process (cpu host
devices alongside an accelerator) supports it on some local devices
only. The capability probe is therefore PER DEVICE — each device is
probed once (cached per process) and degrades individually, so one
stats-less device never blinds sampling for the rest.

jax is imported lazily: the telemetry package must stay importable
(and cheap) from modules that load before the backend is up.
"""
import threading

from . import registry as _registry

__all__ = ["device_memory_supported", "sample_device_memory",
           "reset_memory_probe"]

_probe = {}            # device label -> True/False cached verdict
_probe_lock = threading.Lock()


def _label(d):
    return f"{d.platform}:{d.id}"


def reset_memory_probe():
    """Testing hook: force the next sample to re-probe every device."""
    with _probe_lock:
        _probe.clear()


def _probe_device(d):
    """Cached per-device verdict: does THIS device report allocator
    stats? Any exception, None, or dict without bytes_in_use means
    unsupported — for that device only."""
    key = _label(d)
    verdict = _probe.get(key)
    if verdict is not None:
        return verdict
    with _probe_lock:
        verdict = _probe.get(key)
        if verdict is not None:
            return verdict
        try:
            stats = d.memory_stats()
            verdict = bool(stats) and "bytes_in_use" in stats
        except Exception:
            verdict = False
        _probe[key] = verdict
    return verdict


def device_memory_supported():
    """True when ANY local device reports allocator stats (each probed
    and cached individually)."""
    try:
        import jax
        devs = jax.local_devices()
    except Exception:
        return False
    return any(_probe_device(d) for d in devs)


def sample_device_memory():
    """Update `device.<platform>:<id>.bytes_in_use` (gauge) and
    `.peak_bytes_in_use` (high-watermark gauge) for every local device
    that supports stats; unsupported devices are skipped individually.
    Returns {device_label: bytes_in_use}, empty when nothing does."""
    try:
        import jax
        devs = jax.local_devices()
    except Exception:
        return {}
    out = {}
    for d in devs:
        if not _probe_device(d):
            continue
        try:
            stats = d.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        in_use = stats.get("bytes_in_use")
        if in_use is None:
            continue
        label = f"device.{_label(d)}"
        _registry.gauge(f"{label}.bytes_in_use").set(in_use)
        _registry.gauge(f"{label}.peak_bytes_in_use").set_max(
            stats.get("peak_bytes_in_use", in_use))
        limit = stats.get("bytes_limit")
        if limit:
            _registry.gauge(f"{label}.bytes_limit").set(limit)
        out[label] = in_use
    return out
