"""Device-memory watermark gauges.

jax exposes per-device allocator stats through `Device.memory_stats()`,
but support varies by backend and version: TPU returns a populated
dict, this image's CPU devices (jax 0.4.37) return None, and some
plugin backends raise. A one-shot capability probe (cached per
process) classifies the backend so tier-1 CPU runs degrade to no-op
sampling — no gauges registered, no exceptions — instead of failing.

jax is imported lazily: the telemetry package must stay importable
(and cheap) from modules that load before the backend is up.
"""
import threading

from . import registry as _registry

__all__ = ["device_memory_supported", "sample_device_memory",
           "reset_memory_probe"]

_probe = None          # None = not probed; False/True = cached verdict
_probe_lock = threading.Lock()


def reset_memory_probe():
    """Testing hook: force the next sample to re-probe."""
    global _probe
    with _probe_lock:
        _probe = None


def device_memory_supported():
    """True when the local backend reports allocator stats. Probes the
    first local device once; any exception, None, or empty dict means
    unsupported (the capability is all-or-nothing per backend)."""
    global _probe
    if _probe is not None:
        return _probe
    with _probe_lock:
        if _probe is not None:
            return _probe
        try:
            import jax
            devs = jax.local_devices()
            stats = devs[0].memory_stats() if devs else None
            verdict = bool(stats) and "bytes_in_use" in stats
        except Exception:
            verdict = False
        _probe = verdict
    return _probe


def sample_device_memory():
    """Update `device.<platform>:<id>.bytes_in_use` (gauge) and
    `.peak_bytes_in_use` (high-watermark gauge) for every local device.
    Returns {device_label: bytes_in_use}, empty when unsupported."""
    if not device_memory_supported():
        return {}
    import jax
    out = {}
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        in_use = stats.get("bytes_in_use")
        if in_use is None:
            continue
        label = f"device.{d.platform}:{d.id}"
        _registry.gauge(f"{label}.bytes_in_use").set(in_use)
        _registry.gauge(f"{label}.peak_bytes_in_use").set_max(
            stats.get("peak_bytes_in_use", in_use))
        limit = stats.get("bytes_limit")
        if limit:
            _registry.gauge(f"{label}.bytes_limit").set(limit)
        out[label] = in_use
    return out
