"""paddle_tpu.telemetry.memledger — live device-memory ledger.

The stack makes byte-level promises in three places nothing at runtime
verifies: meshlint's static device-footprint pass predicts a floor,
ScalePlanner's verify gate rejects grows against that floor, and the
farm publishes analytic `kv_cache_bytes` gauges. This module closes the
loop: every device byte is attributed to an owning category at its
creation site —

    params        persistable model parameters
    optimizer     optimizer accumulator slots (velocity/moment/...)
    gradsync_ef   gradsync error-feedback state (gradsync.ef.*)
    sparse_table  row-sharded embedding table shards
    kv_cache      decode KV-cache blocks (fp32 vs int8 tagged per owner)
    staging       async-window staging + pipeline prefetch buffers
    feed          synchronous feed arrays put by the executor
    workspace     executable workspace (derived: allocator in-use minus
                  ledger total, only where Device.memory_stats() works)

— and sampled cheaply at step boundaries (a walk over weakref'd
entries, no device sync), with a full `jax.live_arrays()` sweep on
demand. Peaks reconcile against meshlint's static member_footprint
(drift gauge + a tpulint-format WARNING Diagnostic beyond tolerance),
and an OOM doctor turns RESOURCE_EXHAUSTED anywhere in the run path
into a typed MemoryReport dumped through the flight recorder.

Off-path contract: `PADDLE_TPU_MEMLEDGER` unset, this module is never
imported (telemetry.memledger_enabled() is a plain bool; pinned by
tests/test_bench_contract.py). Everything here assumes the caller
already checked that gate. jax is imported lazily — registration
happens from package-init-adjacent code paths.
"""
import collections
import logging
import os
import threading
import time
import weakref

from . import registry as _registry
from . import spans as _spans
from . import memory as _memory
from .ckey_vocab import mem_component_phrase

__all__ = ["CATEGORIES", "MemLedger", "MemoryReport", "get", "register",
           "unregister_owner", "on_step", "sweep", "snapshot_report",
           "classify_persist_name", "is_oom_error",
           "handle_possible_oom", "reconcile", "replica_peaks",
           "last_report", "reset", "device_cap_bytes", "fmt_bytes"]

_LOG = logging.getLogger("paddle_tpu.telemetry.memledger")

CATEGORIES = ("params", "optimizer", "gradsync_ef", "sparse_table",
              "kv_cache", "staging", "feed", "workspace",
              "unattributed")

# optimizer accumulator slots are named unique_name.generate(
# f"{param.name}_{slot}") — these markers are the slot vocabulary of
# paddle_tpu/optimizer.py plus the lr var every optimizer creates
_OPT_SLOT_MARKERS = ("_velocity_", "_moment", "_beta1_pow", "_beta2_pow",
                     "_inf_norm", "_avg_squared_", "_mean_square",
                     "_mean_grad", "_squared_", "_linear_",
                     "learning_rate")

_EF_PREFIX = "gradsync.ef."    # parallel/gradsync.py EF_PREFIX

_OOM_MARKERS = ("resource_exhausted", "out of memory",
                "hbm_left_out_of_memory", "allocation failure",
                "oom while")

_TIMELINE_MAX = 4096
_TOP_N = 12


def fmt_bytes(n):
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.2f}{unit}")
        n /= 1024
    return f"{n:.2f}GiB"


def device_cap_bytes():
    """Per-device byte budget: PADDLE_TPU_DEVICE_MEM_CAP (MiB — the
    meshlint footprint pass's unit) wins; else the allocator's
    bytes_limit where memory_stats() works; else None."""
    env = os.environ.get("PADDLE_TPU_DEVICE_MEM_CAP")
    if env:
        try:
            return int(float(env) * (1 << 20))
        except ValueError:
            pass
    if _memory.device_memory_supported():
        try:
            import jax
            for d in jax.local_devices():
                stats = d.memory_stats() or {}
                if stats.get("bytes_limit"):
                    return int(stats["bytes_limit"])
        except Exception:
            pass
    return None


def classify_persist_name(name):
    """Ledger category for one persistable-scope var name. The executor
    registers its whole persist collection through this so optimizer
    slots, gradsync error-feedback state, and params land in their own
    buckets without per-site bookkeeping."""
    if name.startswith(_EF_PREFIX):
        return "gradsync_ef"
    for marker in _OPT_SLOT_MARKERS:
        if marker in name:
            return "optimizer"
    return "params"


def is_oom_error(exc):
    """Does this exception look like a device allocator exhaustion?
    jax surfaces XLA's RESOURCE_EXHAUSTED through XlaRuntimeError with
    backend-varying phrasing, so this is a marker-text classifier (the
    tpudoctor pattern), not an isinstance check."""
    if exc is None:
        return False
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(m in text for m in _OOM_MARKERS)


class _Entry:
    __slots__ = ("ref", "category", "owner", "nbytes", "meta")

    def __init__(self, ref, category, owner, nbytes, meta):
        self.ref = ref
        self.category = category
        self.owner = owner
        self.nbytes = nbytes
        self.meta = meta


class MemoryReport:
    """Typed OOM / over-cap post-mortem (the tpudoctor report shape:
    to_dict() for the flight recorder, format() for humans)."""

    kind = "memory"

    def __init__(self, reason, error=None, context=None, cap_bytes=None,
                 total_bytes=0, peak_bytes=0, categories=None, top=None,
                 growth=None, hints=None, device=None, timeline=None):
        self.reason = reason              # "oom" | "over_cap"
        self.error = error
        self.context = dict(context or {})
        self.cap_bytes = cap_bytes
        self.total_bytes = int(total_bytes)
        self.peak_bytes = int(peak_bytes)
        self.categories = dict(categories or {})
        self.top = list(top or [])        # [{category, owner, bytes}]
        self.growth = list(growth or [])  # [{category, before, after,
                                          #   delta, phrase}]
        self.hints = list(hints or [])
        self.device = dict(device or {})
        self.timeline = list(timeline or [])
        self.unix_time = time.time()

    @property
    def top_category(self):
        if not self.categories:
            return None
        return max(self.categories.items(), key=lambda kv: kv[1])[0]

    @property
    def top_growth_category(self):
        if not self.growth:
            return None
        return max(self.growth, key=lambda g: g["delta"])["category"]

    def to_dict(self):
        return {
            "kind": self.kind, "reason": self.reason,
            "error": self.error, "context": self.context,
            "unix_time": self.unix_time, "cap_bytes": self.cap_bytes,
            "total_bytes": self.total_bytes,
            "peak_bytes": self.peak_bytes,
            "top_category": self.top_category,
            "categories": self.categories, "top": self.top,
            "growth": self.growth, "hints": self.hints,
            "device": self.device, "timeline": self.timeline,
        }

    def format(self):
        lines = [f"MemoryReport ({self.reason})"]
        if self.error:
            lines.append(f"  error: {self.error}")
        for k, v in sorted(self.context.items()):
            lines.append(f"  {k}: {v}")
        cap = (fmt_bytes(self.cap_bytes) if self.cap_bytes
               else "uncapped")
        lines.append(f"  peak {fmt_bytes(self.peak_bytes)} / cap {cap} "
                     f"(live {fmt_bytes(self.total_bytes)})")
        lines.append("  by category:")
        for cat, b in sorted(self.categories.items(),
                             key=lambda kv: -kv[1]):
            if b:
                lines.append(f"    {cat:<13} {fmt_bytes(b)}")
        if self.top:
            lines.append(f"  top allocations:")
            for t in self.top[:_TOP_N]:
                lines.append(f"    {t['category']}/{t['owner']:<20} "
                             f"{fmt_bytes(t['bytes'])}")
        if self.growth:
            lines.append("  grew since the last fit:")
            for g in self.growth:
                lines.append(
                    f"    {g['category']}: {fmt_bytes(g['before'])} -> "
                    f"{fmt_bytes(g['after'])} "
                    f"(+{fmt_bytes(g['delta'])}) [{g['phrase']}]")
        for h in self.hints:
            lines.append(f"  hint: {h}")
        return "\n".join(lines)


def _growth_hints(growth, categories, meta_by_owner):
    """Fix hints keyed off what actually grew (or, with no fit to diff
    against, what dominates)."""
    cats = ([g["category"] for g in
             sorted(growth, key=lambda g: -g["delta"])]
            or [c for c, b in sorted(categories.items(),
                                     key=lambda kv: -kv[1]) if b])
    hints, seen = [], set()
    for cat in cats:
        if cat in seen:
            continue
        seen.add(cat)
        if cat == "staging":
            hints.append("lower async_steps — the in-flight window "
                         "multiplies staged feed buffers per step")
        elif cat == "kv_cache":
            quants = {m.get("quant") for m in meta_by_owner.values()
                      if m.get("category") == "kv_cache"}
            if "int8" in quants and len(quants) == 1:
                hints.append("KV cache already int8 — shrink "
                             "num_slots/max_len or replicas")
            else:
                hints.append("set kv_quant='int8' (~0.69x the fp32 "
                             "cache bytes) or shrink replicas")
        elif cat == "sparse_table":
            hints.append("shard embedding tables over more devices or "
                         "lower the sparse cap")
        elif cat == "optimizer":
            hints.append("pick an optimizer with fewer slots "
                         "(sgd:0, momentum:1, adam:2)")
        elif cat == "gradsync_ef":
            hints.append("drop error_feedback (ef=0) from the "
                         "grad_sync policy to free per-param EF state")
        elif cat == "feed":
            hints.append("shrink the batch or bucket feed shapes")
        elif cat == "params":
            hints.append("shard params over more devices or shrink "
                         "replicas")
    return hints[:4]


class MemLedger:
    """Process-global ledger: id(array) -> weakref'd entry. Dead
    entries self-remove via weakref callback, so per-step sampling is
    one lock + one walk over live entries — no device sync, no GC."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}            # id -> _Entry
        self._peak = 0
        self._cat_peak = collections.defaultdict(int)
        self._owner_peak = collections.defaultdict(int)
        self._last_fit = None         # {category: bytes} at last clean step
        self._last_report = None
        self._breach_open = False     # one over-cap report per breach
        self._timeline = collections.deque(maxlen=_TIMELINE_MAX)
        self._steps = 0

    # -- registration -------------------------------------------------
    def register(self, category, owner, value, **meta):
        """Attribute every jax array in `value` (array / dict / tuple /
        nested) to (category, owner). Re-registering an array moves it;
        dead arrays fall out on their own. Returns bytes registered."""
        if category not in CATEGORIES:
            raise ValueError(f"unknown ledger category {category!r} "
                             f"(have {CATEGORIES})")
        total = 0
        for arr in _iter_arrays(value):
            nbytes = getattr(arr, "nbytes", None)
            if nbytes is None:
                continue
            key = id(arr)
            try:
                ref = weakref.ref(arr, _make_reaper(self, key))
            except TypeError:
                ref = None            # not weakref-able: track by id only
            with self._lock:
                self._entries[key] = _Entry(ref, category, str(owner),
                                            int(nbytes), meta or {})
            total += int(nbytes)
        return total

    def unregister_owner(self, owner):
        """Drop every entry for an owner (e.g. a shrunk replica)."""
        owner = str(owner)
        with self._lock:
            dead = [k for k, e in self._entries.items()
                    if e.owner == owner]
            for k in dead:
                del self._entries[k]
        return len(dead)

    # -- sampling -----------------------------------------------------
    def _live_totals(self):
        """(total, {category: bytes}, {(category, owner): bytes}) over
        entries whose array is still alive."""
        cats = dict.fromkeys(CATEGORIES, 0)
        owners = collections.defaultdict(int)
        with self._lock:
            entries = list(self._entries.values())
        total = 0
        for e in entries:
            if e.ref is not None:
                arr = e.ref()
                if arr is None or getattr(arr, "is_deleted",
                                          lambda: False)():
                    continue
            total += e.nbytes
            cats[e.category] += e.nbytes
            owners[(e.category, e.owner)] += e.nbytes
        return total, cats, dict(owners)

    def on_step(self, step=None, context=None):
        """Cheap per-step sample: update totals/peaks, extend the
        timeline + Chrome counter track, publish gauges (when telemetry
        is on), and emit one over-cap MemoryReport per breach of the
        device cap. Returns the live total in bytes."""
        total, cats, owners = self._live_totals()
        if _memory.device_memory_supported():
            dev = _memory.sample_device_memory()
            if dev:
                # allocator truth minus attributed bytes = workspace
                cats["workspace"] = max(
                    0, max(dev.values()) - total + cats["workspace"])
                total = max(total, max(dev.values()))
        self._steps += 1
        self._peak = max(self._peak, total)
        for c, b in cats.items():
            self._cat_peak[c] = max(self._cat_peak[c], b)
        for (c, o), b in owners.items():
            self._owner_peak[(c, o)] = max(self._owner_peak[(c, o)], b)
        self._timeline.append(
            {"step": self._steps if step is None else step,
             "total": total,
             "categories": {c: b for c, b in cats.items() if b}})
        from . import enabled as _tm_enabled
        if _tm_enabled():
            _registry.gauge("memledger.total_bytes").set(total)
            _registry.gauge("memledger.peak_bytes").set_max(total)
            with self._lock:
                n = len(self._entries)
            _registry.gauge("memledger.entries").set(n)
            for c, b in cats.items():
                if b or self._cat_peak[c]:
                    _registry.gauge(f"memledger.bytes.{c}").set(b)
            _spans.counter_event(
                "hbm", {c: b for c, b in cats.items() if b}
                or {"total": total})
        cap = device_cap_bytes()
        if cap:
            if total > cap and not self._breach_open:
                self._breach_open = True
                self._emit_report("over_cap", context=context)
            elif total <= cap:
                if self._breach_open:
                    self._breach_open = False
                self._mark_fit(cats)
        else:
            self._mark_fit(cats)
        return total

    def _mark_fit(self, cats):
        self._last_fit = {c: b for c, b in cats.items() if b}

    def sweep(self):
        """Full jax.live_arrays() pass: every live device byte, matched
        against the ledger; unmatched arrays land in `unattributed`.
        Returns {total, categories, top, n_live, n_matched}."""
        import jax
        with self._lock:
            entries = dict(self._entries)
        cats = dict.fromkeys(CATEGORIES, 0)
        owners = collections.defaultdict(int)
        n_live = n_matched = 0
        total = 0
        for arr in jax.live_arrays():
            nbytes = getattr(arr, "nbytes", 0)
            n_live += 1
            total += nbytes
            e = entries.get(id(arr))
            if e is not None:
                n_matched += 1
                cats[e.category] += nbytes
                owners[(e.category, e.owner)] += nbytes
            else:
                cats["unattributed"] += nbytes
                owners[("unattributed", "?")] += nbytes
        top = [{"category": c, "owner": o, "bytes": b}
               for (c, o), b in sorted(owners.items(),
                                       key=lambda kv: -kv[1])]
        return {"total": total, "categories": cats, "top": top[:_TOP_N],
                "n_live": n_live, "n_matched": n_matched}

    # -- post-mortems -------------------------------------------------
    def _growth_since_fit(self, cats):
        if self._last_fit is None:
            return []
        growth = []
        for c in CATEGORIES:
            before = self._last_fit.get(c, 0)
            after = cats.get(c, 0)
            if after > before:
                growth.append({"category": c, "before": before,
                               "after": after, "delta": after - before,
                               "phrase": mem_component_phrase(c)})
        growth.sort(key=lambda g: -g["delta"])
        return growth

    def _emit_report(self, reason, error=None, context=None):
        total, cats, owners = self._live_totals()
        try:
            swept = self.sweep()
        except Exception:           # backend gone mid-OOM: ledger only
            swept = None
        if swept is not None:
            for c in CATEGORIES:
                cats[c] = max(cats[c], swept["categories"].get(c, 0))
            top = swept["top"]
            total = max(total, swept["total"])
        else:
            top = [{"category": c, "owner": o, "bytes": b}
                   for (c, o), b in sorted(owners.items(),
                                           key=lambda kv: -kv[1])]
        meta_by_owner = {}
        with self._lock:
            for e in self._entries.values():
                meta_by_owner[e.owner] = dict(e.meta,
                                              category=e.category)
        growth = self._growth_since_fit(cats)
        report = MemoryReport(
            reason, error=error, context=context,
            cap_bytes=device_cap_bytes(), total_bytes=total,
            peak_bytes=max(self._peak, total),
            categories={c: b for c, b in cats.items() if b},
            top=top, growth=growth,
            hints=_growth_hints(growth, cats, meta_by_owner),
            device=_memory.sample_device_memory(),
            timeline=list(self._timeline)[-64:])
        self._last_report = report
        from . import enabled as _tm_enabled
        if _tm_enabled():
            _registry.counter(f"memledger.reports.{reason}").inc()
        _LOG.warning("memledger %s report: top category %s, peak %s / "
                     "cap %s", reason, report.top_category,
                     fmt_bytes(report.peak_bytes),
                     fmt_bytes(report.cap_bytes)
                     if report.cap_bytes else "none")
        self._dump_via_flight(report)
        return report

    def _dump_via_flight(self, report):
        try:
            from ..diagnostics import recorder as _rec
        except Exception:
            return
        flight = _rec.active()
        if flight is None:
            return
        try:
            flight.event("memory_report", reason=report.reason,
                         top_category=report.top_category,
                         peak_bytes=report.peak_bytes)
            flight.dump(reason=f"memory_{report.reason}", report=report,
                        error=report.error)
        except Exception as e:
            _LOG.warning("flight dump of memory report failed: %s", e)

    def handle_possible_oom(self, exc, context=None):
        """Run-path hook: classify `exc`; when it is an allocator
        exhaustion, emit the post-mortem. Never raises — the original
        exception must propagate unchanged."""
        if not is_oom_error(exc):
            return None
        try:
            return self._emit_report("oom", error=f"{exc}",
                                     context=context)
        except Exception as e:
            _LOG.warning("OOM post-mortem itself failed: %s", e)
            return None

    # -- reconciliation -----------------------------------------------
    def reconcile(self, static, tolerance=0.25, label=""):
        """Measured peak vs meshlint's static floor. `static` is either
        plain bytes or a member_footprint() dict. Publishes the drift
        gauge; beyond tolerance also returns a WARNING Diagnostic in
        the tpulint format (None inside tolerance).

        The static floor counts params + optimizer + gradsync_ef +
        declared extra state; transient staging/feed/workspace bytes
        are runtime-only, so the measured side uses the same persistent
        categories."""
        if isinstance(static, dict):
            static_bytes = int(static.get("total", 0))
        else:
            static_bytes = int(static)
        measured = sum(self._cat_peak[c] for c in
                       ("params", "optimizer", "gradsync_ef",
                        "sparse_table", "kv_cache"))
        ratio = (measured / static_bytes) if static_bytes else 0.0
        drift = abs(ratio - 1.0) if static_bytes else 0.0
        ok = drift <= tolerance
        from . import enabled as _tm_enabled
        if _tm_enabled():
            _registry.gauge("memledger.static_drift_ratio").set(ratio)
            _registry.gauge("memledger.static_drift_alarm").set(
                0 if ok else 1)
        diag = None
        if not ok:
            from ..analysis.diagnostics import Diagnostic, WARNING
            diag = Diagnostic(
                WARNING, "memledger-drift",
                f"runtime footprint {fmt_bytes(measured)} vs static "
                f"prediction {fmt_bytes(static_bytes)} "
                f"(x{ratio:.2f}, tolerance x{1 + tolerance:.2f})"
                + (f" [{label}]" if label else ""),
                hint="the static device-footprint pass no longer "
                     "predicts this config — re-derive param specs / "
                     "extra_state_bytes or investigate the leak")
            _LOG.warning("%s", diag.message)
        return {"static_bytes": static_bytes,
                "measured_bytes": measured, "ratio": ratio,
                "ok": ok, "tolerance": tolerance,
                "diagnostic": diag}

    # -- read surfaces ------------------------------------------------
    def replica_peaks(self):
        """{owner: peak bytes} for serving replicas — owners named
        `replica<N>` (or `decode` pre-assignment) across categories.
        Feeds ScalePlanner's measured gate."""
        peaks = collections.defaultdict(int)
        for (c, o), b in self._owner_peak.items():
            if o.startswith("replica") or o == "decode":
                peaks[o] += b
        return dict(peaks)

    def snapshot_report(self):
        total, cats, owners = self._live_totals()
        cap = device_cap_bytes()
        return {
            "enabled": True, "steps": self._steps,
            "total_bytes": total, "peak_bytes": self._peak,
            "cap_bytes": cap,
            "categories": {c: b for c, b in cats.items() if b},
            "category_peaks": {c: b for c, b in self._cat_peak.items()
                               if b},
            "owners": [{"category": c, "owner": o, "bytes": b}
                       for (c, o), b in sorted(owners.items(),
                                               key=lambda kv: -kv[1])
                       ][:_TOP_N],
            "replica_peaks": self.replica_peaks(),
            "last_fit": self._last_fit,
            "device": _memory.sample_device_memory(),
            "timeline_len": len(self._timeline),
        }

    def timeline(self):
        return list(self._timeline)

    def last_report(self):
        return self._last_report

    def peak_bytes(self):
        return self._peak

    def take_peak(self):
        """Read-and-reset the peak watermark (bench per-stage stamps)."""
        p = self._peak
        self._peak = 0
        self._cat_peak.clear()
        return p

    def reset(self):
        with self._lock:
            self._entries.clear()
        self._peak = 0
        self._cat_peak.clear()
        self._owner_peak.clear()
        self._last_fit = None
        self._last_report = None
        self._breach_open = False
        self._timeline.clear()
        self._steps = 0


def _make_reaper(ledger, key):
    lref = weakref.ref(ledger)

    def _reap(_wr):
        l = lref()
        if l is not None:
            with l._lock:
                l._entries.pop(key, None)
    return _reap


def _iter_arrays(value):
    """Yield the jax arrays in a value: array / mapping / sequence,
    nested. Duck-typed on .nbytes + .dtype so numpy stays out."""
    if value is None:
        return
    if isinstance(value, dict):
        for v in value.values():
            yield from _iter_arrays(v)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _iter_arrays(v)
    elif hasattr(value, "nbytes") and hasattr(value, "dtype") \
            and type(value).__module__.startswith(("jax", "jaxlib")):
        yield value


_LEDGER = MemLedger()


def get():
    return _LEDGER


# module-level conveniences bound to the process ledger
def register(category, owner, value, **meta):
    return _LEDGER.register(category, owner, value, **meta)


def unregister_owner(owner):
    return _LEDGER.unregister_owner(owner)


def on_step(step=None, context=None):
    return _LEDGER.on_step(step=step, context=context)


def sweep():
    return _LEDGER.sweep()


def snapshot_report():
    return _LEDGER.snapshot_report()


def handle_possible_oom(exc, context=None):
    return _LEDGER.handle_possible_oom(exc, context=context)


def reconcile(static, tolerance=0.25, label=""):
    return _LEDGER.reconcile(static, tolerance=tolerance, label=label)


def replica_peaks():
    return _LEDGER.replica_peaks()


def last_report():
    return _LEDGER.last_report()


def reset():
    return _LEDGER.reset()
