"""String/number compat helpers (ref python/paddle/compat.py).

The reference carries py2/py3 bridging utilities that user code and the
fluid tooling call (`to_text`/`to_bytes` on names read from serialized
program descs, banker's `round`, true `floor_division`). Python 2 is
gone, so the semantics here are the py3 branch of each reference
function, kept because the *API* is what downstream code imports.
"""
import math

__all__ = [
    "long_type", "int_type",
    "to_text", "to_bytes", "round", "floor_division",
    "get_exception_message",
]

int_type = int
long_type = int


def to_text(obj, encoding="utf-8", inplace=False):
    """Decode bytes (or containers of bytes) to str. Lists/sets are
    converted element-wise; `inplace` mutates the container."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_to_text(x, encoding) for x in obj]
            return obj
        return [_to_text(x, encoding) for x in obj]
    if isinstance(obj, set):
        new = {_to_text(x, encoding) for x in obj}
        if inplace:
            obj.clear()
            obj.update(new)
            return obj
        return new
    return _to_text(obj, encoding)


def _to_text(obj, encoding):
    if obj is None or isinstance(obj, str):
        return obj
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    return str(obj)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """Encode str (or containers of str) to bytes — inverse of to_text."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_to_bytes(x, encoding) for x in obj]
            return obj
        return [_to_bytes(x, encoding) for x in obj]
    if isinstance(obj, set):
        new = {_to_bytes(x, encoding) for x in obj}
        if inplace:
            obj.clear()
            obj.update(new)
            return obj
        return new
    return _to_bytes(obj, encoding)


def _to_bytes(obj, encoding):
    if obj is None or isinstance(obj, bytes):
        return obj
    if isinstance(obj, str):
        return obj.encode(encoding)
    return bytes(obj)


def round(x, d=0):
    """Half-away-from-zero rounding (the py2 semantics the reference
    preserves, vs py3's banker's rounding)."""
    if x is None:
        return None
    p = 10 ** d
    if x >= 0:
        return float(math.floor((x * p) + 0.5)) / p
    return float(math.ceil((x * p) - 0.5)) / p


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    """Message text of an exception instance."""
    assert exc is not None
    return str(exc)
