"""MovieLens CTR dataset (ref python/paddle/dataset/movielens.py).

Samples: ([user_id], [gender], [age_index], [job], [movie_id], [score]).
When the ml-1m.zip archive is present in the dataset cache, the real
parser reads ml-1m/{users,movies,ratings}.dat ('::'-separated, latin-1
— the GroupLens format the reference downloads), maps gender M/F → 0/1
and raw age → its age_table index, and splits train/test per rating
with the reference's seeded uniform(0,1) < test_ratio rule.
Synthetic fallback: preference structure (score correlates with
user/movie id buckets) so ranking models can learn offline.
"""
import os
import zipfile

import numpy as np

from . import common

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table", "movie_categories", "get_movie_title_dict"]

_USERS, _MOVIES, _JOBS = 6040, 3952, 21
age_table = [1, 18, 25, 35, 45, 50, 56]

_ARCHIVE = "ml-1m.zip"
_meta = None


def _archive_path():
    p = common.data_path("movielens", _ARCHIVE)
    return p if os.path.exists(p) else None


def _load_meta():
    """Parse users.dat + movies.dat once; returns (users, movies,
    categories, title_words) with users[uid] = (gender01, age_idx, job)."""
    global _meta
    if _meta is not None:
        return _meta
    path = _archive_path()
    users, movies, categories, title_words = {}, {}, {}, {}
    with zipfile.ZipFile(path) as z:
        with z.open("ml-1m/users.dat") as f:
            for line in f.read().decode("latin-1").splitlines():
                uid, gender, age, job, _zip = line.strip().split("::")
                users[int(uid)] = (0 if gender == "M" else 1,
                                   age_table.index(int(age)), int(job))
        with z.open("ml-1m/movies.dat") as f:
            for line in f.read().decode("latin-1").splitlines():
                mid, title, cats = line.strip().split("::")
                for c in cats.split("|"):
                    categories.setdefault(c, len(categories))
                for w in title.split():
                    title_words.setdefault(w.lower(), len(title_words))
                movies[int(mid)] = (title, cats.split("|"))
    _meta = (users, movies, categories, title_words)
    return _meta


def _real_reader(is_test, test_ratio=0.1, rand_seed=0):
    users, _, _, _ = _load_meta()
    path = _archive_path()

    def reader():
        rng = np.random.RandomState(rand_seed)
        with zipfile.ZipFile(path) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f.read().decode("latin-1").splitlines():
                    uid, mid, rating, _ts = line.strip().split("::")
                    if (rng.uniform() < test_ratio) != is_test:
                        continue
                    u = int(uid)
                    gender, age_idx, job = users[u]
                    yield ([u], [gender], [age_idx], [job],
                           [int(mid)], [float(rating)])
    return reader


def movie_categories():
    if _archive_path():
        return _load_meta()[2]
    return {f"cat{i}": i for i in range(18)}


def get_movie_title_dict():
    if _archive_path():
        return _load_meta()[3]
    return {f"t{i}": i for i in range(512)}


def max_user_id():
    if _archive_path():
        return max(_load_meta()[0])
    return _USERS


def max_movie_id():
    if _archive_path():
        return max(_load_meta()[1])
    return _MOVIES


def max_job_id():
    if _archive_path():
        return max(j for _, _, j in _load_meta()[0].values())
    return _JOBS - 1


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            u = int(rng.randint(1, _USERS + 1))
            m = int(rng.randint(1, _MOVIES + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, _JOBS))
            base = 1 + ((u * 7 + m * 13) % 9) / 2.0
            score = float(np.clip(base + 0.3 * rng.randn(), 1, 5))
            yield [u], [gender], [age], [job], [m], [score]
    return reader


def train(n_synthetic=2048):
    if _archive_path():
        return _real_reader(is_test=False)
    return _synthetic(n_synthetic, seed=0)


def test(n_synthetic=512):
    if _archive_path():
        return _real_reader(is_test=True)
    return _synthetic(n_synthetic, seed=1)
