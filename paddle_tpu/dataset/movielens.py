"""MovieLens CTR dataset (ref python/paddle/dataset/movielens.py).

Samples: (user_id, gender, age, job, movie_id, category, score). The
synthetic fallback generates preference structure (score correlates with
user/movie id buckets) so ranking models can learn.
"""
import numpy as np

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table"]

_USERS, _MOVIES, _JOBS = 6040, 3952, 21
age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    return _USERS


def max_movie_id():
    return _MOVIES


def max_job_id():
    return _JOBS - 1


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            u = int(rng.randint(1, _USERS + 1))
            m = int(rng.randint(1, _MOVIES + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, _JOBS))
            base = 1 + ((u * 7 + m * 13) % 9) / 2.0
            score = float(np.clip(base + 0.3 * rng.randn(), 1, 5))
            yield [u], [gender], [age], [job], [m], [score]
    return reader


def train(n_synthetic=2048):
    return _synthetic(n_synthetic, seed=0)


def test(n_synthetic=512):
    return _synthetic(n_synthetic, seed=1)
