"""MovieLens CTR dataset (ref python/paddle/dataset/movielens.py).

Samples: ([user_id], [gender], [age_index], [job], [movie_id], [score]).
When the ml-1m.zip archive is present in the dataset cache, the real
parser reads ml-1m/{users,movies,ratings}.dat ('::'-separated, latin-1
— the GroupLens format the reference downloads), maps gender M/F → 0/1
and raw age → its age_table index, and splits train/test per rating
with the reference's seeded uniform(0,1) < test_ratio rule.
Synthetic fallback: preference structure (score correlates with
user/movie id buckets) so ranking models can learn offline.
"""
import os
import zipfile

import numpy as np

from . import common

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table", "movie_categories", "get_movie_title_dict",
           "user_info", "movie_info", "MovieInfo", "UserInfo", "convert"]

_USERS, _MOVIES, _JOBS = 6040, 3952, 21
age_table = [1, 18, 25, 35, 45, 50, 56]

_ARCHIVE = "ml-1m.zip"
_meta = None


def _archive_path():
    p = common.data_path("movielens", _ARCHIVE)
    return p if os.path.exists(p) else None


def _load_meta():
    """Parse users.dat + movies.dat once; returns (users, movies,
    categories, title_words) with users[uid] = (gender01, age_idx, job)."""
    global _meta
    if _meta is not None:
        return _meta
    path = _archive_path()
    users, movies, categories, title_words = {}, {}, {}, {}
    with zipfile.ZipFile(path) as z:
        with z.open("ml-1m/users.dat") as f:
            for line in f.read().decode("latin-1").splitlines():
                uid, gender, age, job, _zip = line.strip().split("::")
                users[int(uid)] = (0 if gender == "M" else 1,
                                   age_table.index(int(age)), int(job))
        with z.open("ml-1m/movies.dat") as f:
            for line in f.read().decode("latin-1").splitlines():
                mid, title, cats = line.strip().split("::")
                for c in cats.split("|"):
                    categories.setdefault(c, len(categories))
                for w in title.split():
                    title_words.setdefault(w.lower(), len(title_words))
                movies[int(mid)] = (title, cats.split("|"))
    _meta = (users, movies, categories, title_words)
    return _meta


class MovieInfo:
    """Movie id, categories and title (ref movielens.py:48); value()
    encodes categories/title words through the module dicts."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        cats = movie_categories()
        words = get_movie_title_dict()
        return [self.index,
                [cats[c] for c in self.categories],
                [words[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    """User id, gender, age bucket, job (ref movielens.py:75)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), gender({'M' if self.is_male else 'F'}), "
                f"age({age_table[self.age]}), job({self.job_id})>")


def user_info():
    """{user_id: UserInfo} (ref movielens.py:233). Synthetic fallback:
    deterministic per-id attributes consistent across calls."""
    if _archive_path():
        users = _load_meta()[0]
        return {u: UserInfo(u, "M" if g == 0 else "F", age_table[a], j)
                for u, (g, a, j) in users.items()}
    return {u: UserInfo(u, "M" if (u * 7) % 2 == 0 else "F",
                        age_table[(u * 11) % len(age_table)],
                        (u * 13) % _JOBS)
            for u in range(1, _USERS + 1)}


def movie_info():
    """{movie_id: MovieInfo} (ref movielens.py:241)."""
    if _archive_path():
        movies = _load_meta()[1]
        return {m: MovieInfo(m, cats, title)
                for m, (title, cats) in movies.items()}
    cats = sorted(movie_categories())
    words = sorted(get_movie_title_dict())
    return {m: MovieInfo(m, [cats[(m * 5) % len(cats)]],
                         words[(m * 3) % len(words)])
            for m in range(1, _MOVIES + 1)}


def _real_reader(is_test, test_ratio=0.1, rand_seed=0):
    users, _, _, _ = _load_meta()
    path = _archive_path()

    def reader():
        rng = np.random.RandomState(rand_seed)
        with zipfile.ZipFile(path) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f.read().decode("latin-1").splitlines():
                    uid, mid, rating, _ts = line.strip().split("::")
                    if (rng.uniform() < test_ratio) != is_test:
                        continue
                    u = int(uid)
                    gender, age_idx, job = users[u]
                    yield ([u], [gender], [age_idx], [job],
                           [int(mid)], [float(rating)])
    return reader


def movie_categories():
    if _archive_path():
        return _load_meta()[2]
    return {f"cat{i}": i for i in range(18)}


def get_movie_title_dict():
    if _archive_path():
        return _load_meta()[3]
    return {f"t{i}": i for i in range(512)}


def max_user_id():
    if _archive_path():
        return max(_load_meta()[0])
    return _USERS


def max_movie_id():
    if _archive_path():
        return max(_load_meta()[1])
    return _MOVIES


def max_job_id():
    if _archive_path():
        return max(j for _, _, j in _load_meta()[0].values())
    return _JOBS - 1


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            u = int(rng.randint(1, _USERS + 1))
            m = int(rng.randint(1, _MOVIES + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, _JOBS))
            base = 1 + ((u * 7 + m * 13) % 9) / 2.0
            score = float(np.clip(base + 0.3 * rng.randn(), 1, 5))
            yield [u], [gender], [age], [job], [m], [score]
    return reader


def train(n_synthetic=2048):
    if _archive_path():
        return _real_reader(is_test=False)
    return _synthetic(n_synthetic, seed=0)


def test(n_synthetic=512):
    if _archive_path():
        return _real_reader(is_test=True)
    return _synthetic(n_synthetic, seed=1)


def convert(path):
    """Write the movielens splits as sharded RecordIO (ref
    movielens.py:262)."""
    from . import common
    common.convert(path, train(), 1000, "movielens_train")
    common.convert(path, test(), 1000, "movielens_test")
