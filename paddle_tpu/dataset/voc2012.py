"""PASCAL VOC2012 segmentation dataset (ref
python/paddle/dataset/voc2012.py).

Samples: (image [3,H,W] uint8, segmentation label [H,W] int32 with
class ids 0..20 and 255 = void border). Synthetic fallback: rectangular
object blobs whose pixel statistics correlate with their class id.
"""
import numpy as np

__all__ = ["train", "test", "val"]

CLASS_NUM = 21   # 20 object classes + background
VOID = 255
_HW = 64


def _synthetic(n, seed, hw=_HW):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            img = rng.randint(0, 80, (3, hw, hw)).astype("uint8")
            lab = np.zeros((hw, hw), "int32")
            for _obj in range(int(rng.randint(1, 4))):
                c = int(rng.randint(1, CLASS_NUM))
                x0, y0 = rng.randint(0, hw - 8, 2)
                w, h = rng.randint(6, hw // 2, 2)
                x1, y1 = min(hw, x0 + w), min(hw, y0 + h)
                lab[y0:y1, x0:x1] = c
                # class-correlated intensity so segmenters can learn
                img[:, y0:y1, x0:x1] = np.clip(
                    80 + c * 8 + rng.randint(-10, 10, (3, y1 - y0, x1 - x0)),
                    0, 255).astype("uint8")
                # 1-px void border like VOC annotations
                lab[y0:y1, x0] = VOID
                if x1 - 1 > x0:
                    lab[y0:y1, x1 - 1] = VOID
            yield img, lab
    return reader


def train(n_synthetic=256):
    return _synthetic(n_synthetic, seed=0)


def test(n_synthetic=64):
    return _synthetic(n_synthetic, seed=1)


def val(n_synthetic=64):
    return _synthetic(n_synthetic, seed=2)
