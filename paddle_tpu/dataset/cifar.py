"""CIFAR-10/100 dataset (ref python/paddle/dataset/cifar.py).

Samples: (image float32[3072] in [0,1], label int64). Synthetic fallback:
class-colored noise images (each class biases one color channel pattern).
"""
import os
import pickle
import tarfile
import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100", "convert"]

_IMG = 3 * 32 * 32


def _synthetic(n, num_classes, seed):
    rng = np.random.RandomState(seed)
    proto_rng = np.random.RandomState(42)
    protos = proto_rng.uniform(0, 1, size=(num_classes, _IMG)).astype("float32")

    def reader():
        for i in range(n):
            label = i % num_classes
            img = 0.7 * protos[label] + 0.3 * rng.rand(_IMG).astype("float32")
            yield img.astype("float32"), int(label)
    return reader


def _tar_reader(path, key, sub):
    def reader():
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if sub in m.name:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    for img, lbl in zip(d[b"data"], d[key]):
                        yield (img.astype("float32") / 255.0), int(lbl)
    return reader


def train10(n_synthetic=2048):
    p = common.data_path("cifar", "cifar-10-python.tar.gz")
    if os.path.exists(p):
        return _tar_reader(p, b"labels", "data_batch")
    return _synthetic(n_synthetic, 10, seed=0)


def test10(n_synthetic=512):
    p = common.data_path("cifar", "cifar-10-python.tar.gz")
    if os.path.exists(p):
        return _tar_reader(p, b"labels", "test_batch")
    return _synthetic(n_synthetic, 10, seed=1)


def train100(n_synthetic=2048):
    p = common.data_path("cifar", "cifar-100-python.tar.gz")
    if os.path.exists(p):
        return _tar_reader(p, b"fine_labels", "train")
    return _synthetic(n_synthetic, 100, seed=0)


def test100(n_synthetic=512):
    p = common.data_path("cifar", "cifar-100-python.tar.gz")
    if os.path.exists(p):
        return _tar_reader(p, b"fine_labels", "test")
    return _synthetic(n_synthetic, 100, seed=1)


def convert(path):
    """Write the cifar splits as sharded RecordIO (ref cifar.py:149)."""
    from . import common
    common.convert(path, train100(), 1000, "cifar_train100")
    common.convert(path, test100(), 1000, "cifar_test100")
    common.convert(path, train10(), 1000, "cifar_train10")
    common.convert(path, test10(), 1000, "cifar_test10")
