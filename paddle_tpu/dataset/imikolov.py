"""PTB-style LM dataset (ref python/paddle/dataset/imikolov.py).

Samples: n-gram tuples of word ids. Synthetic fallback: a Markov chain
with deterministic transition structure (learnable next-word signal).
"""
import numpy as np

__all__ = ["train", "test", "build_dict"]

_VOCAB = 2048


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic(n, window, seed):
    rng = np.random.RandomState(seed)
    # deterministic "grammar": next ~ (3*cur + noise) mod V
    def reader():
        cur = 1
        for _ in range(n):
            seq = []
            for _ in range(window):
                seq.append(cur)
                cur = (3 * cur + int(rng.randint(0, 5))) % _VOCAB
            yield tuple(np.asarray(seq, dtype="int64"))
    return reader


def train(word_idx=None, n=5, n_synthetic=2048):
    return _synthetic(n_synthetic, n, seed=0)


def test(word_idx=None, n=5, n_synthetic=512):
    return _synthetic(n_synthetic, n, seed=1)
