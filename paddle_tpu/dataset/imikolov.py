"""PTB-style LM dataset (ref python/paddle/dataset/imikolov.py).

Samples: n-gram tuples of word ids (DataType.NGRAM) or
(src_seq, trg_seq) pairs with <s>/<e> wrapping (DataType.SEQ). When the
simple-examples.tgz archive is in the dataset cache, the real parser
reads ./simple-examples/data/ptb.{train,valid}.txt from the tarball,
builds the frequency dict with the reference's min_word_freq cutoff
('<unk>' last), and yields the reference's exact n-gram / seq layouts.
Synthetic fallback: a Markov chain with deterministic transition
structure (learnable next-word signal).
"""
import os
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "build_dict", "DataType", "convert"]

_VOCAB = 2048
_ARCHIVE = "simple-examples.tgz"
_TRAIN = "./simple-examples/data/ptb.train.txt"
_VALID = "./simple-examples/data/ptb.valid.txt"


class DataType(object):
    NGRAM = 1
    SEQ = 2


def _archive_path():
    p = common.data_path("imikolov", _ARCHIVE)
    return p if os.path.exists(p) else None


def word_count(f, word_freq=None):
    """Count words in an open (binary) file; each line also counts one
    <s> and one <e> (ref imikolov.py word_count)."""
    if word_freq is None:
        word_freq = {}
    for line in f:
        for w in line.strip().split():
            w = w.decode() if isinstance(w, bytes) else w
            word_freq[w] = word_freq.get(w, 0) + 1
        word_freq["<s>"] = word_freq.get("<s>", 0) + 1
        word_freq["<e>"] = word_freq.get("<e>", 0) + 1
    return word_freq


def build_dict(min_word_freq=50):
    path = _archive_path()
    if not path:
        return {f"w{i}": i for i in range(_VOCAB)}
    with tarfile.open(path) as tf:
        freq = word_count(tf.extractfile(_VALID),
                          word_count(tf.extractfile(_TRAIN)))
    freq.pop("<unk>", None)  # re-added as the last index
    items = [x for x in freq.items() if x[1] > min_word_freq]
    items.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _real_reader(filename, word_idx, n, data_type):
    path = _archive_path()

    def reader():
        with tarfile.open(path) as tf:
            f = tf.extractfile(filename)
            UNK = word_idx["<unk>"]
            for line in f:
                line = line.decode() if isinstance(line, bytes) else line
                if data_type == DataType.NGRAM:
                    assert n > -1, "Invalid gram length"
                    words = ["<s>"] + line.strip().split() + ["<e>"]
                    if len(words) >= n:
                        ids = [word_idx.get(w, UNK) for w in words]
                        for i in range(n, len(ids) + 1):
                            yield tuple(ids[i - n:i])
                elif data_type == DataType.SEQ:
                    ids = [word_idx.get(w, UNK)
                           for w in line.strip().split()]
                    src_seq = [word_idx["<s>"]] + ids
                    trg_seq = ids + [word_idx["<e>"]]
                    if n > 0 and len(src_seq) > n:
                        continue
                    yield src_seq, trg_seq
                else:
                    raise ValueError(f"unknown data type {data_type}")
    return reader


def _synthetic(n, window, seed):
    rng = np.random.RandomState(seed)
    # deterministic "grammar": next ~ (3*cur + noise) mod V
    def reader():
        cur = 1
        for _ in range(n):
            seq = []
            for _ in range(window):
                seq.append(cur)
                cur = (3 * cur + int(rng.randint(0, 5))) % _VOCAB
            yield tuple(np.asarray(seq, dtype="int64"))
    return reader


def train(word_idx=None, n=5, data_type=DataType.NGRAM, n_synthetic=2048):
    if _archive_path() and word_idx:
        return _real_reader(_TRAIN, word_idx, n, data_type)
    return _synthetic(n_synthetic, n, seed=0)


def test(word_idx=None, n=5, data_type=DataType.NGRAM, n_synthetic=512):
    if _archive_path() and word_idx:
        return _real_reader(_VALID, word_idx, n, data_type)
    return _synthetic(n_synthetic, n, seed=1)


def convert(path):
    """Write the imikolov splits as sharded RecordIO (ref
    imikolov.py:157)."""
    from . import common
    n = 5
    w = build_dict()
    common.convert(path, train(w, n), 1000, "imikolov_train")
    common.convert(path, test(w, n), 1000, "imikolov_test")
