"""MNIST dataset (ref python/paddle/dataset/mnist.py).

Samples: (image float32[784] scaled to [-1,1], label int64). Falls back
to a deterministic synthetic digit generator (class-dependent blob
patterns — linearly separable enough for convergence tests) when the
real IDX files are not cached locally.
"""
import gzip
import os
import struct
import numpy as np

from . import common

__all__ = ["train", "test", "convert"]

_IMG = 784


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    protos = rng.RandomState if False else None
    # 10 fixed class prototypes; samples = prototype + noise
    proto_rng = np.random.RandomState(1234)
    prototypes = proto_rng.uniform(-1, 1, size=(10, _IMG)).astype("float32")

    def reader():
        for i in range(n):
            label = i % 10
            img = prototypes[label] + 0.3 * rng.randn(_IMG).astype("float32")
            yield np.clip(img, -1, 1).astype("float32"), int(label)
    return reader


def _idx_reader(img_path, lbl_path):
    def reader():
        with gzip.open(img_path, "rb") as fi, gzip.open(lbl_path, "rb") as fl:
            # IDX headers (ref mnist.py reader_creator): images
            # magic 2051 + count + rows + cols, labels magic 2049 + count
            magic_i, n_i, rows, cols = struct.unpack(">IIII", fi.read(16))
            magic_l, n_l = struct.unpack(">II", fl.read(8))
            if magic_i != 2051 or magic_l != 2049:
                raise ValueError(
                    f"bad IDX magic: images={magic_i} labels={magic_l}")
            if n_i != n_l:
                raise ValueError(f"image/label count mismatch {n_i}/{n_l}")
            if rows * cols != _IMG:
                raise ValueError(f"unexpected image size {rows}x{cols}")
            while True:
                lbl = fl.read(1)
                if not lbl:
                    break
                img = np.frombuffer(fi.read(_IMG), dtype=np.uint8)
                img = img.astype("float32") / 127.5 - 1.0
                yield img, int(lbl[0])
    return reader


def train(n_synthetic=2048):
    ip = common.data_path("mnist", "train-images-idx3-ubyte.gz")
    lp = common.data_path("mnist", "train-labels-idx1-ubyte.gz")
    if os.path.exists(ip) and os.path.exists(lp):
        return _idx_reader(ip, lp)
    return _synthetic(n_synthetic, seed=0)


def test(n_synthetic=512):
    ip = common.data_path("mnist", "t10k-images-idx3-ubyte.gz")
    lp = common.data_path("mnist", "t10k-labels-idx1-ubyte.gz")
    if os.path.exists(ip) and os.path.exists(lp):
        return _idx_reader(ip, lp)
    return _synthetic(n_synthetic, seed=1)


def convert(path):
    """Write the mnist splits as sharded RecordIO (ref mnist.py:133;
    the reference's "minist" prefix typo is kept for artifact-name
    compatibility)."""
    from . import common
    common.convert(path, train(), 1000, "minist_train")
    common.convert(path, test(), 1000, "minist_test")
