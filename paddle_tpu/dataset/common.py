"""Shared dataset utilities (ref python/paddle/dataset/common.py)."""
import os

DATA_HOME = os.path.expanduser(os.environ.get(
    "PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def data_path(*parts):
    return os.path.join(DATA_HOME, *parts)


def cached_exists(*parts):
    return os.path.exists(data_path(*parts))


def download(url, module_name, md5sum=None, save_name=None):
    """Zero-egress environment: only returns an existing cache path."""
    path = data_path(module_name, save_name or os.path.basename(url))
    if os.path.exists(path):
        return path
    raise IOError(
        f"dataset file {path} not present and downloads are disabled; "
        f"synthetic fallback should have been used")
