"""Shared dataset utilities (ref python/paddle/dataset/common.py)."""
import os

DATA_HOME = os.path.expanduser(os.environ.get(
    "PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def data_path(*parts):
    return os.path.join(DATA_HOME, *parts)


def cached_exists(*parts):
    return os.path.exists(data_path(*parts))


def download(url, module_name, md5sum=None, save_name=None):
    """Zero-egress environment: only returns an existing cache path."""
    path = data_path(module_name, save_name or os.path.basename(url))
    if os.path.exists(path):
        return path
    raise IOError(
        f"dataset file {path} not present and downloads are disabled; "
        f"synthetic fallback should have been used")


def md5file(fname):
    """Hex md5 of a file, streamed (ref common.py:58)."""
    import hashlib
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Split a reader's samples into files of `line_count` each (ref
    common.py:137). Returns the list of paths written."""
    import pickle
    dumper = dumper or pickle.dump
    if "%" not in suffix:
        raise ValueError("suffix must contain a %d-style slot")
    paths, buf, idx = [], [], 0

    def flush():
        nonlocal buf, idx
        if not buf:
            return
        path = suffix % idx
        with open(path, "wb") as f:
            dumper(buf, f)
        paths.append(path)
        buf, idx = [], idx + 1

    for sample in reader():
        buf.append(sample)
        if len(buf) == line_count:
            flush()
    flush()
    return paths


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Reader over this trainer's strided share of split() files (ref
    common.py:175): file i belongs to trainer i % trainer_count."""
    import glob as _glob
    import pickle
    loader = loader or pickle.load

    def reader():
        if not callable(loader):
            raise TypeError("loader should be callable")
        file_list = sorted(_glob.glob(files_pattern))
        for i, path in enumerate(file_list):
            if i % trainer_count != trainer_id:
                continue
            with open(path, "rb") as f:
                yield from loader(f)
    return reader


def convert(output_path, reader, line_count, name_prefix):
    """Write a reader's samples as sharded RecordIO files
    `<output_path>/<name_prefix>-%05d.recordio` of at most `line_count`
    records each (ref common.py:210, which used the C++ recordio
    module; here the repo's own native-backed writer). Returns the
    paths written."""
    from ..recordio_writer import convert_reader_to_recordio_files
    if line_count < 1:
        raise ValueError("line_count must be >= 1")
    return convert_reader_to_recordio_files(
        os.path.join(output_path, name_prefix + ".recordio"),
        line_count, reader)
