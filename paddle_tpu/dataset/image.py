"""Image preprocessing utilities (ref python/paddle/dataset/image.py).

The reference shells out to cv2; here every transform is pure numpy
(deterministic, no native deps — TPU input pipelines feed from host
numpy anyway). Images are HWC uint8/float arrays unless noted; `to_chw`
converts to the CHW layout the conv kernels use.
"""
import numpy as np

__all__ = ["resize_short", "to_chw", "center_crop", "random_crop",
           "left_right_flip", "simple_transform", "load_and_transform",
           "load_image", "load_image_bytes", "batch_images_from_tar"]


def _resize_bilinear(im, h, w):
    """HWC (or HW) bilinear resize in numpy."""
    src_h, src_w = im.shape[:2]
    if (src_h, src_w) == (h, w):
        return im
    ys = (np.arange(h) + 0.5) * src_h / h - 0.5
    xs = (np.arange(w) + 0.5) * src_w / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, src_h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, src_w - 1)
    y1 = np.clip(y0 + 1, 0, src_h - 1)
    x1 = np.clip(x0 + 1, 0, src_w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None]
    wx = np.clip(xs - x0, 0, 1)[None, :]
    if im.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = im[y0][:, x0].astype(np.float32)
    b = im[y0][:, x1].astype(np.float32)
    c = im[y1][:, x0].astype(np.float32)
    d = im[y1][:, x1].astype(np.float32)
    out = (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
           + c * wy * (1 - wx) + d * wy * wx)
    return out.astype(im.dtype) if im.dtype == np.uint8 else out


def resize_short(im, size):
    """Scale so the SHORT side equals `size`, keeping aspect ratio."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    return _resize_bilinear(im, nh, nw)


def to_chw(im, order=(2, 0, 1)):
    """HWC → CHW (ref image.py:to_chw)."""
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = max(0, (h - size) // 2)
    w0 = max(0, (w - size) // 2)
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    h0 = rng.randint(0, h - size + 1) if h > size else 0
    w0 = rng.randint(0, w - size + 1) if w > size else 0
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short + (random|center) crop (+ random flip in training)
    + CHW + float32 + optional mean subtraction — ref simple_transform."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(2):
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.asarray(mean, "float32")
        im -= mean if mean.ndim != 1 else mean[:, None, None]
    return im


def load_image(file, is_color=True):
    """Decode an image file to an HWC numpy array (PIL if available,
    else raw .npy — the offline path)."""
    if str(file).endswith(".npy"):
        return np.load(file)
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError(
            "no image decoder available offline; save arrays as .npy or "
            "install PIL") from e
    img = Image.open(file)
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image_bytes(data, is_color=True):
    import io
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("no image decoder available offline") from e
    img = Image.open(io.BytesIO(data))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pack tar'd images into pickled batch files (ref
    image.py:batch_images_from_tar); returns the meta file path."""
    import os
    import pickle
    import tarfile
    out_path = f"{data_file}_{dataset_name}_batch"
    os.makedirs(out_path, exist_ok=True)
    data, labels, file_id = [], [], 0
    with tarfile.open(data_file) as f:
        for mem in f.getmembers():
            if mem.name not in img2label:
                continue
            data.append(f.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                with open(f"{out_path}/batch_{file_id}", "wb") as o:
                    pickle.dump({"data": data, "label": labels}, o,
                                protocol=2)
                file_id += 1
                data, labels = [], []
    if data:
        with open(f"{out_path}/batch_{file_id}", "wb") as o:
            pickle.dump({"data": data, "label": labels}, o, protocol=2)
    meta = f"{out_path}/meta"
    with open(meta, "w") as o:
        o.write(f"{len(img2label)}\n")
    return meta
