"""UCI housing regression dataset (ref python/paddle/dataset/uci_housing.py).

Samples: (features float32[13], target float32[1]). Synthetic fallback is
a fixed linear model + noise so linear-regression convergence tests have
a recoverable signal.
"""
import os
import numpy as np

from . import common

__all__ = ["train", "test"]

_DIM = 13
_W = np.linspace(-1.5, 2.0, _DIM).astype("float32")
_B = 0.7


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            x = rng.uniform(-1, 1, _DIM).astype("float32")
            y = float(x @ _W + _B + 0.05 * rng.randn())
            yield x, np.array([y], dtype="float32")
    return reader


def _file_reader(path, start, end):
    # whitespace-separated 14-column UCI table; the reference normalizes
    # each feature to (x - mean) / (max - min) over the WHOLE file
    # before the 80/20 split (ref uci_housing.py load_data)
    data = np.loadtxt(path)
    mx, mn, avg = (data[:, :-1].max(0), data[:, :-1].min(0),
                   data[:, :-1].mean(0))
    feats = (data[:, :-1] - avg) / np.maximum(mx - mn, 1e-6)

    def reader():
        for i in range(start, min(end, len(data))):
            yield feats[i].astype("float32"), \
                np.array([data[i, -1]], dtype="float32")
    return reader


def train(n_synthetic=1024):
    p = common.data_path("uci_housing", "housing.data")
    if os.path.exists(p):
        return _file_reader(p, 0, 404)
    return _synthetic(n_synthetic, seed=0)


def test(n_synthetic=256):
    p = common.data_path("uci_housing", "housing.data")
    if os.path.exists(p):
        return _file_reader(p, 404, 506)
    return _synthetic(n_synthetic, seed=1)
