"""IMDB sentiment dataset (ref python/paddle/dataset/imdb.py).

Samples: (word-id list, label) with the reference's label convention
(pos=0, neg=1). When the aclImdb_v1.tar.gz archive is present in the
dataset cache, the real parser streams the tarball sequentially
(aclImdb/{train,test}/{pos,neg}/*.txt members), tokenizes each review
(punctuation stripped, lowercased, whitespace split — ref imdb.py
tokenize()), and builds the frequency-sorted word dict with the
reference's cutoff semantics. Synthetic fallback otherwise: two vocab
distributions (positive ids skew low, negative skew high) so sentiment
models can actually learn offline.
"""
import os
import re
import string
import tarfile

import numpy as np

from . import common

__all__ = ["build_dict", "train", "test", "word_dict", "convert"]

_VOCAB = 5147  # matches ref default vocab cutoff order of magnitude
_ARCHIVE = "aclImdb_v1.tar.gz"
_PUNCT = str.maketrans("", "", string.punctuation)


def _archive_path():
    p = common.data_path("imdb", _ARCHIVE)
    return p if os.path.exists(p) else None


def tokenize(pattern, path=None):
    """Stream reviews whose member name matches `pattern` from the
    aclImdb tarball; yields token lists. Sequential tar access (next()),
    matching the reference's streaming read."""
    path = path or _archive_path()
    with tarfile.open(path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if pattern.match(tf.name):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="ignore")
                yield (text.rstrip("\n\r").translate(_PUNCT)
                       .lower().split())
            tf = tarf.next()


def build_dict(pattern, cutoff, path=None):
    """Frequency dict over tokenized reviews; words with freq > cutoff
    get ids ordered by (-freq, word); '<unk>' is the last id."""
    word_freq = {}
    for doc in tokenize(pattern, path):
        for w in doc:
            word_freq[w] = word_freq.get(w, 0) + 1
    items = [x for x in word_freq.items() if x[1] > cutoff]
    items.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _real_reader(pos_pattern, neg_pattern, word_idx, path):
    UNK = word_idx["<unk>"]

    def load(pattern, label):
        return [([word_idx.get(w, UNK) for w in doc], label)
                for doc in tokenize(pattern, path)]

    ins = load(pos_pattern, 0) + load(neg_pattern, 1)

    def reader():
        for doc, label in ins:
            yield doc, label
    return reader


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for i in range(n):
            label = i % 2
            length = int(rng.randint(20, 120))
            if label == 1:
                ids = rng.zipf(1.7, size=length) % (_VOCAB // 2)
            else:
                ids = _VOCAB // 2 + (rng.zipf(1.7, size=length) % (_VOCAB // 2))
            yield ids.astype("int64").tolist(), int(label)
    return reader


def word_dict(cutoff=150):
    path = _archive_path()
    if path:
        return build_dict(
            re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
            cutoff, path)
    return {f"w{i}": i for i in range(_VOCAB - 1)} | {"<unk>": _VOCAB - 1}


def train(word_idx=None, n_synthetic=1024):
    path = _archive_path()
    if path and word_idx:
        return _real_reader(re.compile(r"aclImdb/train/pos/.*\.txt$"),
                            re.compile(r"aclImdb/train/neg/.*\.txt$"),
                            word_idx, path)
    return _synthetic(n_synthetic, seed=0)


def test(word_idx=None, n_synthetic=256):
    path = _archive_path()
    if path and word_idx:
        return _real_reader(re.compile(r"aclImdb/test/pos/.*\.txt$"),
                            re.compile(r"aclImdb/test/neg/.*\.txt$"),
                            word_idx, path)
    return _synthetic(n_synthetic, seed=1)


def convert(path):
    """Write the imdb splits as sharded RecordIO (ref imdb.py:145)."""
    from . import common
    w = word_dict()
    common.convert(path, train(w), 1000, "imdb_train")
    common.convert(path, test(w), 1000, "imdb_test")
