"""IMDB sentiment dataset (ref python/paddle/dataset/imdb.py).

Samples: (word-id list, label 0/1). Synthetic fallback: two vocab
distributions (positive ids skew low, negative skew high) so sentiment
models can actually learn.
"""
import numpy as np

__all__ = ["train", "test", "word_dict"]

_VOCAB = 5147  # matches ref default vocab cutoff order of magnitude


def word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for i in range(n):
            label = i % 2
            length = int(rng.randint(20, 120))
            if label == 1:
                ids = rng.zipf(1.7, size=length) % (_VOCAB // 2)
            else:
                ids = _VOCAB // 2 + (rng.zipf(1.7, size=length) % (_VOCAB // 2))
            yield ids.astype("int64").tolist(), int(label)
    return reader


def train(word_idx=None, n_synthetic=1024):
    return _synthetic(n_synthetic, seed=0)


def test(word_idx=None, n_synthetic=256):
    return _synthetic(n_synthetic, seed=1)
