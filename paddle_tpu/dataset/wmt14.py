"""WMT14 en→fr NMT dataset (ref python/paddle/dataset/wmt14.py).

Samples: (src_ids, trg_ids, trg_ids_next) where src has <s>/<e>
wrapping, trg starts with <s>, trg_next ends with <e> — the reference's
exact slot layout. Synthetic fallback: target is a deterministic
function of the source (shifted ids, reversed order) so seq2seq models
converge offline.
"""
import numpy as np

__all__ = ["train", "test", "get_dict", "convert"]

START = "<s>"
END = "<e>"
UNK = "<unk>"
START_IDX, END_IDX, UNK_IDX = 0, 1, 2


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict); reverse=True gives id → word like the ref."""
    words = [START, END, UNK] + [f"w{i}" for i in range(dict_size - 3)]
    d = {w: i for i, w in enumerate(words)}
    if reverse:
        rd = {i: w for w, i in d.items()}
        return rd, dict(rd)
    return d, dict(d)


def _synthetic(n, dict_size, seed, max_len=30):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            length = int(rng.randint(3, max_len))
            body = rng.randint(3, dict_size, length)
            src_ids = [START_IDX] + body.tolist() + [END_IDX]
            # deterministic "translation": shift + reverse
            trg_body = ((body[::-1] - 3 + 7) % (dict_size - 3) + 3).tolist()
            trg_ids = [START_IDX] + trg_body
            trg_ids_next = trg_body + [END_IDX]
            yield src_ids, trg_ids, trg_ids_next
    return reader


def train(dict_size=1000, n_synthetic=2048):
    return _synthetic(n_synthetic, dict_size, seed=0)


def test(dict_size=1000, n_synthetic=256):
    return _synthetic(n_synthetic, dict_size, seed=1)


def gen(dict_size=1000, n_synthetic=128):
    return _synthetic(n_synthetic, dict_size, seed=2)


def convert(path):
    """Write the wmt14 splits as sharded RecordIO (ref wmt14.py:172)."""
    from . import common
    dict_size = 30000
    common.convert(path, train(dict_size), 1000, "wmt14_train")
    common.convert(path, test(dict_size), 1000, "wmt14_test")
