"""Datasets.

Parity: python/paddle/dataset/* (mnist, cifar, uci_housing, imdb,
imikolov, movielens, wmt16). This environment has zero egress, so each
dataset uses a real on-disk cache when present and otherwise falls back
to a DETERMINISTIC synthetic generator with the exact same sample
schema/shapes as the reference loader — models and tests exercise the
same code paths either way.
"""
from . import mnist
from . import cifar
from . import uci_housing
from . import imdb
from . import imikolov
from . import movielens
from . import wmt16
from . import wmt14
from . import conll05
from . import sentiment
from . import flowers
from . import voc2012
from . import mq2007
from . import image
from . import common
