"""Oxford 102 Flowers dataset (ref python/paddle/dataset/flowers.py).

Samples: (image CHW float32 scaled to [0,1], label int 0..101).
Synthetic fallback: class-conditional color statistics (each class has a
distinct mean hue) so classifiers can learn offline.
"""
import numpy as np

__all__ = ["train", "test", "valid"]

CLASS_NUM = 102
_HW = 32   # synthetic resolution (ref resizes to 224 via mappers)


def _synthetic(n, seed, hw=_HW):
    rng = np.random.RandomState(seed)

    def reader():
        for i in range(n):
            label = int(rng.randint(0, CLASS_NUM))
            base = np.zeros((3, hw, hw), "float32")
            # distinct per-class channel means
            base[0] += (label % 7) / 7.0
            base[1] += (label % 11) / 11.0
            base[2] += (label % 13) / 13.0
            img = np.clip(base + rng.randn(3, hw, hw).astype("float32")
                          * 0.1, 0.0, 1.0)
            yield img, label
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False,
          n_synthetic=1024):
    r = _synthetic(n_synthetic, seed=0)
    return _apply(r, mapper, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False,
         n_synthetic=256):
    return _apply(_synthetic(n_synthetic, seed=1), mapper, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True,
          n_synthetic=256):
    return _apply(_synthetic(n_synthetic, seed=2), mapper, False)


def _apply(reader, mapper, cycle):
    def out():
        while True:
            for s in reader():
                yield mapper(s) if mapper else s
            if not cycle:
                break
    return out
