"""WMT16-style NMT dataset (ref python/paddle/dataset/wmt16.py).

Samples: (src ids, trg ids, trg_next ids). Synthetic fallback: a
deterministic "translation" (trg = reversed src shifted by vocab offset)
— a real learnable seq2seq mapping for Transformer convergence tests.
"""
import numpy as np

__all__ = ["train", "test", "get_dict"]

BOS, EOS, UNK = 0, 1, 2


def get_dict(lang="en", dict_size=10000):
    return {f"{lang}{i}": i for i in range(dict_size)}


def _synthetic(n, src_vocab, trg_vocab, seed, max_len=24):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            L = int(rng.randint(4, max_len))
            src = rng.randint(3, src_vocab, size=L).astype("int64")
            trg_core = ((src[::-1] + 7) % (trg_vocab - 3)) + 3
            trg = np.concatenate([[BOS], trg_core]).astype("int64")
            trg_next = np.concatenate([trg_core, [EOS]]).astype("int64")
            yield src.tolist(), trg.tolist(), trg_next.tolist()
    return reader


def train(src_dict_size=10000, trg_dict_size=10000, tag=None,
          n_synthetic=2048):
    return _synthetic(n_synthetic, src_dict_size, trg_dict_size, seed=0)


def test(src_dict_size=10000, trg_dict_size=10000, tag=None,
         n_synthetic=256):
    return _synthetic(n_synthetic, src_dict_size, trg_dict_size, seed=1)
