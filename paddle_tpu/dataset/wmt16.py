"""WMT16-style NMT dataset (ref python/paddle/dataset/wmt16.py).

Samples: (src ids, trg ids, trg_next ids) with <s>=0, <e>=1, <unk>=2.
When the wmt16.tar.gz archive is in the dataset cache, the real parser
reads the 'wmt16/{train,val,test}' members (one "en\tde" tokenized
sentence pair per line — the format the reference downloads), builds
frequency-capped dicts per language, and yields the reference's exact
slot layout (trg wrapped with BOS, trg_next with EOS). Synthetic
fallback: a deterministic "translation" (trg = reversed src shifted by
vocab offset) — a real learnable seq2seq mapping for Transformer
convergence tests.
"""
import os
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "validation", "get_dict", "fetch", "convert"]

BOS, EOS, UNK = 0, 1, 2
_BOS_MARK, _EOS_MARK, _UNK_MARK = "<s>", "<e>", "<unk>"
_ARCHIVE = "wmt16.tar.gz"
# canonical source the reference downloads from (fetch() only
# checks the cache here — zero egress)
_URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"


def _archive_path():
    p = common.data_path("wmt16", _ARCHIVE)
    return p if os.path.exists(p) else None


_dict_cache = {}


def _build_dict(lang, dict_size):
    """Frequency dict over the train member for `lang` ('en' = column 0,
    'de' = column 1); ids 0/1/2 are <s>/<e>/<unk>. Memoized — building
    is a full decompress+tokenize pass over the corpus."""
    key = (lang, dict_size, _archive_path())
    if key in _dict_cache:
        return _dict_cache[key]
    freq = {}
    with tarfile.open(_archive_path()) as tf:
        for line in tf.extractfile("wmt16/train"):
            parts = line.decode("utf-8", "ignore").strip().split("\t")
            if len(parts) != 2:
                continue
            for w in parts[0 if lang == "en" else 1].split():
                freq[w] = freq.get(w, 0) + 1
    items = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
    word_idx = {_BOS_MARK: BOS, _EOS_MARK: EOS, _UNK_MARK: UNK}
    for w, _ in items:
        if len(word_idx) >= dict_size:
            break
        if w not in word_idx:  # corpus may contain literal <s>/<e>/<unk>
            word_idx[w] = len(word_idx)
    _dict_cache[key] = word_idx
    return word_idx


def get_dict(lang="en", dict_size=10000, reverse=False):
    if _archive_path():
        d = _build_dict(lang, dict_size)
    else:
        d = {_BOS_MARK: BOS, _EOS_MARK: EOS, _UNK_MARK: UNK}
        d.update({f"{lang}{i}": i + 3 for i in range(dict_size - 3)})
    if reverse:
        return {i: w for w, i in d.items()}
    return d


def _real_reader(member, src_dict_size, trg_dict_size, src_lang="en"):
    path = _archive_path()
    src_dict = _build_dict(src_lang, src_dict_size)
    trg_lang = "de" if src_lang == "en" else "en"
    trg_dict = _build_dict(trg_lang, trg_dict_size)
    src_col = 0 if src_lang == "en" else 1

    def reader():
        with tarfile.open(path) as tf:
            for line in tf.extractfile(member):
                parts = line.decode("utf-8", "ignore").strip().split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [src_dict.get(w, UNK)
                           for w in parts[src_col].split()]
                trg_core = [trg_dict.get(w, UNK)
                            for w in parts[1 - src_col].split()]
                trg_ids = [BOS] + trg_core
                trg_ids_next = trg_core + [EOS]
                yield src_ids, trg_ids, trg_ids_next
    return reader


def _synthetic(n, src_vocab, trg_vocab, seed, max_len=24):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            L = int(rng.randint(4, max_len))
            src = rng.randint(3, src_vocab, size=L).astype("int64")
            trg_core = ((src[::-1] + 7) % (trg_vocab - 3)) + 3
            trg = np.concatenate([[BOS], trg_core]).astype("int64")
            trg_next = np.concatenate([trg_core, [EOS]]).astype("int64")
            yield src.tolist(), trg.tolist(), trg_next.tolist()
    return reader


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en",
          n_synthetic=2048):
    if _archive_path():
        return _real_reader("wmt16/train", src_dict_size, trg_dict_size,
                            src_lang)
    return _synthetic(n_synthetic, src_dict_size, trg_dict_size, seed=0)


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en",
         n_synthetic=256):
    if _archive_path():
        return _real_reader("wmt16/test", src_dict_size, trg_dict_size,
                            src_lang)
    return _synthetic(n_synthetic, src_dict_size, trg_dict_size, seed=1)


def validation(src_dict_size=10000, trg_dict_size=10000, src_lang="en",
               n_synthetic=256):
    if _archive_path():
        return _real_reader("wmt16/val", src_dict_size, trg_dict_size,
                            src_lang)
    return _synthetic(n_synthetic, src_dict_size, trg_dict_size, seed=2)


def convert(path, src_dict_size=30000, trg_dict_size=30000,
            src_lang="en"):
    """Write the wmt16 splits as sharded RecordIO (ref wmt16.py:331)."""
    from . import common
    common.convert(path, train(src_dict_size, trg_dict_size, src_lang),
                   1000, "wmt16_train")
    common.convert(path, test(src_dict_size, trg_dict_size, src_lang),
                   1000, "wmt16_test")


def fetch():
    """Ensure the wmt16 archive is in the dataset cache (ref
    wmt16.py:324 downloads it; this environment is zero-egress, so
    fetch only verifies presence and raises with placement
    instructions otherwise)."""
    return common.download(_URL, "wmt16", save_name=_ARCHIVE)
