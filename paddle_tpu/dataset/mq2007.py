"""MQ2007 learning-to-rank dataset (ref python/paddle/dataset/mq2007.py).

Three sample formats, matching the reference generators:
- pointwise: (score float, 46-dim feature vector)
- pairwise:  (d_high [46], d_low [46]) with rel(high) > rel(low)
- listwise:  (label_list, feature_list) per query

Synthetic fallback: relevance is a noisy linear function of the
features, so rankers can fit offline.
"""
import numpy as np

__all__ = ["train", "test"]

FEATURE_DIM = 46
_W = None


def _weights():
    global _W
    if _W is None:
        _W = np.random.RandomState(7).randn(FEATURE_DIM) * 0.3
    return _W


def _queries(n_queries, seed):
    rng = np.random.RandomState(seed)
    w = _weights()
    out = []
    for _ in range(n_queries):
        n_docs = int(rng.randint(5, 20))
        feats = rng.rand(n_docs, FEATURE_DIM).astype("float32")
        score = feats @ w + rng.randn(n_docs) * 0.1
        rel = np.digitize(score, np.quantile(score, [0.5, 0.8]))
        out.append((rel.astype("int64"), feats))
    return out


def _reader(n_queries, seed, format):
    qs = _queries(n_queries, seed)
    rng = np.random.RandomState(seed + 99)

    def pointwise():
        for rel, feats in qs:
            for r, f in zip(rel, feats):
                yield float(r), f

    def pairwise():
        for rel, feats in qs:
            idx = np.arange(len(rel))
            for i in idx:
                for j in idx:
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]

    def listwise():
        for rel, feats in qs:
            yield rel.tolist(), feats

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise", n_queries=64):
    return _reader(n_queries, seed=0, format=format)


def test(format="pairwise", n_queries=16):
    return _reader(n_queries, seed=1, format=format)
