"""MQ2007 learning-to-rank dataset (ref python/paddle/dataset/mq2007.py).

Three sample formats, matching the reference generators:
- pointwise: (score float, 46-dim feature vector)
- pairwise:  (d_high [46], d_low [46]) with rel(high) > rel(low)
- listwise:  (label_list, feature_list) per query

When train.txt/test.txt in the LETOR 4.0 line format
(``rel qid:N 1:v 2:v ... 46:v #docid = ...``) are present in the
dataset cache, the real parser groups lines by query id and feeds the
same three generators. Synthetic fallback: relevance is a noisy linear
function of the features, so rankers can fit offline.
"""
import os

import numpy as np

from . import common

__all__ = ["train", "test", "FEATURE_DIM"]

FEATURE_DIM = 46
_W = None


def _weights():
    global _W
    if _W is None:
        _W = np.random.RandomState(7).randn(FEATURE_DIM) * 0.3
    return _W


def parse_letor_line(text):
    """One LETOR line → (rel int, qid int, feats float32[46]); the
    '#'-comment tail (docid etc.) is ignored."""
    head = text.split("#", 1)[0].strip()
    parts = head.split()
    rel = int(parts[0])
    qid = int(parts[1].split(":")[1])
    feats = np.zeros(FEATURE_DIM, dtype="float32")
    for p in parts[2:]:
        k, v = p.split(":")
        idx = int(k) - 1
        if 0 <= idx < FEATURE_DIM:
            feats[idx] = float(v)
    return rel, qid, feats


def _parse_file(path):
    """Group a LETOR file by query id (file order preserved); returns
    [(rel int64[n_docs], feats float32[n_docs, 46])]."""
    queries = {}
    order = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rel, qid, feats = parse_letor_line(line)
            if qid not in queries:
                queries[qid] = ([], [])
                order.append(qid)
            queries[qid][0].append(rel)
            queries[qid][1].append(feats)
    return [(np.asarray(queries[q][0], dtype="int64"),
             np.stack(queries[q][1]).astype("float32")) for q in order]


def _queries_synthetic(n_queries, seed):
    rng = np.random.RandomState(seed)
    w = _weights()
    out = []
    for _ in range(n_queries):
        n_docs = int(rng.randint(5, 20))
        feats = rng.rand(n_docs, FEATURE_DIM).astype("float32")
        score = feats @ w + rng.randn(n_docs) * 0.1
        rel = np.digitize(score, np.quantile(score, [0.5, 0.8]))
        out.append((rel.astype("int64"), feats))
    return out


def _reader(qs, format):
    def pointwise():
        for rel, feats in qs:
            for r, f in zip(rel, feats):
                yield float(r), f

    def pairwise():
        for rel, feats in qs:
            idx = np.arange(len(rel))
            for i in idx:
                for j in idx:
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]

    def listwise():
        for rel, feats in qs:
            yield rel.tolist(), feats

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def _make(fname, format, n_queries, seed):
    p = common.data_path("mq2007", fname)
    if os.path.exists(p):
        return _reader(_parse_file(p), format)
    return _reader(_queries_synthetic(n_queries, seed), format)


def train(format="pairwise", n_queries=64):
    return _make("train.txt", format, n_queries, seed=0)


def test(format="pairwise", n_queries=16):
    return _make("test.txt", format, n_queries, seed=1)
