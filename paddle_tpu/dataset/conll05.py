"""CoNLL-2005 SRL dataset (ref python/paddle/dataset/conll05.py).

Samples are the reference's 9 slots per (sentence, predicate) pair:
(word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_idx, mark,
label_idx) — the five context slots are the predicate window broadcast
over the sentence, mark flags the window, labels are BIO SRL tags.
Synthetic fallback: role labels correlate with position relative to the
predicate so an SRL tagger can actually learn.
"""
import numpy as np

__all__ = ["test", "get_dict", "get_embedding"]

WORD_DICT_LEN = 4000
PRED_DICT_LEN = 300
# BIO tagset: O + B/I for a handful of core roles + B-V
_ROLES = ["A0", "A1", "A2", "AM-TMP", "AM-LOC"]
_LABELS = ["O", "B-V"] + [f"{bi}-{r}" for r in _ROLES for bi in ("B", "I")]
LABEL_DICT_LEN = len(_LABELS)
UNK_IDX = 0


def get_dict():
    """(word_dict, verb_dict, label_dict) — name → id."""
    word_dict = {f"w{i}": i for i in range(WORD_DICT_LEN)}
    verb_dict = {f"v{i}": i for i in range(PRED_DICT_LEN)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding(emb_dim=32):
    """Deterministic word-embedding table (the reference ships a
    pretrained table; offline we provide a fixed random one)."""
    rng = np.random.RandomState(17)
    return rng.randn(WORD_DICT_LEN, emb_dim).astype("float32") * 0.1


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            sen_len = int(rng.randint(5, 40))
            words = rng.randint(1, WORD_DICT_LEN, sen_len)
            verb_index = int(rng.randint(0, sen_len))
            pred = int(rng.randint(0, PRED_DICT_LEN))
            # roles correlate with signed distance to the predicate
            labels = []
            for i in range(sen_len):
                d = i - verb_index
                if d == 0:
                    labels.append("B-V")
                elif -3 <= d < 0:
                    labels.append("B-A0" if d == -3 else "I-A0")
                elif 0 < d <= 3:
                    labels.append("B-A1" if d == 1 else "I-A1")
                else:
                    labels.append("O")
            label_dict = {l: i for i, l in enumerate(_LABELS)}

            def ctx(off, default):
                j = verb_index + off
                return int(words[j]) if 0 <= j < sen_len else default

            mark = [0] * sen_len
            for off in (-2, -1, 0, 1, 2):
                j = verb_index + off
                if 0 <= j < sen_len:
                    mark[j] = 1
            word_idx = words.tolist()
            bos, eos = 0, 0
            yield (word_idx,
                   [ctx(-2, bos)] * sen_len, [ctx(-1, bos)] * sen_len,
                   [ctx(0, bos)] * sen_len,
                   [ctx(1, eos)] * sen_len, [ctx(2, eos)] * sen_len,
                   [pred] * sen_len, mark,
                   [label_dict[l] for l in labels])
    return reader


def test(n_synthetic=256):
    return _synthetic(n_synthetic, seed=1)


def train(n_synthetic=1024):
    """The reference only ships test() publicly; train() is provided for
    the synthetic corpus so SRL models can fit something."""
    return _synthetic(n_synthetic, seed=0)
