"""CoNLL-2005 SRL dataset (ref python/paddle/dataset/conll05.py).

Samples are the reference's 9 slots per (sentence, predicate) pair:
(word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_idx, mark,
label_idx) — the five context slots are the predicate window broadcast
over the sentence, mark flags the window, labels are BIO SRL tags.

When the conll05st-tests.tar.gz archive (with the standard
conll05st-release/test.wsj/words|props .gz members) plus
wordDict.txt/verbDict.txt/targetDict.txt are in the dataset cache, the
real parser reads the words file (one token per line, blank line per
sentence) zipped against the props file (column 0 = predicate lemma or
'-', one bracket-tag column per predicate: '(A0*', '*', '*)' ...), and
converts bracket spans to B-/I-/O tags exactly like the reference.
Synthetic fallback: role labels correlate with position relative to the
predicate so an SRL tagger can actually learn.
"""
import gzip
import os
import tarfile

import numpy as np

from . import common

__all__ = ["test", "get_dict", "get_embedding", "convert"]

WORD_DICT_LEN = 4000
PRED_DICT_LEN = 300
# BIO tagset: O + B/I for a handful of core roles + B-V
_ROLES = ["A0", "A1", "A2", "AM-TMP", "AM-LOC"]
_LABELS = ["O", "B-V"] + [f"{bi}-{r}" for r in _ROLES for bi in ("B", "I")]
LABEL_DICT_LEN = len(_LABELS)
UNK_IDX = 0

_ARCHIVE = "conll05st-tests.tar.gz"
_WORDS = "conll05st-release/test.wsj/words/test.wsj.words.gz"
_PROPS = "conll05st-release/test.wsj/props/test.wsj.props.gz"


def _cache(*names):
    paths = [common.data_path("conll05st", n) for n in names]
    return paths if all(os.path.exists(p) for p in paths) else None


def load_dict(path):
    with open(path) as f:
        return {line.strip(): i for i, line in enumerate(f)}


def load_label_dict(path):
    """targetDict.txt lists B-*/I-* tags; ids pair Bs and Is per tag
    with O last (ref conll05.py load_label_dict)."""
    tags = set()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith(("B-", "I-")):
                tags.add(line[2:])
    d = {}
    for tag in sorted(tags):  # deterministic ids across processes
        d["B-" + tag] = len(d)
        d["I-" + tag] = len(d)
    d["O"] = len(d)
    return d


def _props_to_bio(col):
    """One props bracket column → BIO tag sequence."""
    out = []
    cur, inside = "O", False
    for tok in col:
        if tok == "*":
            out.append("I-" + cur if inside else "O")
        elif tok == "*)":
            out.append("I-" + cur)
            inside = False
        elif "(" in tok and ")" in tok:
            cur = tok[1:tok.find("*")]
            out.append("B-" + cur)
            inside = False
        elif "(" in tok:
            cur = tok[1:tok.find("*")]
            out.append("B-" + cur)
            inside = True
        else:
            raise RuntimeError(f"unexpected props label {tok!r}")
    return out


def corpus_reader(data_path, words_name=_WORDS, props_name=_PROPS):
    """Yield (sentence words, predicate lemma, BIO labels) per
    (sentence, predicate) pair from the words/props gz pair."""

    def reader():
        with tarfile.open(data_path) as tf, \
                gzip.GzipFile(fileobj=tf.extractfile(words_name)) as wf, \
                gzip.GzipFile(fileobj=tf.extractfile(props_name)) as pf:
            words, prop_rows = [], []
            for wline, pline in zip(wf, pf):
                word = wline.strip().decode("utf-8", "ignore")
                cols = pline.strip().decode("utf-8", "ignore").split()
                if not cols:  # blank line = end of sentence
                    if prop_rows:
                        lemmas = [r[0] for r in prop_rows]
                        verbs = [l for l in lemmas if l != "-"]
                        n_pred = len(prop_rows[0]) - 1
                        for i in range(n_pred):
                            col = [r[1 + i] for r in prop_rows]
                            yield words, verbs[i], _props_to_bio(col)
                    words, prop_rows = [], []
                else:
                    words.append(word)
                    prop_rows.append(cols)
    return reader


def reader_creator(corpus, word_dict, predicate_dict, label_dict):
    """9-slot transform (ref conll05.py reader_creator): predicate
    window of ±2 words broadcast over the sentence + mark flags."""

    def reader():
        for sentence, predicate, labels in corpus():
            sen_len = len(sentence)
            verb_index = labels.index("B-V")
            mark = [0] * sen_len

            def ctx(off, default):
                j = verb_index + off
                if 0 <= j < sen_len:
                    mark[j] = 1
                    return sentence[j]
                return default

            ctx_n2 = ctx(-2, "bos")
            ctx_n1 = ctx(-1, "bos")
            ctx_0 = ctx(0, "bos")
            ctx_p1 = ctx(1, "eos")
            ctx_p2 = ctx(2, "eos")

            def wids(w):
                return [word_dict.get(w, UNK_IDX)] * sen_len

            yield ([word_dict.get(w, UNK_IDX) for w in sentence],
                   wids(ctx_n2), wids(ctx_n1), wids(ctx_0),
                   wids(ctx_p1), wids(ctx_p2),
                   [predicate_dict.get(predicate, 0)] * sen_len, mark,
                   [label_dict.get(l, label_dict["O"]) for l in labels])
    return reader


def get_dict():
    """(word_dict, verb_dict, label_dict) — name → id."""
    cached = _cache("wordDict.txt", "verbDict.txt", "targetDict.txt")
    if cached:
        return (load_dict(cached[0]), load_dict(cached[1]),
                load_label_dict(cached[2]))
    word_dict = {f"w{i}": i for i in range(WORD_DICT_LEN)}
    verb_dict = {f"v{i}": i for i in range(PRED_DICT_LEN)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding(emb_dim=32):
    """Deterministic word-embedding table (the reference ships a
    pretrained table; offline we provide a fixed random one)."""
    rng = np.random.RandomState(17)
    return rng.randn(WORD_DICT_LEN, emb_dim).astype("float32") * 0.1


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            sen_len = int(rng.randint(5, 40))
            words = rng.randint(1, WORD_DICT_LEN, sen_len)
            verb_index = int(rng.randint(0, sen_len))
            pred = int(rng.randint(0, PRED_DICT_LEN))
            # roles correlate with signed distance to the predicate
            labels = []
            for i in range(sen_len):
                d = i - verb_index
                if d == 0:
                    labels.append("B-V")
                elif -3 <= d < 0:
                    labels.append("B-A0" if d == -3 else "I-A0")
                elif 0 < d <= 3:
                    labels.append("B-A1" if d == 1 else "I-A1")
                else:
                    labels.append("O")
            label_dict = {l: i for i, l in enumerate(_LABELS)}

            def ctx(off, default):
                j = verb_index + off
                return int(words[j]) if 0 <= j < sen_len else default

            mark = [0] * sen_len
            for off in (-2, -1, 0, 1, 2):
                j = verb_index + off
                if 0 <= j < sen_len:
                    mark[j] = 1
            word_idx = words.tolist()
            bos, eos = 0, 0
            yield (word_idx,
                   [ctx(-2, bos)] * sen_len, [ctx(-1, bos)] * sen_len,
                   [ctx(0, bos)] * sen_len,
                   [ctx(1, eos)] * sen_len, [ctx(2, eos)] * sen_len,
                   [pred] * sen_len, mark,
                   [label_dict[l] for l in labels])
    return reader


def test(n_synthetic=256):
    # the real path needs the archive AND the three dict files (separate
    # downloads in the reference) — with synthetic dicts every real word
    # would silently map to UNK
    cached = _cache(_ARCHIVE, "wordDict.txt", "verbDict.txt",
                    "targetDict.txt")
    if cached:
        word_dict, verb_dict, label_dict = get_dict()
        return reader_creator(corpus_reader(cached[0]), word_dict,
                              verb_dict, label_dict)
    return _synthetic(n_synthetic, seed=1)


def train(n_synthetic=1024):
    """The reference only ships test() publicly; train() is provided for
    the synthetic corpus so SRL models can fit something."""
    return _synthetic(n_synthetic, seed=0)


def convert(path):
    """Write the conll05 test split as sharded RecordIO (ref
    conll05.py:253 — the reference, too, only ships the test split)."""
    from . import common
    common.convert(path, test(), 1000, "conl105_test")
