"""NLTK movie-review sentiment dataset (ref
python/paddle/dataset/sentiment.py).

Samples: (word-id list, label 0/1). Synthetic fallback mirrors imdb's:
class-conditional vocab skew makes the task learnable offline.
"""
import numpy as np

__all__ = ["train", "test", "get_word_dict", "convert"]

_VOCAB = 2048


def get_word_dict():
    """word → id, most-frequent-first like the reference's build."""
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)

    def reader():
        for i in range(n):
            label = i % 2
            length = int(rng.randint(10, 60))
            if label:
                ids = rng.zipf(1.8, size=length) % (_VOCAB // 2)
            else:
                ids = _VOCAB // 2 + rng.zipf(1.8, size=length) % (_VOCAB // 2)
            yield ids.astype("int64").tolist(), int(label)
    return reader


def train(n_synthetic=800):
    return _synthetic(n_synthetic, seed=0)


def test(n_synthetic=200):
    return _synthetic(n_synthetic, seed=1)


def convert(path):
    """Write the sentiment splits as sharded RecordIO (ref
    sentiment.py:139)."""
    from . import common
    common.convert(path, train(), 1000, "sentiment_train")
    common.convert(path, test(), 1000, "sentiment_test")
