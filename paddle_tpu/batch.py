"""Top-level `batch` reader decorator (ref python/paddle/batch.py).

The reference exposes `paddle.batch` at the package top level in
addition to the reader-decorator module; user training loops call it
directly (`train_reader = paddle.batch(dataset.mnist.train(), 128)`).

Note the deliberate TPU deviation documented in reader/__init__.py:
`drop_last` defaults to True there because a ragged final batch changes
the feed shape and forces an XLA recompile. This top-level shim keeps
the REFERENCE default (False) for drop-in compatibility — callers who
keep the default get the reference's behavior, and the executor's
compile cache simply holds one extra entry for the tail batch.
"""
from .reader import batch as _batch

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    batch_size = int(batch_size)
    if batch_size <= 0:
        raise ValueError(
            f"batch_size should be a positive integer, got {batch_size}")
    return _batch(reader, batch_size, drop_last=drop_last)
