"""Raw operator construction helpers.

Parity: python/paddle/fluid/op.py — thin factory for appending a raw op
to a block by type string (the reference builds OpDesc protobufs from
the C++ OpProto registry; here the kernel registry is the authority).
"""
from .core.framework import default_main_program
from .ops.registry import has_kernel, KERNELS

__all__ = ["Operator", "OpDescCreationMethod"]


class OpDescCreationMethod:
    """Callable that appends an op of a fixed type (ref op.py's
    OpDescCreationMethod built per OpProto)."""

    def __init__(self, op_type):
        if not has_kernel(op_type):
            raise ValueError(f"unknown op type {op_type!r} "
                             f"({len(KERNELS)} registered)")
        self.op_type = op_type

    def __call__(self, inputs=None, outputs=None, attrs=None, block=None):
        block = block or default_main_program().current_block()
        return block.append_op(self.op_type, inputs or {}, outputs or {},
                               attrs or {})


class _OperatorFactory:
    """`Operator("relu", inputs={"X": [x]}, outputs={"Out": [y]})` —
    ref op.py:Operator factory. Slot direction isn't inferable without
    the reference's OpProto registry, so slots must come as explicit
    inputs=/outputs= dicts; bare slot kwargs raise instead of silently
    appending a disconnected op."""

    def types(self):
        return sorted(KERNELS)

    def __call__(self, op_type, inputs=None, outputs=None, attrs=None,
                 **kwargs):
        if kwargs:
            raise TypeError(
                f"pass op slots as inputs=/outputs= dicts, not bare "
                f"kwargs {sorted(kwargs)} (slot direction is ambiguous)")
        return OpDescCreationMethod(op_type)(inputs, outputs, attrs)


Operator = _OperatorFactory()
