"""Optimizers.

Parity: python/paddle/fluid/optimizer.py — SGD/Momentum/Adam/Adagrad/
Adadelta/RMSProp/Adamax/Ftrl/Lamb/LarsMomentum + ModelAverage/EMA.
`minimize(loss)` appends (1) the backward macro (core/backward.py),
(2) regularization ops, (3) clip ops, (4) one update op per parameter —
all into the SAME program, so the entire train step (fwd+bwd+update)
compiles as one XLA module with donated param buffers.
"""
import numpy as np

from . import unique_name
from .core.framework import default_startup_program, grad_var_name
from .core.backward import append_backward
from .initializer import ConstantInitializer
from .clip import append_gradient_clip_ops
from .regularizer import append_regularization_ops
from .layer_helper import LayerHelper

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "Adamax", "Adagrad", "Adadelta",
    "RMSProp", "Ftrl", "Lamb", "LarsMomentum", "DecayedAdagrad",
    "SGDOptimizer", "MomentumOptimizer", "AdamOptimizer", "AdamaxOptimizer",
    "AdagradOptimizer", "AdadeltaOptimizer", "RMSPropOptimizer",
    "FtrlOptimizer", "LambOptimizer", "LarsMomentumOptimizer",
    "DecayedAdagradOptimizer", "ModelAverage", "ExponentialMovingAverage",
]


class Optimizer:
    op_type = None
    health_monitor = None   # set by minimize(health=True)

    def __init__(self, learning_rate, regularization=None, name=None):
        self._lr = learning_rate
        self.regularization = regularization
        self._name = name
        self._accumulators = {}   # name -> {param_name: var}
        self._lr_var = None

    # ------------------------------------------------------------------
    def _create_lr_var(self, block):
        if hasattr(self._lr, "name"):       # scheduler output Variable
            self._lr_var = self._lr
            return
        if self._lr_var is not None:
            return
        helper = LayerHelper(self.__class__.__name__.lower() + "_lr")
        var = helper.create_global_variable(
            [1], "float32", persistable=True,
            name=unique_name.generate("learning_rate"))
        helper.set_variable_initializer(
            var, ConstantInitializer(float(self._lr)))
        self._lr_var = var

    def _add_accumulator(self, name, param, fill_value=0.0, shape=None,
                         dtype="float32"):
        helper = LayerHelper(f"{name}_acc")
        var = helper.create_global_variable(
            shape or list(param.shape), dtype, persistable=True,
            name=unique_name.generate(f"{param.name}_{name}"))
        helper.set_variable_initializer(var, ConstantInitializer(fill_value))
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, params):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    # ------------------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_sync=None):
        return append_backward(loss, parameter_list, no_grad_set,
                               grad_sync=grad_sync)

    def _append_sparse_optimize_op(self, block, param):
        raise NotImplementedError(
            f"{type(self).__name__} has no row-sparse update; use Adam "
            "or SGD for embedding(is_sparse=True) tables (the reference "
            "supports SelectedRows grads for the same pair — "
            "adam_op.h/sgd_op.h)")

    def apply_gradients(self, params_grads):
        block = params_grads[0][0].block.program.global_block()
        # row-sparse embedding tables bypass clip/regularization (the
        # reference's SelectedRows path likewise skips global-norm clip
        # and L2Decay densification) and get lazy row updates
        sparse = [(p, g) for p, g in params_grads
                  if getattr(p, "_sparse_lookup", None)]
        dense = [pg for pg in params_grads
                 if not getattr(pg[0], "_sparse_lookup", None)]
        if dense:
            dense = append_gradient_clip_ops(dense)
            dense = append_regularization_ops(dense, self.regularization)
        self._create_lr_var(block)
        self._create_accumulators(block, [p for p, _ in dense + sparse])
        ops = []
        for pg in dense:
            op = self._append_optimize_op(block, pg)
            op.attrs["is_optimizer_op"] = True
            ops.append(op)
        for p, _ in sparse:
            for op in self._append_sparse_optimize_op(block, p):
                op.attrs["is_optimizer_op"] = True
                ops.append(op)
        return ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, health=False, grad_sync=None):
        """`health=True` (or a dict of HealthMonitor options) appends
        the training-vitals fetches (global grad norm, param norm,
        update ratio) between the backward section and the update ops —
        see diagnostics/health.py; the monitor lands on
        `self.health_monitor`. Steps that don't fetch the vitals prune
        them away, so the option costs nothing until observed.

        `grad_sync` records a gradient-synchronization policy (e.g.
        "int8", "bf16:bucket_mb=2" — parallel/gradsync.py) as the
        program's default for ParallelExecutor; None (the default)
        keeps the implicit XLA all-reduce."""
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set, grad_sync=grad_sync)
        monitor = None
        if health:
            from .diagnostics.health import HealthMonitor
            opts = dict(health) if isinstance(health, dict) else {}
            # pre-update, pre-clip vitals: appended before the update
            # ops so the param norm reads this step's pre-step weights
            monitor = HealthMonitor.attach(loss, params_grads, **opts)
        opt_ops = self.apply_gradients(params_grads)
        if monitor is not None:
            monitor._append_update_ratio(self._lr_var)
            self.health_monitor = monitor
        return opt_ops, params_grads


class SGD(Optimizer):
    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "sgd",
            {"Param": [p], "Grad": [g], "LearningRate": [self._lr_var]},
            {"ParamOut": [p]}, {})

    def _append_sparse_optimize_op(self, block, p):
        # ONE op per table, all lookup taps merged: the kernel
        # concatenates ids+row-grads before dedup, so a table shared by
        # several lookups gets a single combined update (SelectedRows
        # MergeAdd semantics)
        from .core.framework import grad_var_name
        return [block.append_op(
            "sparse_sgd",
            {"Param": [p],
             "Grad": [block.var(grad_var_name(t["delta"]))
                      for t in p._sparse_lookup],
             "Ids": [block.var(t["ids"]) for t in p._sparse_lookup],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p]}, {})]


class Momentum(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False,
                 **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            {"Param": [p], "Grad": [g], "Velocity": [v],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p], "VelocityOut": [v]},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov})


class LarsMomentum(Momentum):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, momentum, **kw)
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            {"Param": [p], "Grad": [g], "Velocity": [v],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p], "VelocityOut": [v]},
            {"mu": self._momentum, "lars_coeff": self._lars_coeff,
             "lars_weight_decay": self._lars_weight_decay})


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, fill_value=1.0, shape=[1])
            self._add_accumulator("beta2_pow", p, fill_value=1.0, shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "adam",
            {"Param": [p], "Grad": [g],
             "Moment1": [self._get_accumulator("moment1", p)],
             "Moment2": [self._get_accumulator("moment2", p)],
             "Beta1Pow": [self._get_accumulator("beta1_pow", p)],
             "Beta2Pow": [self._get_accumulator("beta2_pow", p)],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p],
             "Moment1Out": [self._get_accumulator("moment1", p)],
             "Moment2Out": [self._get_accumulator("moment2", p)],
             "Beta1PowOut": [self._get_accumulator("beta1_pow", p)],
             "Beta2PowOut": [self._get_accumulator("beta2_pow", p)]},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon})

    def _append_sparse_optimize_op(self, block, p):
        """Lazy row-sparse Adam (ref optimizer.py lazy_mode +
        adam_op.h SparseAdamFunctor): ONE sparse_adam op per table with
        every lookup tap's (ids, row-grads) merged by the kernel before
        dedup — a shared table gets one combined update per step and
        the beta-pow accumulators advance exactly once."""
        from .core.framework import grad_var_name
        return [block.append_op(
            "sparse_adam",
            {"Param": [p],
             "Grad": [block.var(grad_var_name(t["delta"]))
                      for t in p._sparse_lookup],
             "Ids": [block.var(t["ids"]) for t in p._sparse_lookup],
             "Moment1": [self._get_accumulator("moment1", p)],
             "Moment2": [self._get_accumulator("moment2", p)],
             "Beta1Pow": [self._get_accumulator("beta1_pow", p)],
             "Beta2Pow": [self._get_accumulator("beta2_pow", p)],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p],
             "Moment1Out": [self._get_accumulator("moment1", p)],
             "Moment2Out": [self._get_accumulator("moment2", p)],
             "Beta1PowOut": [self._get_accumulator("beta1_pow", p)],
             "Beta2PowOut": [self._get_accumulator("beta2_pow", p)]},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon})]


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "adamax",
            {"Param": [p], "Grad": [g],
             "Moment": [self._get_accumulator("moment", p)],
             "InfNorm": [self._get_accumulator("inf_norm", p)],
             "Beta1Pow": [self._get_accumulator("beta1_pow", p)],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p],
             "MomentOut": [self._get_accumulator("moment", p)],
             "InfNormOut": [self._get_accumulator("inf_norm", p)],
             "Beta1PowOut": [self._get_accumulator("beta1_pow", p)]},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon})


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "adagrad",
            {"Param": [p], "Grad": [g],
             "Moment": [self._get_accumulator("moment", p)],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p], "MomentOut": [self._get_accumulator("moment", p)]},
            {"epsilon": self._epsilon})


class DecayedAdagrad(Adagrad):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, epsilon=epsilon, **kw)
        self._decay = decay

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "decayed_adagrad",
            {"Param": [p], "Grad": [g],
             "Moment": [self._get_accumulator("moment", p)],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p], "MomentOut": [self._get_accumulator("moment", p)]},
            {"decay": self._decay, "epsilon": self._epsilon})


class Adadelta(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "adadelta",
            {"Param": [p], "Grad": [g],
             "AvgSquaredGrad": [self._get_accumulator("avg_squared_grad", p)],
             "AvgSquaredUpdate": [self._get_accumulator("avg_squared_update", p)],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p],
             "AvgSquaredGradOut": [self._get_accumulator("avg_squared_grad", p)],
             "AvgSquaredUpdateOut": [self._get_accumulator("avg_squared_update", p)]},
            {"epsilon": self._epsilon, "rho": self._rho})


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("moment", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        ins = {"Param": [p], "Grad": [g],
               "MeanSquare": [self._get_accumulator("mean_square", p)],
               "Moment": [self._get_accumulator("moment", p)],
               "LearningRate": [self._lr_var]}
        outs = {"ParamOut": [p],
                "MeanSquareOut": [self._get_accumulator("mean_square", p)],
                "MomentOut": [self._get_accumulator("moment", p)]}
        if self._centered:
            ins["MeanGrad"] = [self._get_accumulator("mean_grad", p)]
            outs["MeanGradOut"] = [self._get_accumulator("mean_grad", p)]
        return block.append_op(
            "rmsprop", ins, outs,
            {"decay": self._rho, "epsilon": self._epsilon,
             "momentum": self._momentum, "centered": self._centered})


class Ftrl(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "ftrl",
            {"Param": [p], "Grad": [g],
             "SquaredAccumulator": [self._get_accumulator("squared", p)],
             "LinearAccumulator": [self._get_accumulator("linear", p)],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p],
             "SquaredAccumOut": [self._get_accumulator("squared", p)],
             "LinearAccumOut": [self._get_accumulator("linear", p)]},
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, fill_value=1.0, shape=[1])
            self._add_accumulator("beta2_pow", p, fill_value=1.0, shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "lamb",
            {"Param": [p], "Grad": [g],
             "Moment1": [self._get_accumulator("moment1", p)],
             "Moment2": [self._get_accumulator("moment2", p)],
             "Beta1Pow": [self._get_accumulator("beta1_pow", p)],
             "Beta2Pow": [self._get_accumulator("beta2_pow", p)],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [p],
             "Moment1Out": [self._get_accumulator("moment1", p)],
             "Moment2Out": [self._get_accumulator("moment2", p)],
             "Beta1PowOut": [self._get_accumulator("beta1_pow", p)],
             "Beta2PowOut": [self._get_accumulator("beta2_pow", p)]},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon, "weight_decay": self._wd})


class ExponentialMovingAverage:
    """EMA of parameters (ref optimizer.py:ExponentialMovingAverage).
    update() appends in-graph EMA ops; apply()/restore() swap scope values."""

    def __init__(self, decay=0.999, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._pairs = []
        self._counter_name = None

    def update(self):
        from .core.framework import default_main_program
        block = default_main_program().global_block()
        helper = LayerHelper(self._name)
        # step counter for bias correction (ref debiases by 1/(1-decay^t))
        counter = helper.create_global_variable(
            [1], "float32", persistable=True,
            name=unique_name.generate(f"{self._name}_step"))
        helper.set_variable_initializer(counter, ConstantInitializer(0.0))
        block.append_op("increment", {"X": [counter]}, {"Out": [counter]},
                        {"step": 1.0, "is_train_only": True})
        self._counter_name = counter.name
        for p in block.all_parameters():
            if not p.trainable:
                continue
            ema = helper.create_global_variable(
                list(p.shape), p.dtype, persistable=True,
                name=unique_name.generate(f"{p.name}_ema"))
            helper.set_variable_initializer(ema, ConstantInitializer(0.0))
            scaled_old = block.create_var(
                name=unique_name.generate(f"{p.name}_ema_t"),
                shape=p.shape, dtype=p.dtype, stop_gradient=True)
            block.append_op("scale", {"X": [ema]}, {"Out": [scaled_old]},
                            {"scale": self._decay, "is_train_only": True})
            scaled_new = block.create_var(
                name=unique_name.generate(f"{p.name}_ema_t"),
                shape=p.shape, dtype=p.dtype, stop_gradient=True)
            block.append_op("scale", {"X": [p]}, {"Out": [scaled_new]},
                            {"scale": 1.0 - self._decay,
                             "is_train_only": True})
            block.append_op("elementwise_add",
                            {"X": [scaled_old], "Y": [scaled_new]},
                            {"Out": [ema]},
                            {"axis": -1, "is_train_only": True})
            self._pairs.append((p.name, ema.name))

    def apply(self, executor, need_restore=True):
        import contextlib
        import numpy as _np

        @contextlib.contextmanager
        def guard():
            from .core.scope import global_scope
            scope = global_scope()
            t = 0.0
            if self._counter_name is not None:
                cv = scope.get(self._counter_name)
                if cv is not None:
                    t = float(_np.asarray(cv).reshape(-1)[0])
            debias = 1.0 - self._decay ** t if t > 0 else 1.0
            saved = {}
            for pname, ename in self._pairs:
                saved[pname] = scope.get(pname)
                ema_val = scope.get(ename)
                if ema_val is not None:
                    scope.set(pname, _np.asarray(ema_val) / max(debias, 1e-12))
            try:
                yield
            finally:
                if need_restore:
                    for pname, val in saved.items():
                        scope.set(pname, val)
        return guard()


class ModelAverage(Optimizer):
    """Sliding-window param average (ref optimizer.py:ModelAverage) —
    implemented as EMA (the TPU-friendly constant-memory equivalent)."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(learning_rate=0.0)
        decay = 1.0 - 1.0 / max(min_average_window, 2)
        self._ema = ExponentialMovingAverage(decay=decay)

    def update(self):
        self._ema.update()

    def apply(self, executor, need_restore=True):
        return self._ema.apply(executor, need_restore)

    def restore(self, executor):
        pass


# Fluid-style aliases (ref exposes both `SGD` and `SGDOptimizer`)
SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
AdagradOptimizer = Adagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
LambOptimizer = Lamb
LarsMomentumOptimizer = LarsMomentum
DecayedAdagradOptimizer = DecayedAdagrad
