"""Composite networks.

Parity: python/paddle/fluid/nets.py — simple_img_conv_pool,
img_conv_group, sequence_conv_pool, glu, scaled_dot_product_attention.
"""
from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "glu", "scaled_dot_product_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act)
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _expand(v):
        return [v] * len(conv_num_filter) if not isinstance(v, (list, tuple)) else v

    conv_padding = _expand(conv_padding)
    conv_filter_size = _expand(conv_filter_size)
    param_attr = _expand(param_attr)
    conv_with_batchnorm = _expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _expand(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=conv_filter_size[i], padding=conv_padding[i],
            param_attr=param_attr[i], act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, seq_len=None,
                       param_attr=None, act="sigmoid", pool_type="max"):
    """1-D sequence conv + pool over padded [B,T,D] input (ref nets.py)."""
    # conv over time: implement as conv2d on [B,1,T,D] with full-width kernel
    x = layers.unsqueeze(input, [1])
    conv = layers.conv2d(x, num_filters, (filter_size, int(input.shape[-1])),
                         padding=(filter_size // 2, 0), param_attr=param_attr,
                         act=act)
    conv = layers.squeeze(conv, [3])          # [B, F, T']
    conv = layers.transpose(conv, [0, 2, 1])  # [B, T', F]
    if seq_len is not None:
        return layers.sequence_pool(conv, pool_type, seq_len=seq_len)
    return layers.reduce_max(conv, dim=1)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    from .layers import ops
    return layers.elementwise_mul(a, ops.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    return layers.scaled_dot_product_attention(
        queries, keys, values, num_heads=num_heads,
        dropout_rate=dropout_rate)
