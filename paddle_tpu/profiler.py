"""Profiler.

Parity: python/paddle/fluid/profiler.py + platform/profiler.cc — here
backed by jax.profiler (XLA/TPU traces viewable in TensorBoard /
Perfetto) plus a host-side wall-clock summary table.
"""
import contextlib
import time
from collections import defaultdict

import jax

from . import telemetry as _tm

__all__ = ["cuda_profiler", "profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "record_event", "summary", "device_op_times", "profile_step_fn"]

_records = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
_trace_dir = None


def start_profiler(state="All", tracer_option=None, log_dir="/tmp/ptpu_prof"):
    global _trace_dir
    _trace_dir = log_dir
    try:
        jax.profiler.start_trace(log_dir)
    except Exception:
        _trace_dir = None


def stop_profiler(sorted_key="total", profile_path=None):
    global _trace_dir
    if _trace_dir is not None:
        try:
            jax.profiler.stop_trace()
        finally:
            _trace_dir = None
    return summary(sorted_key)


def reset_profiler():
    _records.clear()


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None,
             log_dir="/tmp/ptpu_prof"):
    start_profiler(state, log_dir=log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def record_event(name):
    """Host-side timing + device annotation (jax named scope). With
    telemetry enabled the same region is also a telemetry span, so
    profiler annotations land on the unified Chrome-trace timeline
    next to the executor's own spans instead of only in _records."""
    t0 = time.perf_counter()
    try:
        with _tm.span(name, cat="profiler"), \
                jax.profiler.TraceAnnotation(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        rec = _records[name]
        rec[0] += 1
        rec[1] += dt
        if _tm.enabled():
            _tm.histogram("profiler.event_seconds").observe(dt)


def summary(sorted_key="total"):
    rows = [(name, c, tot, tot / max(c, 1))
            for name, (c, tot) in _records.items()]
    rows.sort(key=lambda r: -r[2])
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(s)':>12}"]
    for name, c, tot, avg in rows:
        lines.append(f"{name:<40}{c:>8}{tot:>12.4f}{avg:>12.4f}")
    report = "\n".join(lines)
    return report


def device_op_times(trace_dir, family=True):
    """Parse the xplane.pb trace under `trace_dir` and return
    {op_name: total_device_seconds} aggregated over the device plane's
    'XLA Ops' lines. Wall-clock A/B through a remote TPU relay is
    ±5-20% noisy; the device-side event durations in the trace are the
    reliable signal. `family=True` collapses fusion instances
    ('fusion.123' → 'fusion') for a readable breakdown.

    The xplane proto has moved between TF releases
    (tensorflow.core.profiler → tensorflow.tsl.profiler → standalone
    tsl); try every known home, then fall back to a dependency-free
    wire-format decoder of the few fields this summary needs."""
    import glob
    import os
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                          "python")
    xplane_pb2 = _find_xplane_pb2()

    out = defaultdict(float)
    for path in glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True):
        with open(path, "rb") as f:
            data = f.read()
        if xplane_pb2 is not None:
            space = xplane_pb2.XSpace()
            space.ParseFromString(data)
            # filter before materializing: host planes can carry 100k+
            # python-trace events this summary would only discard
            planes = [
                (plane.name,
                 {mid: m.name for mid, m in plane.event_metadata.items()},
                 [(line.name,
                   [(ev.metadata_id, ev.duration_ps)
                    for ev in line.events])
                  for line in plane.lines if "XLA Ops" in line.name])
                for plane in space.planes
                if "TPU" in plane.name or "/device:" in plane.name]
        else:
            planes = _decode_xspace_minimal(data)
        for pname, ev_meta, lines in planes:
            if "TPU" not in pname and "/device:" not in pname:
                continue
            for lname, events in lines:
                if "XLA Ops" not in lname:
                    continue
                for metadata_id, duration_ps in events:
                    nm = ev_meta.get(metadata_id, str(metadata_id))
                    if family:
                        nm = nm.split(".")[0].rstrip("0123456789")
                    out[nm] += duration_ps * 1e-12
    return dict(out)


# every home the TF xplane proto has had across releases; the unit
# test imports this so its cross-check can never drift from production
_XPLANE_PB2_CANDIDATES = (
    "tensorflow.core.profiler.protobuf.xplane_pb2",
    "tensorflow.tsl.profiler.protobuf.xplane_pb2",
    "tsl.profiler.protobuf.xplane_pb2",
)


def _find_xplane_pb2():
    import importlib
    for mod in _XPLANE_PB2_CANDIDATES:
        try:
            return importlib.import_module(mod)
        except Exception:
            continue
    return None


def _pb_fields(buf):
    """Yield (field_number, wire_type, value) over a protobuf message.
    Values: varint int for wire type 0, bytes for type 2; types 1/5
    (fixed64/32) are skipped with correct framing; groups unsupported
    (absent from the xplane schema). Truncated input raises (a partial
    decode would silently understate device time downstream)."""
    i, n = 0, len(buf)
    while i < n:
        tag = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            tag |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wtype = tag >> 3, tag & 7
        if wtype == 0:
            val = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                val |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wtype, val
        elif wtype == 2:
            ln = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            if i + ln > n:
                raise ValueError(
                    f"truncated length-delimited field {field}: "
                    f"declared {ln} bytes, {n - i} remain")
            yield field, wtype, buf[i:i + ln]
            i += ln
        elif wtype == 1:
            if i + 8 > n:
                raise ValueError("truncated fixed64 field")
            i += 8
        elif wtype == 5:
            if i + 4 > n:
                raise ValueError("truncated fixed32 field")
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")


def _decode_xspace_minimal(data):
    """Hand-rolled XSpace decode (tsl/profiler/protobuf/xplane.proto):
    XSpace.planes=1; XPlane{name=2, lines=3, event_metadata=4(map)};
    XLine{name=2, events=4}; XEvent{metadata_id=1, duration_ps=3};
    XEventMetadata{id=1, name=2}. Returns the same
    [(plane_name, {mid: name}, [(line_name, [(mid, dur_ps)])])] shape
    the protobuf path produces."""
    planes = []
    for f, w, v in _pb_fields(data):
        if f != 1 or w != 2:
            continue
        pname, ev_meta, lines = "", {}, []
        for pf, pw, pv in _pb_fields(v):
            if pf == 2 and pw == 2:
                pname = pv.decode("utf-8", "replace")
            elif pf == 3 and pw == 2:  # XLine
                lname, events = "", []
                for lf, lw, lv in _pb_fields(pv):
                    if lf == 2 and lw == 2:
                        lname = lv.decode("utf-8", "replace")
                    elif lf == 4 and lw == 2:  # XEvent
                        mid = dur = 0
                        for ef, ew, evv in _pb_fields(lv):
                            if ef == 1 and ew == 0:
                                mid = evv
                            elif ef == 3 and ew == 0:
                                dur = evv
                        events.append((mid, dur))
                lines.append((lname, events))
            elif pf == 4 and pw == 2:  # map<int64, XEventMetadata>
                mid, mname = 0, ""
                for mf, mw, mv in _pb_fields(pv):
                    if mf == 1 and mw == 0:
                        mid = mv
                    elif mf == 2 and mw == 2:
                        for ef, ew, evv in _pb_fields(mv):
                            if ef == 1 and ew == 0:
                                mid = evv
                            elif ef == 2 and ew == 2:
                                mname = evv.decode("utf-8", "replace")
                ev_meta[mid] = mname
        planes.append((pname, ev_meta, lines))
    return planes


def profile_step_fn(fn, steps=10, trace_dir=None, readback=None):
    """Run `fn()` `steps` times under a device trace; return
    (per_step_device_seconds, {op_family: per_step_seconds}).

    `readback` (callable) forces completion before the trace stops —
    through the axon relay block_until_ready does not synchronize, so
    pass e.g. `lambda out: __import__('numpy').asarray(out)` applied to
    fn's result; default reads back fn's last return value."""
    import shutil
    import tempfile
    import numpy as np
    if trace_dir is None:
        # per-call dir: a fixed path would let concurrent profilers
        # delete or cross-pollute each other's xplane files
        trace_dir = tempfile.mkdtemp(prefix="ptpu_devprof_")
    shutil.rmtree(trace_dir, ignore_errors=True)
    fn()  # warm the compile cache outside the trace
    jax.profiler.start_trace(trace_dir)
    try:
        with _tm.span("profiler.profile_step_fn", steps=steps):
            out = None
            for _ in range(steps):
                out = fn()
            if readback is not None:
                readback(out)
            elif out is not None:
                np.asarray(jax.tree_util.tree_leaves(out)[0])
    finally:
        jax.profiler.stop_trace()
    ops = device_op_times(trace_dir)
    total = sum(ops.values())
    if total <= 0.0:
        # a 0.0 "per-step device time" would masquerade as evidence —
        # an unrecognized plane/line layout must be loud
        raise RuntimeError(
            f"no device-plane 'XLA Ops' events found in {trace_dir}; "
            "trace layout unrecognized for this backend")
    if _tm.enabled():
        # device op times join the host spans on one timeline (per-step
        # durations, laid back-to-back on a synthetic device track)
        _tm.merge_device_ops(ops, scale=steps)
        _tm.gauge("profiler.device_step_seconds").set(total / steps)
    return total / steps, {k: v / steps for k, v in ops.items()}


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Compat alias (ref profiler.py:cuda_profiler): profiles the device
    whatever it is — on TPU this simply delegates to profiler()."""
    with profiler("All", "total", output_file):
        yield
