"""Profiler.

Parity: python/paddle/fluid/profiler.py + platform/profiler.cc — here
backed by jax.profiler (XLA/TPU traces viewable in TensorBoard /
Perfetto) plus a host-side wall-clock summary table.
"""
import contextlib
import time
from collections import defaultdict

import jax

__all__ = ["cuda_profiler", "profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "record_event", "summary", "device_op_times", "profile_step_fn"]

_records = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
_trace_dir = None


def start_profiler(state="All", tracer_option=None, log_dir="/tmp/ptpu_prof"):
    global _trace_dir
    _trace_dir = log_dir
    try:
        jax.profiler.start_trace(log_dir)
    except Exception:
        _trace_dir = None


def stop_profiler(sorted_key="total", profile_path=None):
    global _trace_dir
    if _trace_dir is not None:
        try:
            jax.profiler.stop_trace()
        finally:
            _trace_dir = None
    return summary(sorted_key)


def reset_profiler():
    _records.clear()


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None,
             log_dir="/tmp/ptpu_prof"):
    start_profiler(state, log_dir=log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def record_event(name):
    """Host-side timing + device annotation (jax named scope)."""
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        rec = _records[name]
        rec[0] += 1
        rec[1] += dt


def summary(sorted_key="total"):
    rows = [(name, c, tot, tot / max(c, 1))
            for name, (c, tot) in _records.items()]
    rows.sort(key=lambda r: -r[2])
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(s)':>12}"]
    for name, c, tot, avg in rows:
        lines.append(f"{name:<40}{c:>8}{tot:>12.4f}{avg:>12.4f}")
    report = "\n".join(lines)
    return report


def device_op_times(trace_dir, family=True):
    """Parse the xplane.pb trace under `trace_dir` and return
    {op_name: total_device_seconds} aggregated over the device plane's
    'XLA Ops' lines. Wall-clock A/B through a remote TPU relay is
    ±5-20% noisy; the device-side event durations in the trace are the
    reliable signal. `family=True` collapses fusion instances
    ('fusion.123' → 'fusion') for a readable breakdown.

    Uses the TF xplane proto with the pure-python protobuf impl (the
    tensorboard converter path is version-broken in this image)."""
    import glob
    import os
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                          "python")
    from tensorflow.core.profiler.protobuf import xplane_pb2

    out = defaultdict(float)
    for path in glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True):
        space = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
        for plane in space.planes:
            name = plane.name
            if "TPU" not in name and "/device:" not in name:
                continue
            for line in plane.lines:
                if "XLA Ops" not in line.name:
                    continue
                ev_meta = plane.event_metadata
                for ev in line.events:
                    nm = ev_meta[ev.metadata_id].name
                    if family:
                        nm = nm.split(".")[0].rstrip("0123456789")
                    out[nm] += ev.duration_ps * 1e-12
    return dict(out)


def profile_step_fn(fn, steps=10, trace_dir=None, readback=None):
    """Run `fn()` `steps` times under a device trace; return
    (per_step_device_seconds, {op_family: per_step_seconds}).

    `readback` (callable) forces completion before the trace stops —
    through the axon relay block_until_ready does not synchronize, so
    pass e.g. `lambda out: __import__('numpy').asarray(out)` applied to
    fn's result; default reads back fn's last return value."""
    import shutil
    import tempfile
    import numpy as np
    if trace_dir is None:
        # per-call dir: a fixed path would let concurrent profilers
        # delete or cross-pollute each other's xplane files
        trace_dir = tempfile.mkdtemp(prefix="ptpu_devprof_")
    shutil.rmtree(trace_dir, ignore_errors=True)
    fn()  # warm the compile cache outside the trace
    jax.profiler.start_trace(trace_dir)
    try:
        out = None
        for _ in range(steps):
            out = fn()
        if readback is not None:
            readback(out)
        elif out is not None:
            np.asarray(jax.tree_util.tree_leaves(out)[0])
    finally:
        jax.profiler.stop_trace()
    ops = device_op_times(trace_dir)
    total = sum(ops.values())
    if total <= 0.0:
        # a 0.0 "per-step device time" would masquerade as evidence —
        # an unrecognized plane/line layout must be loud
        raise RuntimeError(
            f"no device-plane 'XLA Ops' events found in {trace_dir}; "
            "trace layout unrecognized for this backend")
    return total / steps, {k: v / steps for k, v in ops.items()}


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Compat alias (ref profiler.py:cuda_profiler): profiles the device
    whatever it is — on TPU this simply delegates to profiler()."""
    with profiler("All", "total", output_file):
        yield
