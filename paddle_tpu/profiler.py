"""Profiler.

Parity: python/paddle/fluid/profiler.py + platform/profiler.cc — here
backed by jax.profiler (XLA/TPU traces viewable in TensorBoard /
Perfetto) plus a host-side wall-clock summary table.
"""
import contextlib
import time
from collections import defaultdict

import jax

__all__ = ["cuda_profiler", "profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "record_event", "summary"]

_records = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
_trace_dir = None


def start_profiler(state="All", tracer_option=None, log_dir="/tmp/ptpu_prof"):
    global _trace_dir
    _trace_dir = log_dir
    try:
        jax.profiler.start_trace(log_dir)
    except Exception:
        _trace_dir = None


def stop_profiler(sorted_key="total", profile_path=None):
    global _trace_dir
    if _trace_dir is not None:
        try:
            jax.profiler.stop_trace()
        finally:
            _trace_dir = None
    return summary(sorted_key)


def reset_profiler():
    _records.clear()


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None,
             log_dir="/tmp/ptpu_prof"):
    start_profiler(state, log_dir=log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def record_event(name):
    """Host-side timing + device annotation (jax named scope)."""
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        rec = _records[name]
        rec[0] += 1
        rec[1] += dt


def summary(sorted_key="total"):
    rows = [(name, c, tot, tot / max(c, 1))
            for name, (c, tot) in _records.items()]
    rows.sort(key=lambda r: -r[2])
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(s)':>12}"]
    for name, c, tot, avg in rows:
        lines.append(f"{name:<40}{c:>8}{tot:>12.4f}{avg:>12.4f}")
    report = "\n".join(lines)
    return report


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Compat alias (ref profiler.py:cuda_profiler): profiles the device
    whatever it is — on TPU this simply delegates to profiler()."""
    with profiler("All", "total", output_file):
        yield
