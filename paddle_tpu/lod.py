"""LoD (level-of-detail / variable-length) tensor compatibility layer.

Parity: paddle/fluid/framework/lod_tensor.{h,cc} + python lod_tensor.py.
The reference packs ragged sequences into one flat tensor + offset table
(LoD). XLA needs static shapes, so here ragged data is PADDED to [B, T]
with an explicit lengths array — `to_padded`/`to_lod` convert both ways,
and sequence layers take (data, seq_len). SURVEY §6 documents the swap.
"""
import numpy as np

__all__ = ["LoDTensor", "LoDTensorArray", "create_lod_tensor", "to_padded",
           "to_ragged", "sequence_mask_np", "bucket_by_length"]


class LoDTensorArray(list):
    """Host-side growable vector of LoDTensors (ref framework
    LoDTensorArray). The IN-GRAPH analog — fixed-capacity device array +
    length scalar so it can ride lax.while_loop — is
    layers.control_flow.create_array; this list type serves the host API
    (e.g. executor feed/fetch of array variables)."""


class LoDTensor:
    """Padded array + lengths; .lod() emulates the reference accessor."""

    def __init__(self, data, seq_lens=None):
        self.data = np.asarray(data)
        self.seq_lens = (np.asarray(seq_lens, dtype=np.int64)
                         if seq_lens is not None else None)

    def lod(self):
        if self.seq_lens is None:
            return []
        offsets = np.concatenate([[0], np.cumsum(self.seq_lens)])
        return [offsets.tolist()]

    def set_lod(self, lod):
        if lod:
            offs = np.asarray(lod[0])
            self.seq_lens = (offs[1:] - offs[:-1]).astype(np.int64)

    def shape(self):
        return self.data.shape

    def __array__(self, dtype=None):
        return self.data if dtype is None else self.data.astype(dtype)


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """ref lod_tensor.py:create_lod_tensor — here: pad ragged rows."""
    if isinstance(data, list):
        lens = recursive_seq_lens[-1]
        return LoDTensor(*to_padded(data))
    lens = np.asarray(recursive_seq_lens[-1], dtype=np.int64)
    return LoDTensor(np.asarray(data), lens)


def to_padded(sequences, maxlen=None, pad_value=0, dtype=None):
    """ragged list[list|array] → (padded [B,T,...], lengths [B])."""
    seqs = [np.asarray(s) for s in sequences]
    lens = np.asarray([len(s) for s in seqs], dtype=np.int64)
    T = int(maxlen or (lens.max() if len(lens) else 0))
    tail = seqs[0].shape[1:] if seqs and seqs[0].ndim > 1 else ()
    dt = dtype or (seqs[0].dtype if seqs else np.float32)
    out = np.full((len(seqs), T) + tail, pad_value, dtype=dt)
    for i, s in enumerate(seqs):
        n = min(len(s), T)
        out[i, :n] = s[:n]
    return out, np.minimum(lens, T)


def to_ragged(padded, seq_lens):
    """(padded, lengths) → list of trimmed arrays."""
    return [padded[i, :int(n)] for i, n in enumerate(seq_lens)]


def sequence_mask_np(seq_lens, maxlen):
    seq_lens = np.asarray(seq_lens)
    return (np.arange(maxlen)[None, :] < seq_lens[:, None])


def bucket_by_length(reader, bucket_bounds, batch_size, len_fn=len):
    """Length-bucketing decorator: groups samples into per-bucket batches
    so padding waste (and XLA recompiles) stay bounded — the TPU answer to
    the reference's LoD dynamic batching."""
    def bucketed():
        buckets = {b: [] for b in bucket_bounds}
        for sample in reader():
            L = len_fn(sample)
            for b in bucket_bounds:
                if L <= b:
                    buckets[b].append(sample)
                    if len(buckets[b]) == batch_size:
                        yield b, buckets[b]
                        buckets[b] = []
                    break
        for b, items in buckets.items():
            if items:
                yield b, items
    return bucketed
