"""fluid.layers — the layer function library.

Parity: python/paddle/fluid/layers/__init__.py — re-exports nn, tensor,
ops, control_flow, io, learning_rate_scheduler, metric_op, detection.
"""
from . import nn
from .nn import *            # noqa: F401,F403
from . import tensor
from .tensor import *        # noqa: F401,F403
from . import ops
from .ops import *           # noqa: F401,F403
from . import control_flow
from .control_flow import *  # noqa: F401,F403
from . import learning_rate_scheduler
from .learning_rate_scheduler import *  # noqa: F401,F403
from . import metric_op
from .metric_op import *     # noqa: F401,F403
from . import io
from .io import *            # noqa: F401,F403
from . import sequence
from .sequence import *      # noqa: F401,F403
from . import struct
from .struct import *        # noqa: F401,F403
from . import vision
from .vision import *        # noqa: F401,F403
from . import detection
from .detection import *     # noqa: F401,F403
from . import layer_function_generator
from .layer_function_generator import *  # noqa: F401,F403
from . import device
from .device import get_places  # noqa: F401 (deprecated, import parity)
from . import utils
from . import math_op_patch

math_op_patch.monkey_patch_variable()

__all__ = []
__all__ += nn.__all__
__all__ += tensor.__all__
__all__ += ops.__all__
__all__ += control_flow.__all__
__all__ += learning_rate_scheduler.__all__
__all__ += metric_op.__all__
__all__ += io.__all__
__all__ += sequence.__all__
__all__ += detection.__all__
__all__ += layer_function_generator.__all__

# --- reference-location aliases -------------------------------------
# The reference's layers/nn.py (9.7k LoC) holds ops that live in
# sibling modules here (sequence.py, struct.py, vision.py, ...).
# Package-level imports (`fluid.layers.sequence_pool`) already work;
# these aliases also honor the reference SUBMODULE paths
# (`fluid.layers.nn.sequence_pool`, `layers.tensor.cast`, ...), pinned
# to the reference's export lists and enforced by
# tests/test_api_parity.py::test_layers_submodule_location_parity.
_REF_NN_EXTRA = [
    "linear_chain_crf", "crf_decoding", "chunk_eval", "sequence_conv",
    "sequence_pool", "sequence_softmax", "pool3d", "adaptive_pool3d",
    "beam_search_decode", "conv3d_transpose", "sequence_expand",
    "sequence_expand_as", "sequence_pad", "sequence_unpad",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "ctc_greedy_decoder", "edit_distance", "warpctc",
    "sequence_reshape", "hsigmoid", "beam_search", "row_conv",
    "multiplex", "autoincreased_step_counter", "lrn",
    "pad_constant_like", "roi_pool", "roi_align", "dice_loss",
    "sequence_scatter", "random_crop", "mean_iou", "relu", "selu",
    "log", "crop", "rank_loss", "elu", "stanh", "sequence_mask",
    "sequence_enumerate", "sequence_concat",
    "uniform_random_batch_size_like", "gaussian_random", "sampling_id",
    "gaussian_random_batch_size_like", "sum", "shape", "logical_and",
    "logical_or", "logical_xor", "logical_not", "space_to_depth",
    "affine_grid", "sequence_reverse", "similarity_focus", "hash",
    "merge_selected_rows", "get_tensor_from_selected_rows", "py_func",
    "psroi_pool",
]
_REF_TENSOR_EXTRA = ["cast", "tensor_array_to_tensor", "argmin",
                     "argmax", "argsort", "has_inf", "has_nan",
                     "isfinite"]
_REF_CONTROL_FLOW_EXTRA = ["increment"]
for _mod, _names in ((nn, _REF_NN_EXTRA), (tensor, _REF_TENSOR_EXTRA),
                     (control_flow, _REF_CONTROL_FLOW_EXTRA)):
    for _n in _names:
        if not hasattr(_mod, _n):
            setattr(_mod, _n, globals()[_n])
del _mod, _names, _n
