"""fluid.layers — the layer function library.

Parity: python/paddle/fluid/layers/__init__.py — re-exports nn, tensor,
ops, control_flow, io, learning_rate_scheduler, metric_op, detection.
"""
from . import nn
from .nn import *            # noqa: F401,F403
from . import tensor
from .tensor import *        # noqa: F401,F403
from . import ops
from .ops import *           # noqa: F401,F403
from . import control_flow
from .control_flow import *  # noqa: F401,F403
from . import learning_rate_scheduler
from .learning_rate_scheduler import *  # noqa: F401,F403
from . import metric_op
from .metric_op import *     # noqa: F401,F403
from . import io
from .io import *            # noqa: F401,F403
from . import sequence
from .sequence import *      # noqa: F401,F403
from . import struct
from .struct import *        # noqa: F401,F403
from . import vision
from .vision import *        # noqa: F401,F403
from . import detection
from .detection import *     # noqa: F401,F403
from . import layer_function_generator
from .layer_function_generator import *  # noqa: F401,F403
from . import device
from .device import get_places  # noqa: F401 (deprecated, import parity)
from . import utils
from . import math_op_patch

math_op_patch.monkey_patch_variable()

__all__ = []
__all__ += nn.__all__
__all__ += tensor.__all__
__all__ += ops.__all__
__all__ += control_flow.__all__
__all__ += learning_rate_scheduler.__all__
__all__ += metric_op.__all__
__all__ += io.__all__
__all__ += sequence.__all__
__all__ += detection.__all__
__all__ += layer_function_generator.__all__
