"""Structured-prediction layers: CRF, CTC, edit distance, beam search,
hierarchical sigmoid.

Parity: python/paddle/fluid/layers/nn.py {linear_chain_crf, crf_decoding,
warpctc, ctc_greedy_decoder, edit_distance, beam_search,
beam_search_decode, hsigmoid}. LoD inputs become padded arrays +
per-row length tensors (SURVEY §6); decode outputs are end/-1 padded
with explicit lengths instead of LoD levels.
"""
from ..layer_helper import LayerHelper

__all__ = [
    "linear_chain_crf", "crf_decoding", "warpctc", "ctc_greedy_decoder",
    "edit_distance", "beam_search", "beam_search_decode",
    "beam_search_loop", "hsigmoid",
]


def linear_chain_crf(input, label, param_attr=None, seq_len=None, name=None):
    """CRF negative log-likelihood [B,1]; creates transition param
    [N+2, N] (row0 start, row1 end) like the reference."""
    helper = LayerHelper("linear_chain_crf", name=name)
    n = int(input.shape[-1])
    w = helper.create_parameter(param_attr, shape=[n + 2, n],
                                dtype=input.dtype)
    B = input.shape[0]
    nll = helper.create_variable_for_type_inference(input.dtype, (B, 1))
    alpha = helper.create_variable_for_type_inference(input.dtype, (B, n), True)
    eexp = helper.create_variable_for_type_inference(input.dtype, input.shape, True)
    texp = helper.create_variable_for_type_inference(input.dtype, (n + 2, n), True)
    ins = {"Emission": [input], "Transition": [w], "Label": [label]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op("linear_chain_crf", ins,
                     {"LogLikelihood": [nll], "Alpha": [alpha],
                      "EmissionExps": [eexp], "TransitionExps": [texp]}, {})
    return nll


def crf_decoding(input, param_attr=None, label=None, seq_len=None, name=None):
    """Viterbi decode [B,T] (or 0/1 correctness vs label). param_attr must
    name the transition parameter created by linear_chain_crf."""
    helper = LayerHelper("crf_decoding", name=name)
    n = int(input.shape[-1])
    w = helper.create_parameter(param_attr, shape=[n + 2, n],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(
        "int64", tuple(input.shape[:-1]), True)
    ins = {"Emission": [input], "Transition": [w]}
    if label is not None:
        ins["Label"] = [label]
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op("crf_decoding", ins, {"ViterbiPath": [out]}, {})
    return out


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None, name=None):
    """CTC loss [B,1] from logits [B,T,C] and labels [B,L] (ref warpctc)."""
    helper = LayerHelper("warpctc", name=name)
    B = input.shape[0]
    loss = helper.create_variable_for_type_inference(input.dtype, (B, 1))
    grad = helper.create_variable_for_type_inference(
        input.dtype, input.shape, True)
    ins = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length]
    if label_length is not None:
        ins["LabelLength"] = [label_length]
    helper.append_op("warpctc", ins,
                     {"Loss": [loss], "WarpCTCGrad": [grad]},
                     {"blank": blank, "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """Greedy CTC decode: (ids [B,T] padded with -1, lengths [B])."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    B, T = input.shape[0], input.shape[1]
    out = helper.create_variable_for_type_inference("int64", (B, T), True)
    out_len = helper.create_variable_for_type_inference("int64", (B,), True)
    ins = {"X": [input]}
    if input_length is not None:
        ins["SeqLen"] = [input_length]
    helper.append_op("ctc_greedy_decoder", ins,
                     {"Out": [out], "OutLen": [out_len]}, {"blank": blank})
    return out, out_len


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance [B,1] (+ SequenceNum scalar, ref parity)."""
    helper = LayerHelper("edit_distance", name=name)
    B = input.shape[0]
    out = helper.create_variable_for_type_inference("float32", (B, 1), True)
    seq_num = helper.create_variable_for_type_inference("int64", (), True)
    ins = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        ins["HypsLength"] = [input_length]
    if label_length is not None:
        ins["RefsLength"] = [label_length]
    helper.append_op("edit_distance", ins,
                     {"Out": [out], "SequenceNum": [seq_num]},
                     {"normalized": normalized,
                      "ignored_tokens": list(ignored_tokens or [])})
    return out, seq_num


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None):
    """One beam expand+prune step on static [B,K(,V)] tensors.

    `scores` [B,K,V]: accumulated log-probs when is_accumulated (default,
    matching the reference), else per-step probabilities which the op
    combines as pre_scores + log(scores). Optional `ids` [B,K,V] carries
    candidate token ids (pre-pruned top-k); without it tokens are the
    vocabulary index. Returns (selected_ids, selected_scores, parent_idx),
    each [B,beam_size].
    """
    helper = LayerHelper("beam_search", name=name)
    B = pre_ids.shape[0]
    sel_ids = helper.create_variable_for_type_inference(
        "int64", (B, beam_size), True)
    sel_scores = helper.create_variable_for_type_inference(
        scores.dtype, (B, beam_size), True)
    parent = helper.create_variable_for_type_inference(
        "int64", (B, beam_size), True)
    ins = {"PreIds": [pre_ids], "PreScores": [pre_scores],
           "Scores": [scores]}
    if ids is not None:
        ins["Ids"] = [ids]
    helper.append_op("beam_search", ins,
                     {"SelectedIds": [sel_ids],
                      "SelectedScores": [sel_scores], "ParentIdx": [parent]},
                     {"beam_size": beam_size, "end_id": end_id,
                      "is_accumulated": is_accumulated})
    return sel_ids, sel_scores, parent


def beam_search_decode(ids, parents, scores=None, beam_size=None, end_id=0,
                       name=None):
    """Backtrace stacked per-step (ids, parents) [B,T,K] into sequences
    [B,K,T] (+ final scores). beam_size/end_id are accepted for reference
    API parity: the beam width is the static K dim, and finished beams
    already carry trailing end_id tokens from beam_search itself."""
    helper = LayerHelper("beam_search_decode", name=name)
    B, T, K = ids.shape
    if beam_size is not None and int(beam_size) != int(K):
        raise ValueError(f"beam_size {beam_size} != ids beam dim {K}")
    seqs = helper.create_variable_for_type_inference("int64", (B, K, T), True)
    ins = {"Ids": [ids], "Parents": [parents]}
    outs = {"SentenceIds": [seqs]}
    sc = None
    if scores is not None:
        ins["Scores"] = [scores]
        sc = helper.create_variable_for_type_inference(
            scores.dtype, tuple(scores.shape), True)
        outs["SentenceScores"] = [sc]
    helper.append_op("beam_search_decode", ins, outs, {})
    return (seqs, sc) if scores is not None else seqs


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid loss [B,1] over a complete binary tree
    (custom trees of the reference are not supported — raise instead)."""
    if is_custom or path_table is not None or path_code is not None:
        raise NotImplementedError(
            "hsigmoid custom trees: only the default complete binary tree "
            "is supported")
    helper = LayerHelper("hsigmoid", name=name)
    d = int(input.shape[-1])
    w = helper.create_parameter(param_attr, shape=[num_classes - 1, d],
                                dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[num_classes - 1],
                                dtype=input.dtype, is_bias=True)
    B = input.shape[0]
    loss = helper.create_variable_for_type_inference(input.dtype, (B, 1))
    depth = max(int(num_classes - 1).bit_length(), 1)
    pre = helper.create_variable_for_type_inference(
        input.dtype, (B, depth), True)
    ins = {"X": [input], "W": [w], "Label": [label]}
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op("hsigmoid", ins, {"Out": [loss], "PreOut": [pre]},
                     {"num_classes": num_classes})
    return loss


def beam_search_loop(init_ids, states, step_fn, beam_size, max_len, end_id,
                     vocab_size, name=None):
    """Whole-sequence beam search as ONE compiled loop.

    TPU-native replacement for the reference's imperative decode (While +
    beam_search + LoDTensorArray): `step_fn(ids [B*beam], states dict) ->
    (log_probs [B*beam, V], new_states)` must be jax-traceable (jnp ops,
    not layer calls). Returns (sentence_ids [B, beam, max_len] Variable,
    scores [B, beam] Variable).
    """
    from ..ops.kernels_struct import register_beam_step_fn
    helper = LayerHelper("beam_search_loop", name=name)
    state_names = list(states)
    B = int(init_ids.shape[0])
    seqs = helper.create_variable_for_type_inference(
        "int64", (B, beam_size, max_len), True)
    scores = helper.create_variable_for_type_inference(
        "float32", (B, beam_size), True)
    helper.append_op(
        "beam_search_loop",
        {"InitIds": [init_ids], "States": [states[n] for n in state_names]},
        {"SentenceIds": [seqs], "SentenceScores": [scores]},
        {"fn_id": register_beam_step_fn(step_fn),
         "state_names": state_names, "beam_size": beam_size,
         "max_len": max_len, "end_id": end_id, "vocab_size": vocab_size})
    return seqs, scores
