"""Neural-network layers.

Parity: python/paddle/fluid/layers/nn.py — same signatures/semantics
(fc composes mul+add+act like the reference LayerHelper does), but every
op lowers through the jnp kernels in ops/kernels_* and compiles as part
of one XLA module. Shapes may use -1 for batch dims.
"""
import numpy as np

from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer, NormalInitializer
from ..core.dtypes import convert_dtype
from .utils import convert_to_list

__all__ = [
    "fc", "embedding", "conv2d", "conv2d_transpose", "conv3d", "pool2d",
    "adaptive_pool2d", "batch_norm", "layer_norm", "group_norm",
    "instance_norm", "dropout", "softmax", "log_softmax",
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost", "huber_loss",
    "hinge_loss", "bpr_loss", "margin_rank_loss", "log_loss", "kldiv_loss",
    "mse_loss", "smooth_l1", "label_smooth", "one_hot", "nce",
    "sampled_softmax_with_cross_entropy",
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "lstm_unit", "gru_unit",
    "lstm",
    "matmul", "mul", "bmm", "dot", "transpose", "reshape", "squeeze",
    "unsqueeze", "flatten", "stack", "unstack", "expand", "expand_as",
    "slice", "strided_slice", "gather", "gather_nd", "scatter",
    "scatter_nd_add", "topk", "argsort", "argmax", "argmin", "where",
    "cond_select", "split", "l2_normalize", "mean", "reduce_sum",
    "reduce_mean", "reduce_max", "reduce_min", "reduce_prod", "reduce_all",
    "reduce_any", "cumsum", "clip", "clip_by_norm", "elementwise_add",
    "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow",
    "elementwise_mod", "scale", "cast", "pad", "pad2d", "prelu",
    "brelu", "leaky_relu", "soft_relu", "relu6", "pow", "hard_sigmoid",
    "swish", "hard_swish", "image_resize", "image_resize_short", "resize_bilinear",
    "resize_nearest", "grid_sampler", "affine_channel", "shuffle_channel",
    "scaled_dot_product_attention", "multi_head_attention",
    "add_position_encoding", "lod_reset", "im2sequence",
    "logsumexp", "bilinear_tensor_product", "isfinite", "cos_sim",
    "unique_with_counts_stub", "maxout", "pixel_shuffle",
]


def _dims(shape):
    return [int(s) for s in shape]


def _same_shape_out(helper, x, type, attrs=None, extra_inputs=None, act=None):
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    ins = {"X": [x]}
    if extra_inputs:
        ins.update(extra_inputs)
    helper.append_op(type, ins, {"Out": [out]}, attrs or {})
    return helper.append_activation(out, act)


# ---------------------------------------------------------------------------
# dense / embedding
# ---------------------------------------------------------------------------
def _sub_attr(param_attr, suffix):
    """Distinct ParamAttr per weight in multi-weight layers: a NAMED attr
    gets '<name>.<suffix>' so the weights don't silently alias one array
    in the scope (unnamed attrs already auto-unique)."""
    import copy
    from ..param_attr import ParamAttr
    if isinstance(param_attr, str):
        return f"{param_attr}.{suffix}"
    if isinstance(param_attr, ParamAttr) and param_attr.name:
        a = copy.copy(param_attr)
        a.name = f"{param_attr.name}.{suffix}"
        return a
    return param_attr


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully-connected layer (ref layers/nn.py:fc → mul + elementwise_add).

    Like the reference, `input` may be a list of Variables: each gets its
    own weight and the projections are summed before bias/activation."""
    helper = LayerHelper("fc", name=name, act=act, bias_attr=bias_attr)
    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    if isinstance(param_attr, (list, tuple)):
        if len(param_attr) != len(inputs):
            raise ValueError(
                f"fc got {len(inputs)} inputs but {len(param_attr)} "
                f"param_attrs (the reference raises here too)")
        attrs = list(param_attr)
    elif len(inputs) > 1:
        # one NAMED attr across several inputs would alias one array —
        # derive a distinct name per input (cf. _sub_attr for lstm/gru)
        attrs = [_sub_attr(param_attr, str(i)) for i in range(len(inputs))]
    else:
        attrs = [param_attr]
    dtype = inputs[0].dtype
    projs = []
    for x, pa in zip(inputs, attrs):
        in_dim = int(np.prod(x.shape[num_flatten_dims:]))
        w = helper.create_parameter(pa, shape=[in_dim, size], dtype=dtype)
        out_shape = tuple(x.shape[:num_flatten_dims]) + (size,)
        tmp = helper.create_variable_for_type_inference(dtype, out_shape)
        helper.append_op("mul", {"X": [x], "Y": [w]}, {"Out": [tmp]},
                         {"x_num_col_dims": num_flatten_dims,
                          "y_num_col_dims": 1})
        projs.append(tmp)
    tmp = projs[0]
    for other in projs[1:]:
        summed = helper.create_variable_for_type_inference(
            dtype, tuple(tmp.shape))
        helper.append_op("elementwise_add", {"X": [tmp], "Y": [other]},
                         {"Out": [summed]}, {"axis": -1})
        tmp = summed
    tmp = helper.append_bias_op(tmp, dim_start=num_flatten_dims,
                                bias_attr=bias_attr, size=size)
    return helper.append_activation(tmp, act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    """ref layers/nn.py:embedding (lookup_table op, lookup_table_op.cc).

    is_sparse=True enables the ROW-SPARSE update path — the XLA-native
    analog of the reference's SelectedRows gradients: the backward
    taps the gathered rows through a zero "delta" input (so the table
    gradient is [..., D] row gradients, never a densified [V, D]
    scatter-add), and the optimizer applies a lazy row-sparse update
    (sparse_adam / sparse_sgd kernels) touching only the rows in Ids.
    Semantics match the reference's lazy_mode (optimizer.py:697):
    untouched rows keep their moments; regularizers/clip are not
    applied to sparse tables. Dense (default) remains the
    MXU-efficient path for small vocabularies."""
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(param_attr, shape=_dims(size), dtype=dtype,
                                default_initializer=NormalInitializer(0.0, 0.02))
    in_shape = input.shape
    if in_shape and in_shape[-1] == 1:
        out_shape = tuple(in_shape[:-1]) + (size[1],)
    else:
        out_shape = tuple(in_shape) + (size[1],)
    out = helper.create_variable_for_type_inference(dtype, out_shape)
    inputs = {"W": [w], "Ids": [input]}
    attrs = {"padding_idx": -1 if padding_idx is None else padding_idx}
    if is_distributed:
        # the pserver-partitioned table analog: DistributeTranspiler
        # row-shards this table (and its optimizer state) over the mesh
        # and XLA SPMD partitions the gather/scatter (ref
        # distribute_lookup_table.py + transpiler pserver split)
        attrs["is_distributed"] = True
    if is_sparse:
        # the row-grad tap: trace seeds it with zeros of the gathered
        # shape inside the diff set; its gradient IS the row gradient
        delta = helper.create_variable_for_type_inference(dtype, out_shape)
        inputs["SparseDelta"] = [delta]
        attrs["is_sparse"] = True
        taps = getattr(w, "_sparse_lookup", None) or []
        taps.append({"ids": input.name, "delta": delta.name})
        w._sparse_lookup = taps
    helper.append_op("lookup_table", inputs, {"Out": [out]}, attrs)
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=5, name=None):
    """Sampled softmax stand-in for ref nce_op (noise-contrastive estimation):
    TPU-friendly fixed-size uniform negative sampling."""
    return sampled_softmax_with_cross_entropy(
        input, label, num_total_classes, num_neg_samples + 1,
        param_attr=param_attr, bias_attr=bias_attr, name=name)


def sampled_softmax_with_cross_entropy(input, label, num_classes, num_samples,
                                       param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("sampled_softmax", name=name)
    dtype = input.dtype
    dim = int(input.shape[-1])
    w = helper.create_parameter(param_attr, shape=[num_classes, dim], dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[num_classes], dtype=dtype,
                                is_bias=True)
    out = helper.create_variable_for_type_inference(dtype, (input.shape[0], 1))
    helper.append_op("sampled_softmax_ce",
                     {"X": [input], "Label": [label], "W": [w], "B": [b]},
                     {"Loss": [out]},
                     {"num_samples": int(num_samples), "num_classes": int(num_classes)})
    return out


# ---------------------------------------------------------------------------
# conv / pool
# ---------------------------------------------------------------------------
def _conv_out_size(i, k, s, p, d=1):
    if i < 0:
        return -1
    ke = d * (k - 1) + 1
    return (i + 2 * p - ke) // s + 1


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    """ref layers/nn.py:conv2d (NCHW). use_cudnn accepted for parity; XLA
    lowers lax.conv onto the MXU."""
    helper = LayerHelper("conv2d", name=name, act=act)
    dtype = input.dtype
    c_in = int(input.shape[1])
    fh, fw = convert_to_list(filter_size, 2, "filter_size")
    sh, sw = convert_to_list(stride, 2, "stride")
    ph, pw = convert_to_list(padding, 2, "padding")
    dh, dw = convert_to_list(dilation, 2, "dilation")
    g = groups or 1
    std = (2.0 / (fh * fw * c_in)) ** 0.5
    w = helper.create_parameter(param_attr, shape=[num_filters, c_in // g, fh, fw],
                                dtype=dtype,
                                default_initializer=NormalInitializer(0.0, std))
    oh = _conv_out_size(int(input.shape[2]), fh, sh, ph, dh)
    ow = _conv_out_size(int(input.shape[3]), fw, sw, pw, dw)
    out_shape = (input.shape[0], num_filters, oh, ow)
    out = helper.create_variable_for_type_inference(dtype, out_shape)
    ins = {"Input": [input], "Filter": [w]}
    b = helper.create_parameter(bias_attr, shape=[num_filters], dtype=dtype,
                                is_bias=True)
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op("conv2d", ins, {"Output": [out]},
                     {"strides": [sh, sw], "paddings": [ph, pw],
                      "dilations": [dh, dw], "groups": g})
    return helper.append_activation(out, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", name=name, act=act)
    dtype = input.dtype
    c_in = int(input.shape[1])
    fh, fw = convert_to_list(filter_size, 2, "filter_size")
    sh, sw = convert_to_list(stride, 2, "stride")
    ph, pw = convert_to_list(padding, 2, "padding")
    w = helper.create_parameter(param_attr, shape=[c_in, num_filters, fh, fw],
                                dtype=dtype)
    ih, iw = int(input.shape[2]), int(input.shape[3])
    oh = (ih - 1) * sh - 2 * ph + fh if ih > 0 else -1
    ow = (iw - 1) * sw - 2 * pw + fw if iw > 0 else -1
    out = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], num_filters, oh, ow))
    ins = {"Input": [input], "Filter": [w]}
    b = helper.create_parameter(bias_attr, shape=[num_filters], dtype=dtype,
                                is_bias=True)
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op("conv2d_transpose", ins, {"Output": [out]},
                     {"strides": [sh, sw], "paddings": [ph, pw],
                      "dilations": [1, 1]})
    return helper.append_activation(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d", name=name, act=act)
    dtype = input.dtype
    c_in = int(input.shape[1])
    fs = convert_to_list(filter_size, 3, "filter_size")
    st = convert_to_list(stride, 3, "stride")
    pd = convert_to_list(padding, 3, "padding")
    w = helper.create_parameter(param_attr,
                                shape=[num_filters, c_in // (groups or 1)] + fs,
                                dtype=dtype)
    od = [_conv_out_size(int(input.shape[2 + i]), fs[i], st[i], pd[i]) for i in range(3)]
    out = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], num_filters) + tuple(od))
    helper.append_op("conv3d", {"Input": [input], "Filter": [w]},
                     {"Output": [out]},
                     {"strides": st, "paddings": pd, "dilations": [1, 1, 1],
                      "groups": groups or 1})
    return helper.append_activation(out, act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False,
           exclusive=True, name=None):
    helper = LayerHelper("pool2d", name=name)
    ks = tuple(convert_to_list(pool_size, 2, "pool_size"))
    st = tuple(convert_to_list(pool_stride, 2, "pool_stride"))
    pd = tuple(convert_to_list(pool_padding, 2, "pool_padding"))
    if global_pooling:
        oh = ow = 1
    else:
        def _out(sz, k, s, p):
            num = sz + 2 * p - k
            return (-(-num // s) if ceil_mode else num // s) + 1
        oh = _out(int(input.shape[2]), ks[0], st[0], pd[0])
        ow = _out(int(input.shape[3]), ks[1], st[1], pd[1])
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], input.shape[1], oh, ow))
    helper.append_op("pool2d", {"X": [input]}, {"Out": [out]},
                     {"pooling_type": pool_type, "ksize": list(ks),
                      "strides": list(st), "paddings": list(pd),
                      "global_pooling": global_pooling,
                      "exclusive": exclusive, "ceil_mode": ceil_mode})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    ks = tuple(convert_to_list(pool_size, 2, "pool_size"))
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], input.shape[1]) + ks)
    helper.append_op("pool2d", {"X": [input]}, {"Out": [out]},
                     {"pooling_type": pool_type, "ksize": list(ks),
                      "adaptive": True})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    c = int(x.shape[1])
    out = helper.create_variable_for_type_inference(
        x.dtype, (x.shape[0], c // groups) + tuple(x.shape[2:]))
    helper.append_op("maxout", {"X": [x]}, {"Out": [out]}, {"groups": groups})
    return out


def pixel_shuffle(x, upscale_factor, name=None):
    helper = LayerHelper("pixel_shuffle", name=name)
    r = upscale_factor
    n, c, h, w = x.shape
    out = helper.create_variable_for_type_inference(
        x.dtype, (n, c // (r * r), h * r, w * r))
    helper.append_op("pixel_shuffle", {"X": [x]}, {"Out": [out]},
                     {"upscale_factor": r})
    return out


# ---------------------------------------------------------------------------
# normalization / dropout
# ---------------------------------------------------------------------------
def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    """ref layers/nn.py:batch_norm. Moving stats live as persistable vars
    updated in-graph each training step."""
    helper = LayerHelper("batch_norm", name=name, act=act)
    dtype = input.dtype
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    scale = helper.create_parameter(param_attr, shape=[c], dtype="float32",
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype="float32",
                                   is_bias=True)
    mean = helper.create_global_variable([c], "float32", persistable=True,
                                         name=moving_mean_name)
    var = helper.create_global_variable([c], "float32", persistable=True,
                                        name=moving_variance_name)
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    helper.set_variable_initializer(var, ConstantInitializer(1.0))
    out = helper.create_variable_for_type_inference(dtype, input.shape)
    saved_mean = helper.create_variable_for_type_inference("float32", (c,), True)
    saved_var = helper.create_variable_for_type_inference("float32", (c,), True)
    helper.append_op(
        "batch_norm",
        {"X": [input], "Scale": [scale], "Bias": [bias],
         "Mean": [mean], "Variance": [var]},
        {"Y": [out], "MeanOut": [mean], "VarianceOut": [var],
         "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        {"momentum": momentum, "epsilon": epsilon,
         "is_test": is_test or use_global_stats, "data_layout": data_layout})
    return helper.append_activation(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", name=name, act=act)
    dtype = input.dtype
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    ins = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, shape=norm_shape, dtype="float32",
                                    default_initializer=ConstantInitializer(1.0))
        ins["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape, dtype="float32",
                                    is_bias=True)
        ins["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype, input.shape)
    mean = helper.create_variable_for_type_inference("float32", (), True)
    var = helper.create_variable_for_type_inference("float32", (), True)
    helper.append_op("layer_norm", ins,
                     {"Y": [out], "Mean": [mean], "Variance": [var]},
                     {"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, name=None):
    helper = LayerHelper("group_norm", name=name, act=act)
    c = int(input.shape[1])
    ins = {"X": [input]}
    s = helper.create_parameter(param_attr, shape=[c], dtype="float32",
                                default_initializer=ConstantInitializer(1.0))
    b = helper.create_parameter(bias_attr, shape=[c], dtype="float32", is_bias=True)
    if s is not None:
        ins["Scale"] = [s]
    if b is not None:
        ins["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    mean = helper.create_variable_for_type_inference("float32", (), True)
    var = helper.create_variable_for_type_inference("float32", (), True)
    helper.append_op("group_norm", ins,
                     {"Y": [out], "Mean": [mean], "Variance": [var]},
                     {"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out, act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", name=name)
    c = int(input.shape[1])
    s = helper.create_parameter(param_attr, shape=[c], dtype="float32",
                                default_initializer=ConstantInitializer(1.0))
    b = helper.create_parameter(bias_attr, shape=[c], dtype="float32", is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("instance_norm",
                     {"X": [input], "Scale": [s], "Bias": [b]},
                     {"Y": [out]}, {"epsilon": epsilon})
    return out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    mask = helper.create_variable_for_type_inference(x.dtype, x.shape, True)
    helper.append_op("dropout", {"X": [x]}, {"Out": [out], "Mask": [mask]},
                     {"dropout_prob": dropout_prob, "is_test": is_test,
                      "dropout_implementation": dropout_implementation})
    return out


# ---------------------------------------------------------------------------
# softmax & losses
# ---------------------------------------------------------------------------
def softmax(input, axis=-1, use_cudnn=False, name=None):
    helper = LayerHelper("softmax", name=name)
    return _same_shape_out(helper, input, "softmax", {"axis": axis})


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    return _same_shape_out(helper, input, "log_softmax", {"axis": axis})


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out_shape = tuple(input.shape[:-1]) + (1,)
    out = helper.create_variable_for_type_inference(input.dtype, out_shape)
    helper.append_op("cross_entropy", {"X": [input], "Label": [label]},
                     {"Y": [out]},
                     {"soft_label": soft_label, "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, smooth_epsilon=0.0):
    """smooth_epsilon > 0 with integer labels computes label-smoothed CE
    in one fused kernel — same numerics as one_hot→label_smooth→this op
    with soft_label=True, without materializing the [.., K] targets
    (beyond-reference attr; the composed path still works)."""
    helper = LayerHelper("softmax_with_cross_entropy")
    loss_shape = tuple(logits.shape[:-1]) + (1,)
    loss = helper.create_variable_for_type_inference(logits.dtype, loss_shape)
    sm = helper.create_variable_for_type_inference(logits.dtype, logits.shape)
    helper.append_op("softmax_with_cross_entropy",
                     {"Logits": [logits], "Label": [label]},
                     {"Loss": [loss], "Softmax": [sm]},
                     {"soft_label": soft_label, "ignore_index": ignore_index,
                      "smooth_epsilon": smooth_epsilon})
    if return_softmax:
        return loss, sm
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     {"X": [x], "Label": [label]}, {"Out": [out]},
                     {"ignore_index": ignore_index})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("square_error_cost", {"X": [input], "Y": [label]},
                     {"Out": [out]}, {})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    res = helper.create_variable_for_type_inference(input.dtype, input.shape, True)
    helper.append_op("huber_loss", {"X": [input], "Y": [label]},
                     {"Out": [out], "Residual": [res]}, {"delta": delta})
    return out


def hinge_loss(input, label, name=None):
    helper = LayerHelper("hinge_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("hinge_loss", {"Logits": [input], "Labels": [label]},
                     {"Loss": [out]}, {})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], 1))
    helper.append_op("bpr_loss", {"X": [input], "Label": [label]},
                     {"Y": [out]}, {})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype, left.shape)
    act = helper.create_variable_for_type_inference(left.dtype, left.shape, True)
    helper.append_op("margin_rank_loss",
                     {"X1": [left], "X2": [right], "Label": [label]},
                     {"Out": [out], "Activated": [act]}, {"margin": margin})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("log_loss", {"Predicted": [input], "Labels": [label]},
                     {"Loss": [out]}, {"epsilon": epsilon})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    shape = () if reduction != "none" else x.shape
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("kldiv_loss", {"X": [x], "Target": [target]},
                     {"Loss": [out]}, {"reduction": reduction})
    return out


def mse_loss(input, label, name=None):
    helper = LayerHelper("mse_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, ())
    helper.append_op("mse_loss", {"X": [input], "Y": [label]},
                     {"Out": [out]}, {})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0):
    helper = LayerHelper("smooth_l1")
    out = helper.create_variable_for_type_inference(x.dtype, (x.shape[0], 1))
    diff = helper.create_variable_for_type_inference(x.dtype, x.shape, True)
    helper.append_op("smooth_l1_loss", {"X": [x], "Y": [y]},
                     {"Out": [out], "Diff": [diff]}, {"sigma": sigma})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype, label.shape)
    ins = {"X": [label]}
    if prior_dist is not None:
        ins["PriorDist"] = [prior_dist]
    helper.append_op("label_smooth", ins, {"Out": [out]}, {"epsilon": epsilon})
    return out


def one_hot(input, depth, name=None):
    helper = LayerHelper("one_hot", name=name)
    base = input.shape[:-1] if (input.shape and input.shape[-1] == 1) else input.shape
    out = helper.create_variable_for_type_inference(
        "float32", tuple(base) + (depth,))
    helper.append_op("one_hot", {"X": [input]}, {"Out": [out]},
                     {"depth": depth})
    return out


# ---------------------------------------------------------------------------
# recurrent
# ---------------------------------------------------------------------------
def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 seq_len=None):
    """Padded-batch LSTM (ref layers/nn.py:dynamic_lstm, LoD → mask).

    input: [B, T, D]; size = 4*hidden (gate-packed, matching the ref API).
    Returns (hidden [B,T,H], cell-state last [B,H]).
    """
    helper = LayerHelper("dynamic_lstm", name=name)
    hidden = size // 4
    d_in = int(input.shape[-1])
    w_ih = helper.create_parameter(_sub_attr(param_attr, "ih"),
                                   shape=[d_in, 4 * hidden], dtype=dtype)
    w_hh = helper.create_parameter(_sub_attr(param_attr, "hh"),
                                   shape=[hidden, 4 * hidden], dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[4 * hidden], dtype=dtype,
                                is_bias=True)
    B, T = input.shape[0], input.shape[1]
    h_seq = helper.create_variable_for_type_inference(dtype, (B, T, hidden))
    last_h = helper.create_variable_for_type_inference(dtype, (B, hidden))
    last_c = helper.create_variable_for_type_inference(dtype, (B, hidden))
    ins = {"Input": [input], "WeightIH": [w_ih], "WeightHH": [w_hh]}
    if b is not None:
        ins["Bias"] = [b]
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op("lstm", ins,
                     {"Hidden": [h_seq], "LastH": [last_h], "LastC": [last_c]},
                     {"is_reverse": is_reverse})
    h_seq._last_h, h_seq._last_c = last_h, last_c
    return h_seq, last_c


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=False, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, seq_len=None):
    """LSTM with recurrent projection (ref layers/nn.py:dynamic_lstmp,
    lstmp_op). input [B,T,D]; size = 4*hidden. Returns
    (projection [B,T,P], last cell [B,H])."""
    helper = LayerHelper("dynamic_lstmp", name=name)
    hidden = size // 4
    d_in = int(input.shape[-1])
    w_ih = helper.create_parameter(_sub_attr(param_attr, "ih"),
                                   shape=[d_in, 4 * hidden],
                                   dtype=dtype)
    w_hh = helper.create_parameter(_sub_attr(param_attr, "hh"),
                                   shape=[proj_size, 4 * hidden],
                                   dtype=dtype)
    w_proj = helper.create_parameter(_sub_attr(param_attr, "proj"),
                                     shape=[hidden, proj_size],
                                     dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[4 * hidden], dtype=dtype,
                                is_bias=True)
    B, T = input.shape[0], input.shape[1]
    proj = helper.create_variable_for_type_inference(dtype, (B, T, proj_size))
    last_h = helper.create_variable_for_type_inference(dtype, (B, proj_size))
    last_c = helper.create_variable_for_type_inference(dtype, (B, hidden))
    ins = {"Input": [input], "WeightIH": [w_ih], "WeightHH": [w_hh],
           "Proj": [w_proj]}
    if b is not None:
        ins["Bias"] = [b]
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op("lstmp", ins,
                     {"Projection": [proj], "LastH": [last_h],
                      "LastC": [last_c]},
                     {"is_reverse": is_reverse})
    return proj, last_c


def lstm(input, init_h=None, init_c=None, max_len=None, hidden_size=None,
         num_layers=1, dropout_prob=0.0, is_bidirec=False, is_test=False,
         name=None, default_initializer=None, seed=-1, seq_len=None):
    """Multi-layer (optionally bidirectional) LSTM (ref layers/nn.py:lstm,
    cudnn_lstm_op → stacked lax.scan LSTMs; XLA fuses the stack).

    input [B,T,D]. Returns (rnn_out [B,T,H*dirs], last_h [L*dirs,B,H],
    last_c [L*dirs,B,H]).
    """
    if hidden_size is None:
        raise ValueError("lstm requires hidden_size")
    from .tensor import concat as _concat

    def _init_state(packed, idx):
        # packed [L*dirs, B, H] → [B, H] for layer-direction idx
        if packed is None:
            return None
        s = slice(packed, axes=[0], starts=[idx], ends=[idx + 1])
        return squeeze(s, axes=[0])

    x = input
    last_hs, last_cs = [], []
    idx = 0
    for layer in range(num_layers):
        fw, fw_c = dynamic_lstm(
            x, 4 * hidden_size, h_0=_init_state(init_h, idx),
            c_0=_init_state(init_c, idx), seq_len=seq_len,
            name=f"{name or 'lstm'}_l{layer}_fw")
        last_hs.append(fw._last_h)
        idx += 1
        if is_bidirec:
            bw, bw_c = dynamic_lstm(
                x, 4 * hidden_size, is_reverse=True,
                h_0=_init_state(init_h, idx), c_0=_init_state(init_c, idx),
                seq_len=seq_len, name=f"{name or 'lstm'}_l{layer}_bw")
            last_hs.append(bw._last_h)
            idx += 1
            x = _concat([fw, bw], axis=-1)
            last_cs += [fw_c, bw_c]
        else:
            x = fw
            last_cs.append(fw_c)
        if dropout_prob > 0.0 and layer < num_layers - 1:
            x = dropout(x, dropout_prob, is_test=is_test)
    last_h = stack(last_hs, axis=0)  # [L*dirs, B, H]
    last_c = stack(last_cs, axis=0)
    return x, last_h, last_c


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, h_0=None, dtype="float32", name=None,
                seq_len=None):
    """Padded-batch GRU (ref layers/nn.py:dynamic_gru). input [B,T,D]."""
    helper = LayerHelper("dynamic_gru", name=name)
    d_in = int(input.shape[-1])
    w_ih = helper.create_parameter(_sub_attr(param_attr, "ih"),
                                   shape=[d_in, 3 * size], dtype=dtype)
    w_hh = helper.create_parameter(_sub_attr(param_attr, "hh"),
                                   shape=[size, 3 * size], dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[3 * size], dtype=dtype,
                                is_bias=True)
    B, T = input.shape[0], input.shape[1]
    h_seq = helper.create_variable_for_type_inference(dtype, (B, T, size))
    last_h = helper.create_variable_for_type_inference(dtype, (B, size))
    ins = {"Input": [input], "WeightIH": [w_ih], "WeightHH": [w_hh]}
    if b is not None:
        ins["Bias"] = [b]
    if h_0 is not None:
        ins["H0"] = [h_0]
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op("gru", ins, {"Hidden": [h_seq], "LastH": [last_h]},
                     {"is_reverse": is_reverse})
    return h_seq


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """ref layers/nn.py:lstm_unit — one step; x_t already projected is not
    assumed: does fc([x,h]) like the reference."""
    from . import tensor as _t
    cat = _t.concat([x_t, hidden_t_prev], axis=1)
    hidden = int(hidden_t_prev.shape[-1])
    gates = fc(cat, 4 * hidden, param_attr=param_attr, bias_attr=bias_attr)
    helper = LayerHelper("lstm_unit", name=name)
    c = helper.create_variable_for_type_inference(x_t.dtype, cell_t_prev.shape)
    h = helper.create_variable_for_type_inference(x_t.dtype, hidden_t_prev.shape)
    helper.append_op("lstm_unit", {"X": [gates], "C_prev": [cell_t_prev]},
                     {"C": [c], "H": [h]}, {"forget_bias": forget_bias})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", name=None):
    helper = LayerHelper("gru_unit", name=name)
    hidden_dim = size // 3
    w = helper.create_parameter(param_attr, shape=[hidden_dim, 3 * hidden_dim],
                                dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[3 * hidden_dim],
                                dtype=input.dtype, is_bias=True)
    h = helper.create_variable_for_type_inference(input.dtype, hidden.shape)
    gate = helper.create_variable_for_type_inference(
        input.dtype, (hidden.shape[0], 2 * hidden_dim), True)
    rhp = helper.create_variable_for_type_inference(input.dtype, hidden.shape, True)
    ins = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op("gru_unit", ins,
                     {"Hidden": [h], "Gate": [gate], "ResetHiddenPrev": [rhp]},
                     {})
    return h, rhp, gate


# ---------------------------------------------------------------------------
# tensor manipulation wrappers (thin; see ops/kernels_*)
# ---------------------------------------------------------------------------
def _simple(helper_name, op_type, x, out_shape=None, attrs=None,
            extra=None, out_slot="Out", dtype=None, stop_gradient=False):
    helper = LayerHelper(helper_name)
    out = helper.create_variable_for_type_inference(
        dtype or x.dtype, out_shape if out_shape is not None else x.shape,
        stop_gradient)
    ins = {"X": [x]}
    if extra:
        ins.update(extra)
    helper.append_op(op_type, ins, {out_slot: [out]}, attrs or {})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    xs = list(x.shape)
    ys = list(y.shape)
    if transpose_x and len(xs) >= 2:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if transpose_y and len(ys) >= 2:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    if len(xs) >= 2 and len(ys) >= 2:
        out_shape = tuple(xs[:-1]) + (ys[-1],)
    else:
        out_shape = ()
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op("matmul", {"X": [x], "Y": [y]}, {"Out": [out]},
                     {"transpose_X": transpose_x, "transpose_Y": transpose_y,
                      "alpha": alpha})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out_shape = tuple(x.shape[:x_num_col_dims]) + tuple(y.shape[y_num_col_dims:])
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op("mul", {"X": [x], "Y": [y]}, {"Out": [out]},
                     {"x_num_col_dims": x_num_col_dims,
                      "y_num_col_dims": y_num_col_dims})
    return out


def bmm(x, y, name=None):
    helper = LayerHelper("bmm", name=name)
    out = helper.create_variable_for_type_inference(
        x.dtype, (x.shape[0], x.shape[1], y.shape[2]))
    helper.append_op("bmm", {"X": [x], "Y": [y]}, {"Out": [out]}, {})
    return out


def dot(x, y, name=None):
    return _simple("dot", "dot", x, tuple(x.shape[:-1]) + (1,),
                   extra={"Y": [y]})


def transpose(x, perm, name=None):
    out_shape = tuple(x.shape[p] for p in perm)
    return _simple("transpose", "transpose", x, out_shape, {"axis": list(perm)})


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    shape = list(shape)
    known = 1
    resolved = []
    for i, s in enumerate(shape):
        s = int(s)
        resolved.append(x.shape[i] if s == 0 else s)
    out_shape = tuple(resolved)
    return _simple("reshape", "reshape", x, out_shape, {"shape": shape})


def squeeze(input, axes=None, name=None):
    shape = list(input.shape)
    if axes:
        out_shape = tuple(s for i, s in enumerate(shape)
                          if i not in [a % len(shape) for a in axes])
    else:
        out_shape = tuple(s for s in shape if s != 1)
    return _simple("squeeze", "squeeze", input, out_shape,
                   {"axes": list(axes or [])})


def unsqueeze(input, axes, name=None):
    shape = list(input.shape)
    for a in sorted(axes):
        shape.insert(a, 1)
    return _simple("unsqueeze", "unsqueeze", input, tuple(shape),
                   {"axes": list(axes)})


def flatten(x, axis=1, name=None):
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    rest = int(np.prod(x.shape[axis:]))
    return _simple("flatten", "flatten", x, (lead, rest), {"axis": axis})


def stack(x, axis=0, name=None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    helper = LayerHelper("stack", name=name)
    shape = list(xs[0].shape)
    shape.insert(axis if axis >= 0 else axis + len(shape) + 1, len(xs))
    out = helper.create_variable_for_type_inference(xs[0].dtype, tuple(shape))
    helper.append_op("stack", {"X": list(xs)}, {"Y": [out]}, {"axis": axis})
    return out


def unstack(x, axis=0, num=None, name=None):
    helper = LayerHelper("unstack", name=name)
    n = num or x.shape[axis]
    shape = tuple(s for i, s in enumerate(x.shape) if i != (axis % len(x.shape)))
    outs = [helper.create_variable_for_type_inference(x.dtype, shape)
            for _ in range(n)]
    helper.append_op("unstack", {"X": [x]}, {"Y": outs}, {"axis": axis})
    return outs


def expand(x, expand_times, name=None):
    out_shape = tuple(-1 if s < 0 else s * t
                      for s, t in zip(x.shape, expand_times))
    return _simple("expand", "expand", x, out_shape,
                   {"expand_times": list(expand_times)})


def expand_as(x, target_tensor, name=None):
    return _simple("expand_as", "expand_as", x, target_tensor.shape,
                   extra={"target_tensor": [target_tensor]})


def slice(input, axes, starts, ends, name=None):
    shape = list(input.shape)
    for a, s, e in zip(axes, starts, ends):
        if shape[a] < 0:
            continue
        dim = shape[a]
        s2 = max(s + dim, 0) if s < 0 else min(s, dim)
        e2 = max(e + dim, 0) if e < 0 else min(e, dim)
        shape[a] = max(e2 - s2, 0)
    helper = LayerHelper("slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, tuple(shape))
    helper.append_op("slice", {"Input": [input]}, {"Out": [out]},
                     {"axes": list(axes), "starts": list(starts),
                      "ends": list(ends)})
    return out


def strided_slice(input, axes, starts, ends, strides, name=None):
    helper = LayerHelper("strided_slice", name=name)
    shape = list(input.shape)
    for a, s, e, st in zip(axes, starts, ends, strides):
        if shape[a] >= 0:
            shape[a] = max(0, (e - s + (st - (1 if st > 0 else -1))) // st)
    out = helper.create_variable_for_type_inference(input.dtype, tuple(shape))
    helper.append_op("strided_slice", {"Input": [input]}, {"Out": [out]},
                     {"axes": list(axes), "starts": list(starts),
                      "ends": list(ends), "strides": list(strides)})
    return out


def gather(input, index, axis=0, name=None):
    out_shape = tuple(list(index.shape) + list(input.shape[1:]))
    return _simple("gather", "gather", input, out_shape, {"axis": axis},
                   extra={"Index": [index]})


def gather_nd(input, index, name=None):
    k = index.shape[-1]
    out_shape = tuple(index.shape[:-1]) + tuple(input.shape[k:])
    return _simple("gather_nd", "gather_nd", input, out_shape,
                   extra={"Index": [index]})


def scatter(input, index, updates, overwrite=True, name=None):
    return _simple("scatter", "scatter", input, input.shape,
                   {"overwrite": overwrite},
                   extra={"Ids": [index], "Updates": [updates]})


def scatter_nd_add(ref, index, updates, name=None):
    return _simple("scatter_nd_add", "scatter_nd_add", ref, ref.shape,
                   extra={"Index": [index], "Updates": [updates]})


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    out_shape = tuple(input.shape[:-1]) + (k,)
    vals = helper.create_variable_for_type_inference(input.dtype, out_shape)
    idx = helper.create_variable_for_type_inference("int64", out_shape, True)
    helper.append_op("top_k", {"X": [input]},
                     {"Out": [vals], "Indices": [idx]}, {"k": k})
    return vals, idx


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    idx = helper.create_variable_for_type_inference("int64", input.shape, True)
    helper.append_op("argsort", {"X": [input]},
                     {"Out": [out], "Indices": [idx]},
                     {"axis": axis, "descending": descending})
    return out, idx


def argmax(x, axis=-1, keepdims=False, name=None):
    shape = list(x.shape)
    ax = axis % len(shape) if shape else 0
    if keepdims:
        shape[ax] = 1
    else:
        shape.pop(ax)
    return _simple("arg_max", "arg_max", x, tuple(shape),
                   {"axis": axis, "keepdims": keepdims}, dtype="int64",
                   stop_gradient=True)


def argmin(x, axis=-1, name=None):
    shape = list(x.shape)
    shape.pop(axis % len(shape) if shape else 0)
    return _simple("arg_min", "arg_min", x, tuple(shape), {"axis": axis},
                   dtype="int64", stop_gradient=True)


def where(condition, x, y, name=None):
    helper = LayerHelper("where", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("where", {"Condition": [condition], "X": [x], "Y": [y]},
                     {"Out": [out]}, {})
    return out


cond_select = where


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    axis = dim % len(input.shape)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        sections = None
        sizes = [input.shape[axis] // n] * n if input.shape[axis] > 0 else [-1] * n
    else:
        sections = list(num_or_sections)
        sizes = sections
        n = len(sections)
    outs = []
    for s in sizes:
        shape = list(input.shape)
        shape[axis] = s
        outs.append(helper.create_variable_for_type_inference(
            input.dtype, tuple(shape)))
    attrs = {"axis": axis}
    if sections:
        attrs["sections"] = sections
    else:
        attrs["num"] = n
    helper.append_op("split", {"X": [input]}, {"Out": outs}, attrs)
    return outs


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    norm = helper.create_variable_for_type_inference(x.dtype, x.shape, True)
    helper.append_op("l2_normalize", {"X": [x]},
                     {"Out": [out], "Norm": [norm]},
                     {"axis": axis, "epsilon": epsilon})
    return out


def mean(x, name=None):
    return _simple("mean", "mean", x, ())


def _reduce_layer(op, input, dim, keep_dim, name):
    shape = list(input.shape)
    if dim is None:
        out_shape = ()
    else:
        dims = [dim] if isinstance(dim, int) else list(dim)
        dims = [d % len(shape) for d in dims]
        if keep_dim:
            out_shape = tuple(1 if i in dims else s for i, s in enumerate(shape))
        else:
            out_shape = tuple(s for i, s in enumerate(shape) if i not in dims)
    return _simple(op, op, input, out_shape,
                   {"dim": [dim] if isinstance(dim, int) else dim,
                    "keep_dim": keep_dim, "reduce_all": dim is None})


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_prod", input, dim, keep_dim, name)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_all", input, dim, keep_dim, name)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_any", input, dim, keep_dim, name)


def logsumexp(x, dim=None, keep_dim=False, name=None):
    return _reduce_layer("logsumexp", x, dim, keep_dim, name)


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    return _simple("cumsum", "cumsum", x, x.shape,
                   {"axis": axis, "exclusive": exclusive, "reverse": reverse})


def clip(x, min, max, name=None):
    return _simple("clip", "clip", x, x.shape, {"min": min, "max": max})


def clip_by_norm(x, max_norm, name=None):
    return _simple("clip_by_norm", "clip_by_norm", x, x.shape,
                   {"max_norm": max_norm})


def _elementwise_layer(op, x, y, axis, act, name):
    helper = LayerHelper(op, name=name, act=act)
    out_shape = x.shape if len(x.shape) >= len(y.shape) else y.shape
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op(op, {"X": [x], "Y": [y]}, {"Out": [out]}, {"axis": axis})
    return helper.append_activation(out, act)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_pow", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _elementwise_layer("elementwise_mod", x, y, axis, act, name)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("scale", {"X": [x]}, {"Out": [out]},
                     {"scale": float(scale), "bias": float(bias),
                      "bias_after_scale": bias_after_scale})
    return helper.append_activation(out, act)


def cast(x, dtype):
    dtype = convert_dtype(dtype)
    return _simple("cast", "cast", x, x.shape, {"out_dtype": dtype},
                   dtype=dtype)


def pad(x, paddings, pad_value=0.0, name=None):
    shape = list(x.shape)
    for i in range(len(shape)):
        if shape[i] >= 0:
            shape[i] += paddings[2 * i] + paddings[2 * i + 1]
    return _simple("pad", "pad", x, tuple(shape),
                   {"paddings": list(paddings), "pad_value": pad_value})


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    t, b, l, r = paddings
    shape = list(input.shape)
    if shape[2] >= 0:
        shape[2] += t + b
    if shape[3] >= 0:
        shape[3] += l + r
    return _simple("pad2d", "pad2d", input, tuple(shape),
                   {"paddings": list(paddings), "mode": mode,
                    "pad_value": pad_value})


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [int(x.shape[1])]
    else:
        alpha_shape = [int(s) for s in x.shape[1:]]
    alpha = helper.create_parameter(param_attr, shape=alpha_shape,
                                    dtype=x.dtype,
                                    default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("prelu", {"X": [x], "Alpha": [alpha]}, {"Out": [out]},
                     {"mode": mode})
    return out


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _simple("brelu", "clip", x, x.shape, {"min": t_min, "max": t_max})


def leaky_relu(x, alpha=0.02, name=None):
    return _simple("leaky_relu", "leaky_relu", x, x.shape, {"alpha": alpha})


def soft_relu(x, threshold=40.0, name=None):
    return _simple("soft_relu", "soft_relu", x, x.shape,
                   {"threshold": threshold})


def relu6(x, threshold=6.0, name=None):
    return _simple("relu6", "relu6", x, x.shape, {"threshold": threshold})


def pow(x, factor=1.0, name=None):
    return _simple("pow", "pow", x, x.shape, {"factor": factor})


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _simple("hard_sigmoid", "hard_sigmoid", x, x.shape,
                   {"slope": slope, "offset": offset})


def swish(x, beta=1.0, name=None):
    return _simple("swish", "swish", x, x.shape, {"beta": beta})


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return _simple("hard_swish", "hard_swish", x, x.shape,
                   {"threshold": threshold, "scale": scale, "offset": offset})


def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 name=None):
    helper = LayerHelper("image_resize", name=name)
    if out_shape:
        oh, ow = out_shape
    else:
        oh = int(input.shape[2] * scale)
        ow = int(input.shape[3] * scale)
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], input.shape[1], oh, ow))
    helper.append_op("bilinear_interp" if resample.upper() == "BILINEAR"
                     else "nearest_interp",
                     {"X": [input]}, {"Out": [out]},
                     {"out_h": oh, "out_w": ow,
                      "interp_method": resample.lower()})
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """ref nn.py:image_resize_short — resize so the SHORT side equals
    out_short_len, keeping aspect ratio."""
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    oh = int(round(h * out_short_len / short))
    ow = int(round(w * out_short_len / short))
    return image_resize(input, out_shape=(oh, ow), resample=resample)


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, "BILINEAR", name)


def resize_nearest(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, "NEAREST", name)


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(
        x.dtype, (x.shape[0], x.shape[1], grid.shape[1], grid.shape[2]))
    helper.append_op("grid_sampler", {"X": [x], "Grid": [grid]},
                     {"Output": [out]}, {})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    helper = LayerHelper("affine_channel", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("affine_channel",
                     {"X": [x], "Scale": [scale], "Bias": [bias]},
                     {"Out": [out]}, {"data_layout": data_layout})
    return out


def shuffle_channel(x, group, name=None):
    return _simple("shuffle_channel", "shuffle_channel", x, x.shape,
                   {"group": group})


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    helper = LayerHelper("bilinear_tensor_product", name=name, act=act)
    w = helper.create_parameter(param_attr,
                                shape=[size, int(x.shape[-1]), int(y.shape[-1])],
                                dtype=x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype, (x.shape[0], size))
    ins = {"X": [x], "Y": [y], "Weight": [w]}
    b = helper.create_parameter(bias_attr, shape=[size], dtype=x.dtype,
                                is_bias=True)
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op("bilinear_tensor_product", ins, {"Out": [out]}, {})
    return helper.append_activation(out, act)


def isfinite(x, name=None):
    return _simple("isfinite", "isfinite", x, (), dtype="bool",
                   stop_gradient=True)


def cos_sim(X, Y, name=None):
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_variable_for_type_inference(X.dtype, (X.shape[0], 1))
    xn = helper.create_variable_for_type_inference(X.dtype, (X.shape[0], 1), True)
    yn = helper.create_variable_for_type_inference(X.dtype, (Y.shape[0], 1), True)
    helper.append_op("cos_sim", {"X": [X], "Y": [Y]},
                     {"Out": [out], "XNorm": [xn], "YNorm": [yn]}, {})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", name=name)
    fh, fw = convert_to_list(filter_size, 2, "filter_size")
    sh, sw = convert_to_list(stride, 2, "stride")
    n, c, h, w = input.shape
    oh = (h - fh) // sh + 1 if h > 0 else -1
    ow = (w - fw) // sw + 1 if w > 0 else -1
    out = helper.create_variable_for_type_inference(
        input.dtype, (n, oh * ow if oh > 0 and ow > 0 else -1, c * fh * fw))
    helper.append_op("im2sequence", {"X": [input]}, {"Out": [out]},
                     {"kernels": [fh, fw], "strides": [sh, sw]})
    return out


def lod_reset(x, y=None, target_lod=None):
    """LoD compat no-op: padded arrays carry lengths separately (SURVEY §6)."""
    return x


def unique_with_counts_stub(*a, **k):
    raise NotImplementedError(
        "unique_with_counts has data-dependent output shape; "
        "use fixed-size hashing (layers.hash-style) on TPU")


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0, mask=None, causal=False,
                                 name=None):
    """ref nets.py:scaled_dot_product_attention. q/k/v: [B, T, D] (heads
    folded in) or [B, H, T, Dh]."""
    helper = LayerHelper("scaled_dot_product_attention", name=name)
    out = helper.create_variable_for_type_inference(queries.dtype, queries.shape)
    wshape = tuple(queries.shape[:-1]) + (keys.shape[-2],)
    w = helper.create_variable_for_type_inference(queries.dtype, wshape, True)
    ins = {"Q": [queries], "K": [keys], "V": [values]}
    if mask is not None:
        ins["Mask"] = [mask]
    helper.append_op("scaled_dot_product_attention", ins,
                     {"Out": [out], "Weights": [w]}, {"causal": causal})
    if dropout_rate:
        out = dropout(out, dropout_rate)
    return out


def multi_head_attention(queries, keys, values, attn_bias=None, d_key=64,
                         d_value=64, d_model=512, n_head=8, dropout_rate=0.0,
                         causal=False, param_attr=None, name=None,
                         cache=None, use_flash=True, fused_qkv=None):
    """Transformer MHA (ref book machine_translation + nets.py). q/k/v:
    [B, T, d_model]; attn_bias broadcastable to [B, n_head, Tq, Tk].

    fused_qkv: project q/k/v with ONE [d_model, (2*d_key+d_value)*H]
    matmul when queries/keys/values are the same tensor (else a fused
    [d_model, d_key*H + d_value*H] k/v projection when keys is values
    — the cross-attention case): bigger MXU tiles, fewer fusion
    boundaries than three separate [d_model, d_head*H] matmuls.
    Parameter NAMES differ from the unfused layout (one
    `..._qkv`/`..._kv` weight), so checkpoints are not interchangeable
    between the two layouts — therefore OPT-IN (default off keeps every
    existing model's names and checkpoints stable); the perf paths
    (bench.py, tools/mfu_probe.py) opt in with fused_qkv=True."""
    from . import tensor as _t
    if fused_qkv is None:
        fused_qkv = False
    if fused_qkv and param_attr is not None:
        raise ValueError(
            "fused_qkv shares one weight across q/k/v and cannot honor "
            "an explicit param_attr naming; pass fused_qkv=False")
    if fused_qkv and d_key == d_value and queries is keys \
            and keys is values:
        qkv = fc(queries, 3 * d_key * n_head, num_flatten_dims=2,
                 param_attr=param_attr, bias_attr=False,
                 name=f"{name}_qkv" if name else None)
        q, k, v = split(qkv, 3, dim=2)
    elif fused_qkv and d_key == d_value and keys is values:
        q = fc(queries, d_key * n_head, num_flatten_dims=2,
               param_attr=param_attr, bias_attr=False,
               name=f"{name}_q" if name else None)
        kv = fc(keys, 2 * d_key * n_head, num_flatten_dims=2,
                param_attr=param_attr, bias_attr=False,
                name=f"{name}_kv" if name else None)
        k, v = split(kv, 2, dim=2)
    else:
        if fused_qkv:
            import warnings
            warnings.warn(
                "fused_qkv=True requested but the fused projection needs "
                "d_key == d_value and q/k/v (or at least k/v) to be the "
                "SAME tensor object"
                f" (got d_key={d_key}, d_value={d_value}, "
                f"queries is keys={queries is keys}, "
                f"keys is values={keys is values}); falling back to the "
                "UNFUSED per-projection weights — parameter names and the "
                "checkpoint layout are the unfused ones",
                stacklevel=2)
        q = fc(queries, d_key * n_head, num_flatten_dims=2,
               param_attr=param_attr, bias_attr=False,
               name=f"{name}_q" if name else None)
        k = fc(keys, d_key * n_head, num_flatten_dims=2,
               param_attr=param_attr, bias_attr=False,
               name=f"{name}_k" if name else None)
        v = fc(values, d_value * n_head, num_flatten_dims=2,
               param_attr=param_attr, bias_attr=False,
               name=f"{name}_v" if name else None)

    # heads stay in [B, T, H, Dh] layout end-to-end: the reshape is free
    # and the attention dots contract with H as a batch dim, so no head
    # split/merge transposes ever materialize (profiled ~1.4 ms/step of
    # copies in the bhtd->bhtd layout on the transformer bench)
    q = reshape(q, [0, 0, n_head, d_key])
    k = reshape(k, [0, 0, n_head, d_key])
    v = reshape(v, [0, 0, n_head, d_value])
    helper = LayerHelper("multi_head_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype, q.shape)
    wshape = (q.shape[0], n_head, q.shape[1], k.shape[1])
    wvar = helper.create_variable_for_type_inference(q.dtype, wshape, True)
    ins = {"Q": [q], "K": [k], "V": [v]}
    if attn_bias is not None:
        ins["Mask"] = [attn_bias]
    helper.append_op("flash_attention" if use_flash else "scaled_dot_product_attention",
                     ins, {"Out": [out], "Weights": [wvar]},
                     {"causal": causal, "scale": d_key ** -0.5,
                      "layout": "bthd"})
    out = reshape(out, [0, 0, n_head * d_value])
    if dropout_rate:
        out = dropout(out, dropout_rate,
                      dropout_implementation="upscale_in_train")
    return fc(out, d_model, num_flatten_dims=2, param_attr=param_attr,
              bias_attr=False, name=f"{name}_o" if name else None)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _simple("add_position_encoding", "add_position_encoding", input,
                   input.shape, {"alpha": alpha, "beta": beta})
