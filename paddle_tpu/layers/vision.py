"""Vision / 3-D / misc layers.

Parity: python/paddle/fluid/layers/nn.py {conv3d_transpose, pool3d,
adaptive_pool3d, lrn, affine_grid, space_to_depth, crop,
pad_constant_like, random_crop, multiplex, similarity_focus, rank_loss,
dice_loss, mean_iou, sampling_id, hash, stanh} and tensor.py
{sum, has_inf, has_nan, *_batch_size_like randoms}.
"""
import numpy as np

from ..layer_helper import LayerHelper

__all__ = [
    "conv3d_transpose", "pool3d", "adaptive_pool3d", "lrn", "affine_grid",
    "space_to_depth", "crop", "pad_constant_like", "random_crop",
    "multiplex", "similarity_focus", "rank_loss", "dice_loss", "mean_iou",
    "sampling_id", "hash", "stanh", "sum", "has_inf", "has_nan",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
]


def _triple(v):
    return [v, v, v] if isinstance(v, int) else list(v)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d_transpose", name=name, act=act,
                         bias_attr=bias_attr)
    dtype = input.dtype
    c_in = int(input.shape[1])
    st, pd, dl = _triple(stride), _triple(padding), _triple(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("conv3d_transpose needs filter_size or "
                             "output_size")
        out_sz = _triple(output_size)
        filter_size = [
            (out_sz[i] - (int(input.shape[2 + i]) - 1) * st[i]
             + 2 * pd[i] - 1) // dl[i] + 1 for i in range(3)]
    fs = _triple(filter_size)
    w = helper.create_parameter(param_attr,
                                shape=[c_in, num_filters] + fs, dtype=dtype)
    od = [(int(input.shape[2 + i]) - 1) * st[i] - 2 * pd[i]
          + dl[i] * (fs[i] - 1) + 1 for i in range(3)]
    out = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], num_filters) + tuple(od))
    helper.append_op("conv3d_transpose",
                     {"Input": [input], "Filter": [w]}, {"Output": [out]},
                     {"strides": st, "paddings": pd, "dilations": dl})
    out = helper.append_bias_op(out, dim_start=1, bias_attr=bias_attr,
                                size=num_filters)
    return helper.append_activation(out, act)


def _pool_out(sz, k, s, p, ceil_mode=False):
    num = sz + 2 * p - k
    return (-(-num // s) if ceil_mode else num // s) + 1


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool3d", name=name)
    ks, st, pd = _triple(pool_size), _triple(pool_stride), _triple(pool_padding)
    if global_pooling:
        od = [1, 1, 1]
    else:
        od = [_pool_out(int(input.shape[2 + i]), ks[i], st[i], pd[i],
                        ceil_mode) for i in range(3)]
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], input.shape[1]) + tuple(od))
    helper.append_op("pool3d", {"X": [input]}, {"Out": [out]},
                     {"pooling_type": pool_type, "ksize": ks, "strides": st,
                      "paddings": pd, "global_pooling": global_pooling,
                      "exclusive": exclusive, "ceil_mode": ceil_mode})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if require_index:
        raise NotImplementedError("adaptive_pool3d: require_index "
                                  "unsupported (mask output)")
    helper = LayerHelper("adaptive_pool3d", name=name)
    ks = _triple(pool_size)
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], input.shape[1]) + tuple(ks))
    helper.append_op("pool3d", {"X": [input]}, {"Out": [out]},
                     {"pooling_type": pool_type if pool_type != "avg" else "avg",
                      "ksize": ks, "adaptive": True})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    mid = helper.create_variable_for_type_inference(
        input.dtype, input.shape, True)
    helper.append_op("lrn", {"X": [input]}, {"Out": [out], "MidOut": [mid]},
                     {"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def affine_grid(theta, out_shape, name=None):
    """theta [N,2,3]; out_shape static [N,C,H,W] list (dynamic shape
    tensors are host-side in the ref; XLA needs static)."""
    helper = LayerHelper("affine_grid", name=name)
    if not isinstance(out_shape, (list, tuple)):
        raise ValueError("affine_grid: out_shape must be a static list on "
                         "TPU (ref also accepts a tensor; see SURVEY §6)")
    N, _, H, W = [int(s) for s in out_shape]
    out = helper.create_variable_for_type_inference(
        theta.dtype, (theta.shape[0], H, W, 2))
    helper.append_op("affine_grid", {"Theta": [theta]}, {"Output": [out]},
                     {"output_shape": [N, 0, H, W]})
    return out


def space_to_depth(x, blocksize, name=None):
    helper = LayerHelper("space_to_depth", name=name)
    n, c, h, w = x.shape
    out = helper.create_variable_for_type_inference(
        x.dtype, (n, int(c) * blocksize ** 2, int(h) // blocksize,
                  int(w) // blocksize))
    helper.append_op("space_to_depth", {"X": [x]}, {"Out": [out]},
                     {"blocksize": blocksize})
    return out


def crop(x, shape=None, offsets=None, name=None):
    """Static crop; shape may be a Variable (its static shape defines the
    crop, wired as the op's Y input like the reference)."""
    helper = LayerHelper("crop", name=name)
    ins = {"X": [x]}
    if hasattr(shape, "shape"):  # reference-tensor form
        tgt = [int(s) for s in shape.shape]
        ins["Y"] = [shape]
    else:
        tgt = [int(s) for s in shape]
    if tgt and tgt[0] in (-1, 0):
        tgt[0] = int(x.shape[0])
    offs = list(offsets or [0] * len(tgt))
    out = helper.create_variable_for_type_inference(x.dtype, tuple(tgt))
    helper.append_op("crop", ins, {"Out": [out]},
                     {"shape": tgt, "offsets": offs})
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype, x.shape)
    helper.append_op("pad_constant_like", {"X": [x], "Y": [y]},
                     {"Out": [out]}, {"pad_value": float(pad_value)})
    return out


def random_crop(x, shape, seed=None, name=None):
    """Random crop of the trailing len(shape) dims (per-op PRNG key)."""
    helper = LayerHelper("random_crop", name=name)
    lead = len(x.shape) - len(shape)
    out = helper.create_variable_for_type_inference(
        x.dtype, tuple(x.shape[:lead]) + tuple(shape))
    helper.append_op("random_crop", {"X": [x]}, {"Out": [out]},
                     {"shape": list(shape)})
    return out


def multiplex(inputs, index, name=None):
    helper = LayerHelper("multiplex", name=name)
    out = helper.create_variable_for_type_inference(
        inputs[0].dtype, inputs[0].shape)
    helper.append_op("multiplex", {"X": list(inputs), "Ids": [index]},
                     {"Out": [out]}, {})
    return out


def similarity_focus(input, axis, indexes, name=None):
    helper = LayerHelper("similarity_focus", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("similarity_focus", {"X": [input]}, {"Out": [out]},
                     {"axis": axis, "indexes": list(indexes)})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype, left.shape)
    helper.append_op("rank_loss",
                     {"Label": [label], "Left": [left], "Right": [right]},
                     {"Out": [out]}, {})
    return out


def dice_loss(input, label, epsilon=1e-5, name=None):
    helper = LayerHelper("dice_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, ())
    helper.append_op("dice_loss", {"X": [input], "Label": [label]},
                     {"Out": [out]}, {"epsilon": epsilon})
    return out


def mean_iou(input, label, num_classes, name=None):
    helper = LayerHelper("mean_iou", name=name)
    miou = helper.create_variable_for_type_inference("float32", (), True)
    wrong = helper.create_variable_for_type_inference(
        "int64", (num_classes,), True)
    correct = helper.create_variable_for_type_inference(
        "int64", (num_classes,), True)
    helper.append_op("mean_iou",
                     {"Predictions": [input], "Labels": [label]},
                     {"OutMeanIou": [miou], "OutWrong": [wrong],
                      "OutCorrect": [correct]},
                     {"num_classes": num_classes})
    return miou, wrong, correct


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32", name=None):
    helper = LayerHelper("sampling_id", name=name)
    out = helper.create_variable_for_type_inference(
        "int64", (x.shape[0],), True)
    helper.append_op("sampling_id", {"X": [x]}, {"Out": [out]}, {})
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """Bucket-hash int id windows → [..., num_hash] int64 in
    [0, hash_size)."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference(
        "int64", tuple(input.shape[:-1]) + (num_hash,), True)
    helper.append_op("hash", {"X": [input]}, {"Out": [out]},
                     {"mod_by": hash_size, "num_hash": num_hash})
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    helper = LayerHelper("stanh", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("stanh", {"X": [x]}, {"Out": [out]},
                     {"scale_a": scale_a, "scale_b": scale_b})
    return out


def sum(x, name=None):
    """Elementwise sum of a list of tensors (ref sum_op); single tensors
    pass through the sums kernel unchanged."""
    from .tensor import sums
    return sums(x if isinstance(x, (list, tuple)) else [x])


def has_inf(x, name=None):
    helper = LayerHelper("has_inf", name=name)
    out = helper.create_variable_for_type_inference("bool", (), True)
    helper.append_op("has_inf", {"X": [x]}, {"Out": [out]}, {})
    return out


def has_nan(x, name=None):
    helper = LayerHelper("has_nan", name=name)
    out = helper.create_variable_for_type_inference("bool", (), True)
    helper.append_op("has_nan", {"X": [x]}, {"Out": [out]}, {})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0, name=None):
    helper = LayerHelper("uniform_random_batch_size_like", name=name)
    out_shape = list(shape)
    out_shape[output_dim_idx] = input.shape[input_dim_idx]
    out = helper.create_variable_for_type_inference(
        dtype, tuple(out_shape), True)
    helper.append_op("uniform_random_batch_size_like", {"Input": [input]},
                     {"Out": [out]},
                     {"shape": list(shape), "dtype": dtype, "min": min,
                      "max": max, "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    return out


def gaussian_random_batch_size_like(input, shape, dtype="float32",
                                    input_dim_idx=0, output_dim_idx=0,
                                    mean=0.0, std=1.0, seed=0, name=None):
    helper = LayerHelper("gaussian_random_batch_size_like", name=name)
    out_shape = list(shape)
    out_shape[output_dim_idx] = input.shape[input_dim_idx]
    out = helper.create_variable_for_type_inference(
        dtype, tuple(out_shape), True)
    helper.append_op("gaussian_random_batch_size_like", {"Input": [input]},
                     {"Out": [out]},
                     {"shape": list(shape), "dtype": dtype, "mean": mean,
                      "std": std, "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    return out
