"""Generated activation layers.

Parity: python/paddle/fluid/layers/ops.py — one thin layer function per
registered activation op (the ref generates these from OpProtos).
"""
from .layer_function_generator import generate_layer_fn_noattr

_UNARY = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "sqrt", "rsqrt",
    "abs", "ceil", "floor", "cos", "sin", "tan", "acos", "asin", "atan",
    "sinh", "cosh", "round", "reciprocal", "square", "softplus", "softsign",
    "log", "log1p", "relu", "gelu", "elu", "selu", "erf", "sign", "silu",
    "mish",
]

__all__ = list(_UNARY)


def _make(op_type):
    layer = generate_layer_fn_noattr(op_type)
    layer.__doc__ = f"{op_type} activation (ref layers/ops.py:{op_type})"
    return layer


for _t in _UNARY:
    globals()[_t] = _make(_t)
