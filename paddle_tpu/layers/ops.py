"""Generated activation layers.

Parity: python/paddle/fluid/layers/ops.py — one thin layer function per
registered activation op (the ref generates these from OpProtos).
"""
from ..layer_helper import LayerHelper

_UNARY = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "sqrt", "rsqrt",
    "abs", "ceil", "floor", "cos", "sin", "tan", "acos", "asin", "atan",
    "sinh", "cosh", "round", "reciprocal", "square", "softplus", "softsign",
    "log", "log1p", "relu", "gelu", "elu", "selu", "erf", "sign", "silu",
    "mish",
]

__all__ = list(_UNARY)


def _make(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
        helper.append_op(op_type, {"X": [x]}, {"Out": [out]}, {})
        return out
    layer.__name__ = op_type
    layer.__doc__ = f"{op_type} activation (ref layers/ops.py:{op_type})"
    return layer


for _t in _UNARY:
    globals()[_t] = _make(_t)
