"""Data layers.

Parity: python/paddle/fluid/layers/io.py — `data` declares a feed
variable (LoD level becomes a companion sequence-length convention);
`py_reader`/`double_buffer` map onto the host-side prefetch pipeline in
reader/pipeline.py (device feed is async via jax dispatch).
"""
from ..core.framework import default_main_program
from ..core.dtypes import convert_dtype

__all__ = ["data"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare an input variable (ref layers/io.py:data).

    append_batch_size=True prepends a -1 batch dim like the reference.
    For lod_level>0 data, feed padded arrays and declare a separate
    `<name>_seq_len` int64 data var (see lod.py helpers).
    """
    shape = list(shape)
    if append_batch_size and (not shape or shape[0] != -1):
        shape = [-1] + shape
    block = default_main_program().global_block()
    return block.create_var(
        name=name, shape=tuple(shape), dtype=convert_dtype(dtype),
        is_data=True, stop_gradient=stop_gradient, lod_level=lod_level)
