"""Data layers + in-program readers.

Parity: python/paddle/fluid/layers/io.py — `data` declares a feed
variable; py_reader / create_py_reader_by_data / open_files /
random_data_generator build host-side prefetch queues that the Executor
drains automatically when no explicit feed covers their variables
(replacing the reference's C++ reader queue + double_buffer ops,
reader/open_files_op.cc). End of data raises core.EOFException exactly
like the reference.
"""
import logging
import threading
import time
import queue as _queue

import numpy as np

from .. import unique_name
from .. import telemetry as _tm
from ..core.framework import default_main_program
from ..core.dtypes import convert_dtype
from ..core import EOFException

_LOG = logging.getLogger("paddle_tpu.py_reader")

__all__ = ["data", "py_reader", "create_py_reader_by_data", "read_file",
           "double_buffer", "batch", "shuffle", "open_files",
           "random_data_generator", "Preprocessor", "load"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare an input variable (ref layers/io.py:data).

    append_batch_size=True prepends a -1 batch dim like the reference.
    For lod_level>0 data, feed padded arrays and declare a separate
    `<name>_seq_len` int64 data var (see lod.py helpers).
    """
    shape = list(shape)
    if append_batch_size and (not shape or shape[0] != -1):
        shape = [-1] + shape
    block = default_main_program().global_block()
    return block.create_var(
        name=name, shape=tuple(shape), dtype=convert_dtype(dtype),
        is_data=True, stop_gradient=stop_gradient, lod_level=lod_level)


class _ReaderError:
    """Provider exception carried through the feed queue to the
    consumer (re-raised by next_feed)."""

    def __init__(self, exc):
        self.exc = exc


class PyReader:
    """Host-side feed queue bound to program data variables.

    A daemon thread pulls batches from the decorated provider into a
    bounded queue; Executor.run pops one batch per step when the reader's
    variables aren't explicitly fed. With use_double_buffer the queue
    depth gives the double-buffer overlap (JAX device puts are async, so
    one batch transfers while the previous computes)."""

    def __init__(self, vars, capacity=64, use_double_buffer=True,
                 provider=None):
        self.vars = list(vars)
        self.capacity = max(2 if use_double_buffer else 1, int(capacity))
        # double-buffer arming (ref py_reader(use_double_buffer=True) /
        # layers.double_buffer): marks this reader eligible for the
        # DEVICE prefetch stage — under Executor.run(async_steps=k) its
        # batches are device_put on a background thread while the
        # current step computes (core/pipeline_exec.DevicePrefetcher).
        # A no-op when async mode is off: the host queue alone already
        # overlaps the provider with training.
        self._device_prefetch = bool(use_double_buffer)
        self._provider = provider
        self._thread = None
        self._q = None
        self._started = False
        self._END = object()
        self._stats = {"polls": 0, "depth_sum": 0, "starved_polls": 0,
                       "low_watermark": float("inf"), "high_watermark": 0}

    # -- decoration (ref decorate_paddle_reader / decorate_tensor_provider)
    def decorate_paddle_reader(self, reader):
        """reader() yields batches: lists of per-sample tuples."""
        def provider():
            for batch_data in reader():
                cols = list(zip(*batch_data))
                yield [np.asarray(np.stack(c), dtype=v.dtype)
                       for c, v in zip(cols, self.vars)]
        self._provider = provider
        return self

    def decorate_tensor_provider(self, reader):
        """reader() yields lists of ready arrays, one per variable."""
        def provider():
            for arrays in reader():
                yield [np.asarray(a, dtype=v.dtype)
                       for a, v in zip(arrays, self.vars)]
        self._provider = provider
        return self

    decorate_batch_generator = decorate_tensor_provider

    # -- lifecycle
    def start(self):
        if self._provider is None:
            raise RuntimeError("py_reader not decorated with a data source")
        if self._started:
            return
        self._q = _queue.Queue(maxsize=self.capacity)
        self._stop = threading.Event()
        q, end, stop = self._q, self._END, self._stop

        def put(item):
            # producer-side backpressure wait: time blocked on a full
            # queue (telemetry on only — the clock reads stay off the
            # disabled path)
            t0 = time.perf_counter() if _tm.enabled() else None
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    if t0 is not None:
                        _tm.histogram(
                            "reader.producer_wait_seconds").observe(
                            time.perf_counter() - t0)
                    return True
                except _queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in self._provider():
                    if not put(item):
                        return          # reset() requested — exit cleanly
            except Exception as e:
                # surface to the consumer: swallowing here would turn a
                # data-pipeline error into a silent truncated epoch
                put(_ReaderError(e))
            finally:
                put(end)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()
        self._started = True

    def reset(self):
        if self._thread is not None:
            self._stop.set()
            # drain so a blocked worker can notice the stop flag
            try:
                while True:
                    self._q.get_nowait()
            except _queue.Empty:
                pass
            self._thread.join(timeout=2.0)
        self._thread, self._q, self._started = None, None, False

    def is_started(self):
        return self._started

    def next_feed(self):
        """One batch as {var_name: array}; EOFException at end of data."""
        if not self._started:
            self.start()
        # queue watermark accounting (SURVEY §2.8 stall detection): a
        # consumer that keeps finding the queue empty is feed-starved —
        # the producer thread (or upstream pipeline) is the stall.
        depth = self._q.qsize()
        self._stats["polls"] += 1
        self._stats["depth_sum"] += depth
        self._stats["low_watermark"] = min(self._stats["low_watermark"],
                                           depth)
        self._stats["high_watermark"] = max(self._stats["high_watermark"],
                                            depth)
        if depth == 0:
            self._stats["starved_polls"] += 1
            n = self._stats["starved_polls"]
            if n in (10, 100) or n % 1000 == 0:
                _LOG.warning(
                    "py_reader feed starvation: queue empty on %d/%d "
                    "polls (capacity %d) — the producer is the "
                    "bottleneck", n, self._stats["polls"], self.capacity)
        if _tm.enabled():
            _tm.gauge("reader.queue_depth").set(depth)
            _tm.gauge("reader.queue_capacity").set(self.capacity)
            _tm.counter("reader.polls").inc()
            if depth == 0:
                _tm.counter("reader.starved_polls").inc()
            t0 = time.perf_counter()
            item = self._q.get()
            _tm.histogram("reader.consumer_wait_seconds").observe(
                time.perf_counter() - t0)
        else:
            item = self._q.get()
        if isinstance(item, _ReaderError):
            self._started = False
            raise item.exc
        if item is self._END:
            self._started = False
            raise EOFException("py_reader exhausted; call reset()+start()")
        return {v.name: a for v, a in zip(self.vars, item)}

    def queue_stats(self):
        """Watermark/starvation counters since construction."""
        s = dict(self._stats)
        s["capacity"] = self.capacity
        if s["polls"]:
            s["mean_depth"] = s["depth_sum"] / s["polls"]
        if s["low_watermark"] == float("inf"):
            s["low_watermark"] = 0
        return s


def _register_reader(reader, program=None):
    program = program or default_main_program()
    if not hasattr(program, "_py_readers"):
        program._py_readers = []
    program._py_readers.append(reader)
    return reader


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """ref layers.py_reader → PyReader over fresh data variables."""
    name = name or unique_name.generate("py_reader")
    vars = []
    for i, (s, d) in enumerate(zip(shapes, dtypes)):
        lod = lod_levels[i] if lod_levels else 0
        vars.append(data(f"{name}_slot{i}", shape=list(s), dtype=d,
                         lod_level=lod, append_batch_size=False))
    return _register_reader(PyReader(vars, capacity, use_double_buffer))


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """ref create_py_reader_by_data: reuse existing data vars."""
    return _register_reader(
        PyReader(feed_list, capacity, use_double_buffer))


def read_file(reader):
    """ref layers.read_file: the variables one step of the reader fills."""
    vars = reader.vars
    return vars[0] if len(vars) == 1 else list(vars)


def double_buffer(reader, place=None, name=None):
    """ref layers.double_buffer — the PyReader queue already overlaps
    the provider with compute; this bumps its depth AND arms the
    device-prefetch stage, so under `Executor.run(async_steps=k)` /
    `PADDLE_TPU_ASYNC=k` the next batch is staged in device memory by
    a background thread while the current step computes (the
    reference's double_buffer op semantics). With async mode off the
    arming is a no-op."""
    reader.capacity = max(reader.capacity, 2)
    reader._device_prefetch = True
    return reader


def batch(reader, batch_size):
    """ref layers.batch (reader-op version): regroup a sample-level
    provider into fixed batches."""
    inner = reader._provider
    if inner is None:
        raise RuntimeError("decorate the reader before layers.batch")

    def provider():
        buf = []
        for sample in inner():
            buf.append(sample)
            if len(buf) == batch_size:
                yield [np.stack(c) for c in zip(*buf)]
                buf = []
        if buf:
            yield [np.stack(c) for c in zip(*buf)]
    reader._provider = provider
    return reader


def shuffle(reader, buffer_size):
    """ref layers.shuffle (reader-op version)."""
    inner = reader._provider
    if inner is None:
        raise RuntimeError("decorate the reader before layers.shuffle")
    import random as _random

    def provider():
        rng = _random.Random()   # fresh order each epoch/start
        buf = []
        for item in inner():
            buf.append(item)
            if len(buf) >= buffer_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf
    reader._provider = provider
    return reader


def open_files(filenames, shapes, dtypes, lod_levels=None, thread_num=1,
               buffer_size=None, pass_num=1, is_test=None, name=None):
    """ref layers.open_files: read recordio files of pickled samples
    (recordio_writer.convert_reader_to_recordio_file format)."""
    from ..recordio_writer import recordio_reader
    if isinstance(filenames, str):
        filenames = [filenames]
    rd = py_reader(buffer_size or 64, shapes, dtypes, lod_levels, name=name)

    def provider():
        for _ in range(pass_num):
            for fn in filenames:
                for sample in recordio_reader(fn)():
                    yield [np.asarray(c, dtype=v.dtype)
                           for c, v in zip(sample, rd.vars)]
    rd._provider = provider
    return rd


def random_data_generator(low, high, shapes, lod_levels=None,
                          for_parallel=True):
    """ref layers.random_data_generator: infinite uniform random feeds
    (used by reader benchmarks/tests)."""
    dtypes = ["float32"] * len(shapes)
    rd = py_reader(4, shapes, dtypes, lod_levels)
    rng = np.random.RandomState(0)

    def provider():
        while True:
            yield [rng.uniform(low, high, size=tuple(s)).astype("float32")
                   for s in shapes]
    rd._provider = provider
    return rd


class Preprocessor:
    """ref layers.io.Preprocessor: transform reader batches with a block
    of ops. The block builds a SEPARATE small Program which runs on each
    batch before it enters the feed queue (the reference splices the
    sub-block into the main ProgramDesc; here the main program stays one
    clean XLA module and preprocessing overlaps on the host thread)."""

    def __init__(self, reader, name=None):
        self.underlying = reader
        self.name = name or unique_name.generate("preprocessor")
        self._program = None
        self._startup = None
        self._in_vars = None
        self._out_vars = None
        self.vars = None

    def block(self):
        from ..core.framework import Program, program_guard
        p = self

        class _G:
            def __enter__(g):
                p._program = Program()
                p._startup = Program()
                g.guard = program_guard(p._program, p._startup)
                g.guard.__enter__()
                return p

            def __exit__(g, et, ev, tb):
                g.guard.__exit__(et, ev, tb)
                if et is None:
                    p._complete()
                return False

        return _G()

    def inputs(self):
        self._in_vars = [
            data(f"{self.name}_in{i}", shape=list(v.shape), dtype=v.dtype,
                 append_batch_size=False)
            for i, v in enumerate(self.underlying.vars)]
        return self._in_vars

    def outputs(self, *outs):
        self._out_vars = list(outs)

    def _complete(self):
        if self._in_vars is None or self._out_vars is None:
            raise RuntimeError("Preprocessor.block must call inputs() and "
                               "outputs()")
        # declare transformed vars in the MAIN program for read_file
        main = default_main_program().global_block()
        self.vars = [
            main.create_var(name=f"{self.name}_out{i}",
                            shape=tuple(v.shape), dtype=v.dtype,
                            is_data=True, stop_gradient=True)
            for i, v in enumerate(self._out_vars)]
        # the preprocessor replaces its underlying reader as the feed
        # source — the raw slots must not also be auto-fed
        prog = default_main_program()
        regs = getattr(prog, "_py_readers", [])
        if self.underlying in regs:
            regs.remove(self.underlying)
        _register_reader(self)

        from ..core.executor import Executor
        from ..core.place import CPUPlace
        exe = Executor(CPUPlace())
        prog, outs = self._program, self._out_vars

        def transform(feed):
            return exe.run(prog, feed=feed, fetch_list=outs)
        self._transform = transform

    # reader protocol (Executor pulls through these)
    def start(self):
        self.underlying.start()

    def reset(self):
        self.underlying.reset()

    def is_started(self):
        return self.underlying.is_started()

    def next_feed(self):
        raw = self.underlying.next_feed()
        feed = {iv.name: raw[uv.name]
                for iv, uv in zip(self._in_vars, self.underlying.vars)}
        res = self._transform(feed)
        return {v.name: a for v, a in zip(self.vars, res)}


def load(out, file_path, load_as_fp16=False):
    """ref layers.load: fill `out` from a file saved by io.save_vars."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("load")
    helper.append_op("load_from_file", {}, {"Out": [out]},
                     {"file_path": file_path, "var_name": out.name,
                      "load_as_fp16": bool(load_as_fp16)})
    return out
