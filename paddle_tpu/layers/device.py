"""Device placement helpers.

Parity: python/paddle/fluid/layers/device.py — get_places was the
multi-GPU placement list for the old ParallelDo; on TPU the mesh owns
placement, so this returns the visible JAX devices' places (deprecated
in the reference too, kept for import compatibility).
"""
from ..annotations import deprecated

__all__ = []


@deprecated(since="0.15.0", instead="ParallelExecutor")
def get_places(device_count=None, device_type=None):
    import jax
    from ..core.place import CPUPlace, TPUPlace
    devs = jax.devices()
    if device_count is not None:
        devs = devs[:device_count]
    if not devs:
        return []
    if device_type == "CPU" or devs[0].platform == "cpu":
        return [CPUPlace() for _ in devs]
    return [TPUPlace(i) for i, _ in enumerate(devs)]
