"""Sequence layers — LoD ops over padded arrays + explicit lengths.

Parity: python/paddle/fluid/operators/sequence_ops/* and the sequence
functions in layers/nn.py. The reference encodes variable-length batches
as LoDTensors; XLA needs static shapes, so every sequence layer here
takes (data [B,T,...], seq_len [B]) — see lod.py for converters. This is
the design swap documented in SURVEY §6.
"""
from ..layer_helper import LayerHelper

__all__ = [
    "sequence_pool", "sequence_softmax", "sequence_expand", "sequence_concat",
    "sequence_reverse", "sequence_mask", "sequence_last_step",
    "sequence_first_step", "sequence_pad", "sequence_conv",
    "sequence_expand_as", "sequence_reshape", "sequence_slice",
    "sequence_unpad", "sequence_scatter", "sequence_enumerate", "row_conv",
    "chunk_eval",
]


def sequence_pool(input, pool_type, seq_len=None, is_test=False):
    helper = LayerHelper("sequence_pool")
    if seq_len is None:
        raise ValueError(
            "sequence_pool requires seq_len (padded-array LoD convention; "
            "see paddle_tpu.lod.to_padded)")
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0],) + tuple(input.shape[2:]))
    helper.append_op("sequence_pool", {"X": [input], "SeqLen": [seq_len]},
                     {"Out": [out]}, {"pooltype": pool_type.upper()})
    return out


def sequence_last_step(input, seq_len=None):
    return sequence_pool(input, "last", seq_len)


def sequence_first_step(input, seq_len=None):
    return sequence_pool(input, "first", seq_len)


def sequence_softmax(input, seq_len=None, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    if seq_len is None:
        raise ValueError("sequence_softmax requires seq_len")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("sequence_softmax", {"X": [input], "SeqLen": [seq_len]},
                     {"Out": [out]}, {})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out_shape = (x.shape[0], y.shape[1]) + tuple(x.shape[2:]) \
        if len(x.shape) != len(y.shape) else tuple(y.shape[:2]) + tuple(x.shape[2:])
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op("sequence_expand", {"X": [x], "Y": [y]}, {"Out": [out]},
                     {"ref_level": ref_level})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    xs = list(input)
    t = sum(x.shape[1] for x in xs) if all(x.shape[1] > 0 for x in xs) else -1
    out = helper.create_variable_for_type_inference(
        xs[0].dtype, (xs[0].shape[0], t) + tuple(xs[0].shape[2:]))
    helper.append_op("sequence_concat", {"X": xs}, {"Out": [out]}, {})
    return out


def sequence_reverse(x, seq_len=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    if seq_len is None:
        raise ValueError("sequence_reverse requires seq_len")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("sequence_reverse", {"X": [x], "SeqLen": [seq_len]},
                     {"Y": [out]}, {})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    if maxlen is None or maxlen <= 0:
        raise ValueError("sequence_mask requires a static maxlen on TPU")
    out = helper.create_variable_for_type_inference(
        dtype, (x.shape[0], maxlen), True)
    helper.append_op("sequence_mask", {"X": [x]}, {"Y": [out]},
                     {"maxlen": maxlen, "out_dtype": dtype})
    return out


def sequence_pad(x, pad_value=None, maxlen=None, seq_len=None, name=None):
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    length = helper.create_variable_for_type_inference("int64", (x.shape[0],), True)
    helper.append_op("sequence_pad", {"X": [x], "SeqLen": [seq_len]},
                     {"Out": [out], "Length": [length]}, {})
    return out, length


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, bias_attr=None, param_attr=None, act=None,
                  name=None, seq_len=None):
    """Context-window conv over time (ref layers/nn.py:sequence_conv).
    input [B,T,D]."""
    if filter_stride != 1:
        raise ValueError(
            "sequence_conv supports filter_stride == 1 only (matching the "
            "reference sequence_conv_op)")
    helper = LayerHelper("sequence_conv", name=name, act=act,
                         bias_attr=bias_attr)
    dtype = input.dtype
    D = int(input.shape[-1])
    w = helper.create_parameter(param_attr,
                                shape=[filter_size * D, num_filters],
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], input.shape[1], num_filters))
    ins = {"X": [input], "Filter": [w]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op("sequence_conv", ins, {"Out": [out]},
                     {"context_length": filter_size,
                      "context_start": -((filter_size - 1) // 2),
                      "context_stride": filter_stride})
    out = helper.append_bias_op(out, dim_start=2, bias_attr=bias_attr,
                                size=num_filters)
    return helper.append_activation(out, act)


def row_conv(input, future_context_size, param_attr=None, act=None, name=None):
    """Lookahead convolution (ref layers/nn.py:row_conv). input [B,T,D]."""
    helper = LayerHelper("row_conv", name=name)
    D = int(input.shape[-1])
    w = helper.create_parameter(param_attr,
                                shape=[future_context_size + 1, D],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("row_conv", {"X": [input], "Filter": [w]},
                     {"Out": [out]}, {})
    return helper.append_activation(out, act)


def sequence_expand_as(x, y, name=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    out_shape = ((x.shape[0], y.shape[1]) + tuple(x.shape[1:])
                 if len(x.shape) != len(y.shape)
                 else tuple(y.shape[:2]) + tuple(x.shape[2:]))
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op("sequence_expand_as", {"X": [x], "Y": [y]},
                     {"Out": [out]}, {})
    return out


def sequence_reshape(input, new_dim, name=None):
    helper = LayerHelper("sequence_reshape", name=name)
    B, T, D = input.shape[0], int(input.shape[1]), int(input.shape[-1])
    out = helper.create_variable_for_type_inference(
        input.dtype, (B, T * D // new_dim, new_dim))
    helper.append_op("sequence_reshape", {"X": [input]}, {"Out": [out]},
                     {"new_dim": new_dim})
    return out


def sequence_slice(input, offset, length, name=None):
    """Per-sequence slice; offset/length are [B] (or [B,1]) tensors.
    Output stays padded at input's T with new lengths returned."""
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    out_len = helper.create_variable_for_type_inference(
        "int64", (input.shape[0],), True)
    helper.append_op("sequence_slice",
                     {"X": [input], "Offset": [offset], "Length": [length]},
                     {"Out": [out], "OutLen": [out_len]}, {})
    return out, out_len


def sequence_unpad(x, length, name=None):
    """Padded analog of ref sequence_unpad: masks past-length positions;
    returns (data, lengths)."""
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    out_len = helper.create_variable_for_type_inference(
        "int64", (x.shape[0],), True)
    helper.append_op("sequence_unpad", {"X": [x], "Length": [length]},
                     {"Out": [out], "OutLen": [out_len]}, {})
    return out, out_len


def sequence_scatter(input, index, updates, seq_len=None, name=None):
    """Adds updates into input at per-row time positions (ref
    sequence_scatter_op)."""
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    ins = {"X": [input], "Ids": [index], "Updates": [updates]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op("sequence_scatter", ins, {"Out": [out]}, {})
    return out


def sequence_enumerate(input, win_size, pad_value=0, seq_len=None, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, tuple(input.shape) + (win_size,), True)
    ins = {"X": [input]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op("sequence_enumerate", ins, {"Out": [out]},
                     {"win_size": win_size, "pad_value": pad_value})
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types, seq_len=None,
               excluded_chunk_types=None, name=None):
    """Chunk detection metrics (ref layers/nn.py:chunk_eval). IOB scheme:
    label = type*2 + (0 for B, 1 for I); label == 2*num_chunk_types is O."""
    if chunk_scheme not in ("IOB",):
        raise NotImplementedError(
            f"chunk_scheme {chunk_scheme!r}: only IOB supported (the other "
            "ref schemes re-encode to IOB)")
    helper = LayerHelper("chunk_eval", name=name)
    f32 = lambda: helper.create_variable_for_type_inference("float32", (), True)
    i64 = lambda: helper.create_variable_for_type_inference("int64", (), True)
    prec, rec, f1 = f32(), f32(), f32()
    ni, nl, nc = i64(), i64(), i64()
    ins = {"Inference": [input], "Label": [label]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op("chunk_eval", ins,
                     {"Precision": [prec], "Recall": [rec], "F1-Score": [f1],
                      "NumInferChunks": [ni], "NumLabelChunks": [nl],
                      "NumCorrectChunks": [nc]},
                     {"num_chunk_types": num_chunk_types,
                      "chunk_scheme": chunk_scheme,
                      "excluded_chunk_types":
                          list(excluded_chunk_types or [])})
    return prec, rec, f1, ni, nl, nc
