"""Sequence layers — LoD ops over padded arrays + explicit lengths.

Parity: python/paddle/fluid/operators/sequence_ops/* and the sequence
functions in layers/nn.py. The reference encodes variable-length batches
as LoDTensors; XLA needs static shapes, so every sequence layer here
takes (data [B,T,...], seq_len [B]) — see lod.py for converters. This is
the design swap documented in SURVEY §6.
"""
from ..layer_helper import LayerHelper

__all__ = [
    "sequence_pool", "sequence_softmax", "sequence_expand", "sequence_concat",
    "sequence_reverse", "sequence_mask", "sequence_last_step",
    "sequence_first_step", "sequence_pad",
]


def sequence_pool(input, pool_type, seq_len=None, is_test=False):
    helper = LayerHelper("sequence_pool")
    if seq_len is None:
        raise ValueError(
            "sequence_pool requires seq_len (padded-array LoD convention; "
            "see paddle_tpu.lod.to_padded)")
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0],) + tuple(input.shape[2:]))
    helper.append_op("sequence_pool", {"X": [input], "SeqLen": [seq_len]},
                     {"Out": [out]}, {"pooltype": pool_type.upper()})
    return out


def sequence_last_step(input, seq_len=None):
    return sequence_pool(input, "last", seq_len)


def sequence_first_step(input, seq_len=None):
    return sequence_pool(input, "first", seq_len)


def sequence_softmax(input, seq_len=None, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    if seq_len is None:
        raise ValueError("sequence_softmax requires seq_len")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("sequence_softmax", {"X": [input], "SeqLen": [seq_len]},
                     {"Out": [out]}, {})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out_shape = (x.shape[0], y.shape[1]) + tuple(x.shape[2:]) \
        if len(x.shape) != len(y.shape) else tuple(y.shape[:2]) + tuple(x.shape[2:])
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op("sequence_expand", {"X": [x], "Y": [y]}, {"Out": [out]},
                     {"ref_level": ref_level})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    xs = list(input)
    t = sum(x.shape[1] for x in xs) if all(x.shape[1] > 0 for x in xs) else -1
    out = helper.create_variable_for_type_inference(
        xs[0].dtype, (xs[0].shape[0], t) + tuple(xs[0].shape[2:]))
    helper.append_op("sequence_concat", {"X": xs}, {"Out": [out]}, {})
    return out


def sequence_reverse(x, seq_len=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    if seq_len is None:
        raise ValueError("sequence_reverse requires seq_len")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("sequence_reverse", {"X": [x], "SeqLen": [seq_len]},
                     {"Y": [out]}, {})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    if maxlen is None or maxlen <= 0:
        raise ValueError("sequence_mask requires a static maxlen on TPU")
    out = helper.create_variable_for_type_inference(
        dtype, (x.shape[0], maxlen), True)
    helper.append_op("sequence_mask", {"X": [x]}, {"Y": [out]},
                     {"maxlen": maxlen, "out_dtype": dtype})
    return out


def sequence_pad(x, pad_value=None, maxlen=None, seq_len=None, name=None):
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    length = helper.create_variable_for_type_inference("int64", (x.shape[0],), True)
    helper.append_op("sequence_pad", {"X": [x], "SeqLen": [seq_len]},
                     {"Out": [out], "Length": [length]}, {})
    return out, length
