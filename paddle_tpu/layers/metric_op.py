"""In-graph metric layers.

Parity: python/paddle/fluid/layers/metric_op.py (accuracy, auc).
"""
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(
        input.dtype, tuple(input.shape[:-1]) + (k,), True)
    topk_idx = helper.create_variable_for_type_inference(
        "int64", tuple(input.shape[:-1]) + (k,), True)
    helper.append_op("top_k", {"X": [input]},
                     {"Out": [topk_out], "Indices": [topk_idx]}, {"k": k})
    acc = helper.create_variable_for_type_inference("float32", (), True)
    correct = correct or helper.create_variable_for_type_inference(
        "int32", (), True)
    total = total or helper.create_variable_for_type_inference(
        "int32", (), True)
    helper.append_op("accuracy",
                     {"Out": [input], "Indices": [topk_idx], "Label": [label]},
                     {"Accuracy": [acc], "Correct": [correct],
                      "Total": [total]}, {"k": k})
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming AUC with persistable histogram state (ref metric_op.py:auc)."""
    helper = LayerHelper("auc")
    buckets = num_thresholds + 1
    stat_pos = helper.create_global_variable([buckets], "float32",
                                             persistable=True)
    stat_neg = helper.create_global_variable([buckets], "float32",
                                             persistable=True)
    helper.set_variable_initializer(stat_pos, ConstantInitializer(0.0))
    helper.set_variable_initializer(stat_neg, ConstantInitializer(0.0))
    auc_out = helper.create_variable_for_type_inference("float32", (), True)
    helper.append_op(
        "auc",
        {"Predict": [input], "Label": [label],
         "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        {"AUC": [auc_out], "StatPosOut": [stat_pos], "StatNegOut": [stat_neg]},
        {"num_thresholds": num_thresholds})
    return auc_out, auc_out, [stat_pos, stat_neg]
