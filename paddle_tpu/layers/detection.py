"""Detection layers (SSD family).

Parity: python/paddle/fluid/layers/detection.py — prior_box, box_coder,
multiclass NMS, iou. TPU notes: NMS output is FIXED-SIZE (keep_top_k
padded with -1 labels) because XLA needs static shapes; the reference's
LoD-variable outputs are a host-side concept.
"""
import numpy as np

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "multiclass_nms",
           "ssd_loss_stub", "detection_output"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    h, w = int(input.shape[2]), int(input.shape[3])
    n_prior = _num_priors(min_sizes, max_sizes, aspect_ratios, flip)
    boxes = helper.create_variable_for_type_inference(
        "float32", (h, w, n_prior, 4), True)
    var = helper.create_variable_for_type_inference(
        "float32", (h, w, n_prior, 4), True)
    helper.append_op("prior_box", {"Input": [input], "Image": [image]},
                     {"Boxes": [boxes], "Variances": [var]},
                     {"min_sizes": list(min_sizes),
                      "max_sizes": list(max_sizes or []),
                      "aspect_ratios": list(aspect_ratios),
                      "variances": list(variance), "flip": flip,
                      "clip": clip, "steps": list(steps), "offset": offset})
    return boxes, var


def _num_priors(min_sizes, max_sizes, aspect_ratios, flip):
    ars = [1.0]
    for ar in aspect_ratios:
        if abs(ar - 1.0) > 1e-6:
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    n = len(min_sizes) * len(ars)
    if max_sizes:
        n += len(max_sizes)
    return n


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", target_box.shape, True)
    helper.append_op("box_coder",
                     {"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                      "TargetBox": [target_box]},
                     {"OutputBox": [out]},
                     {"code_type": code_type,
                      "box_normalized": box_normalized})
    return out


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", (x.shape[0], y.shape[0]), True)
    helper.append_op("iou_similarity", {"X": [x], "Y": [y]},
                     {"Out": [out]}, {})
    return out


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=200, nms_threshold=0.3, normalized=True,
                   background_label=0, name=None):
    """Fixed-size NMS: returns [N, keep_top_k, 6] (label, score, x1..y2),
    padded rows have label=-1 (XLA static-shape version of the ref op)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", (bboxes.shape[0], keep_top_k, 6), True)
    helper.append_op("multiclass_nms",
                     {"BBoxes": [bboxes], "Scores": [scores]},
                     {"Out": [out]},
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold,
                      "background_label": background_label})
    return out


detection_output = multiclass_nms


def ssd_loss_stub(*a, **k):
    raise NotImplementedError(
        "ssd_loss: planned for a later round (needs matched-box targets); "
        "prior_box/box_coder/iou/multiclass_nms are available")
