"""Detection layers (SSD / Faster-RCNN / YOLOv3 families).

Parity: python/paddle/fluid/layers/detection.py. TPU conventions (static
shapes replacing the reference's LoD variable-length tensors):
- NMS-family outputs are fixed keep_top_k rows padded with label -1
- ground truth comes as padded [B, G, ...] batches (pad label < 0 /
  degenerate boxes); RoIs are [R, 5] (batch_idx, x1..y2) or [R, 4]
- sampling ops (rpn_target_assign, generate_proposal_labels) emit fixed
  sample counts with a validity weight instead of variable index lists
"""
import numpy as np

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "density_prior_box", "anchor_generator",
           "box_coder", "iou_similarity", "multiclass_nms",
           "bipartite_match", "target_assign", "ssd_loss",
           "detection_output", "multi_box_head", "rpn_target_assign",
           "generate_proposals", "generate_proposal_labels",
           "roi_pool", "roi_align", "psroi_pool",
           "roi_perspective_transform", "polygon_box_transform",
           "yolov3_loss", "detection_map", "ssd_loss_stub"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    h, w = int(input.shape[2]), int(input.shape[3])
    n_prior = _num_priors(min_sizes, max_sizes, aspect_ratios, flip)
    boxes = helper.create_variable_for_type_inference(
        "float32", (h, w, n_prior, 4), True)
    var = helper.create_variable_for_type_inference(
        "float32", (h, w, n_prior, 4), True)
    helper.append_op("prior_box", {"Input": [input], "Image": [image]},
                     {"Boxes": [boxes], "Variances": [var]},
                     {"min_sizes": list(min_sizes),
                      "max_sizes": list(max_sizes or []),
                      "aspect_ratios": list(aspect_ratios),
                      "variances": list(variance), "flip": flip,
                      "clip": clip, "steps": list(steps), "offset": offset})
    return boxes, var


def _num_priors(min_sizes, max_sizes, aspect_ratios, flip):
    ars = [1.0]
    for ar in aspect_ratios:
        if abs(ar - 1.0) > 1e-6:
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    n = len(min_sizes) * len(ars)
    if max_sizes:
        n += len(max_sizes)
    return n


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", target_box.shape, True)
    helper.append_op("box_coder",
                     {"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                      "TargetBox": [target_box]},
                     {"OutputBox": [out]},
                     {"code_type": code_type,
                      "box_normalized": box_normalized})
    return out


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", (x.shape[0], y.shape[0]), True)
    helper.append_op("iou_similarity", {"X": [x], "Y": [y]},
                     {"Out": [out]}, {})
    return out


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=200, nms_threshold=0.3, normalized=True,
                   background_label=0, name=None):
    """Fixed-size NMS: returns [N, keep_top_k, 6] (label, score, x1..y2),
    padded rows have label=-1 (XLA static-shape version of the ref op)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", (bboxes.shape[0], keep_top_k, 6), True)
    helper.append_op("multiclass_nms",
                     {"BBoxes": [bboxes], "Scores": [scores]},
                     {"Out": [out]},
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold,
                      "background_label": background_label})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """ref layers.detection_output: decode loc vs priors, then NMS.
    loc [N, M, 4], scores [N, M, C] (post-softmax) → [N, keep_top_k, 6]."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    from . import nn as _nn
    sc = _nn.transpose(scores, perm=[0, 2, 1])       # [N, C, M]
    return multiclass_nms(decoded, sc, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label)


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", name=name)
    h, w = int(input.shape[2]), int(input.shape[3])
    A = len(anchor_sizes or []) * len(aspect_ratios or [])
    anchors = helper.create_variable_for_type_inference(
        "float32", (h, w, A, 4), True)
    var = helper.create_variable_for_type_inference(
        "float32", (h, w, A, 4), True)
    helper.append_op("anchor_generator", {"Input": [input]},
                     {"Anchors": [anchors], "Variances": [var]},
                     {"anchor_sizes": list(anchor_sizes),
                      "aspect_ratios": list(aspect_ratios),
                      "variances": list(variance),
                      "stride": list(stride or [16.0, 16.0]),
                      "offset": offset})
    return anchors, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    h, w = int(input.shape[2]), int(input.shape[3])
    P = sum(d * d * len(fixed_ratios or [1.0]) for d in (densities or []))
    shape = (h * w * P, 4) if flatten_to_2d else (h, w, P, 4)
    boxes = helper.create_variable_for_type_inference("float32", shape, True)
    var = helper.create_variable_for_type_inference("float32", shape, True)
    helper.append_op("density_prior_box",
                     {"Input": [input], "Image": [image]},
                     {"Boxes": [boxes], "Variances": [var]},
                     {"densities": list(densities or []),
                      "fixed_sizes": list(fixed_sizes or []),
                      "fixed_ratios": list(fixed_ratios or [1.0]),
                      "variances": list(variance), "clip": clip,
                      "steps": list(steps), "offset": offset,
                      "flatten_to_2d": flatten_to_2d})
    return boxes, var


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """ref layers.bipartite_match: greedy max matching col→row.
    dist_matrix [G, M] (or [B, G, M]) → match indices [B?, M]."""
    helper = LayerHelper("bipartite_match", name=name)
    shape = dist_matrix.shape
    out_shape = (shape[0], shape[2]) if len(shape) == 3 else (1, shape[1])
    match = helper.create_variable_for_type_inference("int32", out_shape, True)
    dist = helper.create_variable_for_type_inference("float32", out_shape, True)
    helper.append_op("bipartite_match", {"DistMat": [dist_matrix]},
                     {"ColToRowMatchIndices": [match],
                      "ColToRowMatchDist": [dist]},
                     {"match_type": match_type or "bipartite",
                      "dist_threshold": (0.5 if dist_threshold is None
                                         else dist_threshold)})
    return match, dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """ref layers.target_assign: out[b, j] = input[b, match[b, j]]."""
    helper = LayerHelper("target_assign", name=name)
    M = matched_indices.shape[-1]
    out = helper.create_variable_for_type_inference(
        input.dtype, (matched_indices.shape[0], M) + tuple(input.shape[2:]),
        True)
    wt = helper.create_variable_for_type_inference(
        "float32", (matched_indices.shape[0], M, 1), True)
    helper.append_op("target_assign",
                     {"X": [input], "MatchIndices": [matched_indices]},
                     {"Out": [out], "OutWeight": [wt]},
                     {"mismatch_value": (0 if mismatch_value is None
                                         else mismatch_value)})
    return out, wt


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """ref layers.ssd_loss (detection.py:779) as one fused op.
    location [B, M, 4], confidence [B, M, C], gt_box [B, G, 4],
    gt_label [B, G] with pad < 0 → per-prior loss [B, M]."""
    helper = LayerHelper("ssd_loss")
    if mining_type != "max_negative":
        raise ValueError("only max_negative mining is supported (ref default)")
    from . import tensor as _t
    if prior_box_var is None:
        prior_box_var = _t.fill_constant(
            [int(np.prod(prior_box.shape[:-1])), 4], "float32", 1.0)
    B, M = int(location.shape[0]), int(location.shape[1])
    loss = helper.create_variable_for_type_inference("float32", (B, M))
    helper.append_op("ssd_loss",
                     {"Loc": [location], "Conf": [confidence],
                      "GtBox": [gt_box], "GtLabel": [gt_label],
                      "PriorBox": [prior_box], "PriorVar": [prior_box_var]},
                     {"Loss": [loss]},
                     {"background_label": background_label,
                      "overlap_threshold": overlap_threshold,
                      "neg_pos_ratio": neg_pos_ratio,
                      "neg_overlap": neg_overlap,
                      "loc_loss_weight": loc_loss_weight,
                      "conf_loss_weight": conf_loss_weight,
                      "normalize": normalize})
    return loss


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """ref layers.multi_box_head (detection.py:1259): SSD heads — per
    feature map a conv for loc + conf and a prior_box, concatenated."""
    from . import nn as _nn
    from . import tensor as _t
    n = len(inputs)
    if min_sizes is None:
        # ref: interpolate ratios between min_ratio and max_ratio
        min_sizes, max_sizes = [], []
        step = int(np.floor((max_ratio - min_ratio) / (n - 2))) if n > 2 else 0
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n - 1]
    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        mx = (max_sizes[i] if isinstance(max_sizes[i], (list, tuple))
              else [max_sizes[i]]) if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        st = steps[i] if steps else [step_w[i] if step_w else 0.0,
                                     step_h[i] if step_h else 0.0]
        if not isinstance(st, (list, tuple)):
            st = [st, st]
        box, var = prior_box(feat, image, ms, mx, ar, variance, flip, clip,
                             (st[1], st[0]), offset)
        P = int(box.shape[2])
        loc = _nn.conv2d(feat, num_filters=P * 4, filter_size=kernel_size,
                         padding=pad, stride=stride)
        conf = _nn.conv2d(feat, num_filters=P * num_classes,
                          filter_size=kernel_size, padding=pad,
                          stride=stride)
        # [N, P*4, H, W] → [N, H*W*P, 4]
        loc = _nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = _nn.reshape(loc, [0, -1, 4])
        conf = _nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = _nn.reshape(conf, [0, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_l.append(_nn.reshape(box, [-1, 4]))
        vars_l.append(_nn.reshape(var, [-1, 4]))
    mbox_locs = _t.concat(locs, axis=1)
    mbox_confs = _t.concat(confs, axis=1)
    boxes = _t.concat(boxes_l, axis=0)
    variances = _t.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


# --- Faster-RCNN pipeline ---------------------------------------------------
def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """ref layers.rpn_target_assign (detection.py:54). Returns FIXED-size
    (loc, score, target_label, target_bbox, bbox_inside_weight) of
    S = rpn_batch_size_per_im samples per image; the last output doubles
    as the validity mask (the reference's variable-length gather)."""
    helper = LayerHelper("rpn_target_assign")
    B = int(bbox_pred.shape[0])
    S = rpn_batch_size_per_im
    loc = helper.create_variable_for_type_inference("float32", (B, S, 4))
    score = helper.create_variable_for_type_inference("float32", (B, S, 1))
    lab = helper.create_variable_for_type_inference("int32", (B, S), True)
    tgt = helper.create_variable_for_type_inference("float32", (B, S, 4), True)
    w = helper.create_variable_for_type_inference("float32", (B, S), True)
    helper.append_op("rpn_target_assign",
                     {"BboxPred": [bbox_pred], "ClsLogits": [cls_logits],
                      "AnchorBox": [anchor_box], "AnchorVar": [anchor_var],
                      "GtBoxes": [gt_boxes]},
                     {"PredictedLocation": [loc], "PredictedScores": [score],
                      "TargetLabel": [lab], "TargetBBox": [tgt],
                      "BBoxInsideWeight": [w]},
                     {"rpn_batch_size_per_im": rpn_batch_size_per_im,
                      "rpn_fg_fraction": rpn_fg_fraction,
                      "rpn_positive_overlap": rpn_positive_overlap,
                      "rpn_negative_overlap": rpn_negative_overlap,
                      "use_random": use_random})
    return loc, score, lab, tgt, w


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """ref layers.generate_proposals → rois [B, post_nms_top_n, 4] +
    roi probs [B, post_nms_top_n, 1] (zero rows past the kept count)."""
    helper = LayerHelper("generate_proposals", name=name)
    B = int(scores.shape[0])
    rois = helper.create_variable_for_type_inference(
        "float32", (B, post_nms_top_n, 4), True)
    probs = helper.create_variable_for_type_inference(
        "float32", (B, post_nms_top_n, 1), True)
    helper.append_op("generate_proposals",
                     {"Scores": [scores], "BboxDeltas": [bbox_deltas],
                      "ImInfo": [im_info], "Anchors": [anchors],
                      "Variances": [variances]},
                     {"RpnRois": [rois], "RpnRoiProbs": [probs]},
                     {"pre_nms_top_n": pre_nms_top_n,
                      "post_nms_top_n": post_nms_top_n,
                      "nms_thresh": nms_thresh, "min_size": min_size})
    return rois, probs


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd=None,
                             gt_boxes=None, im_info=None,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.25, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True):
    """ref layers.generate_proposal_labels → fixed P samples per image:
    (rois, labels_int32, bbox_targets, bbox_inside_weights,
    bbox_outside_weights)."""
    helper = LayerHelper("generate_proposal_labels")
    B = int(rpn_rois.shape[0])
    P = batch_size_per_im
    C = class_nums or 81
    rois = helper.create_variable_for_type_inference("float32", (B, P, 4), True)
    labels = helper.create_variable_for_type_inference("int32", (B, P), True)
    tgts = helper.create_variable_for_type_inference(
        "float32", (B, P, 4 * C), True)
    inw = helper.create_variable_for_type_inference(
        "float32", (B, P, 4 * C), True)
    outw = helper.create_variable_for_type_inference(
        "float32", (B, P, 4 * C), True)
    helper.append_op("generate_proposal_labels",
                     {"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                      "GtBoxes": [gt_boxes]},
                     {"Rois": [rois], "LabelsInt32": [labels],
                      "BboxTargets": [tgts], "BboxInsideWeights": [inw],
                      "BboxOutsideWeights": [outw]},
                     {"batch_size_per_im": batch_size_per_im,
                      "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
                      "bg_thresh_hi": bg_thresh_hi,
                      "bg_thresh_lo": bg_thresh_lo,
                      "bbox_reg_weights": list(bbox_reg_weights),
                      "class_nums": C, "use_random": use_random})
    return rois, labels, tgts, inw, outw


# --- RoI ops ---------------------------------------------------------------
def _roi_op(op_type, input, rois, pooled_height, pooled_width, attrs,
            out_channels=None):
    helper = LayerHelper(op_type)
    R = int(rois.shape[0])
    C = out_channels or int(input.shape[1])
    out = helper.create_variable_for_type_inference(
        input.dtype, (R, C, pooled_height, pooled_width))
    helper.append_op(op_type, {"X": [input], "ROIs": [rois]},
                     {"Out": [out]}, attrs)
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    """ref layers.roi_pool (nn.py:6270). rois [R, 5] (batch_idx, x1..y2)
    or [R, 4] (batch 0)."""
    return _roi_op("roi_pool", input, rois, pooled_height, pooled_width,
                   {"pooled_height": pooled_height,
                    "pooled_width": pooled_width,
                    "spatial_scale": spatial_scale})


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    """ref layers.roi_align (nn.py:6308)."""
    return _roi_op("roi_align", input, rois, pooled_height, pooled_width,
                   {"pooled_height": pooled_height,
                    "pooled_width": pooled_width,
                    "spatial_scale": spatial_scale,
                    "sampling_ratio": sampling_ratio})


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    """ref layers.psroi_pool (nn.py:9628): input channels must equal
    output_channels * pooled_height * pooled_width."""
    if int(input.shape[1]) != output_channels * pooled_height * pooled_width:
        raise ValueError("psroi_pool: C != output_channels*ph*pw")
    return _roi_op("psroi_pool", input, rois, pooled_height, pooled_width,
                   {"pooled_height": pooled_height,
                    "pooled_width": pooled_width,
                    "output_channels": output_channels,
                    "spatial_scale": spatial_scale},
                   out_channels=output_channels)


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    """ref layers.roi_perspective_transform (detection.py:1600): rois are
    quadrilaterals [R, 8] (or [R, 9] with batch index)."""
    helper = LayerHelper("roi_perspective_transform")
    R = int(rois.shape[0])
    C = int(input.shape[1])
    out = helper.create_variable_for_type_inference(
        input.dtype, (R, C, transformed_height, transformed_width))
    helper.append_op("roi_perspective_transform",
                     {"X": [input], "ROIs": [rois]}, {"Out": [out]},
                     {"transformed_height": transformed_height,
                      "transformed_width": transformed_width,
                      "spatial_scale": spatial_scale})
    return out


def polygon_box_transform(input, name=None):
    """ref layers.polygon_box_transform (EAST geometry decoding)."""
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, input.shape, True)
    helper.append_op("polygon_box_transform", {"Input": [input]},
                     {"Output": [out]}, {})
    return out


def yolov3_loss(x, gtbox, gtlabel, anchors, class_num, ignore_thresh,
                loss_weight_xy=None, loss_weight_wh=None,
                loss_weight_conf_target=None, loss_weight_conf_notarget=None,
                loss_weight_class=None, name=None, downsample_ratio=32):
    """ref layers.yolov3_loss (detection.py:408). gtbox [B, G, 4]
    center-form normalized; gtlabel [B, G]; pad rows have width 0."""
    helper = LayerHelper("yolov3_loss", name=name)
    B = int(x.shape[0])
    loss = helper.create_variable_for_type_inference("float32", (B,))
    helper.append_op("yolov3_loss",
                     {"X": [x], "GTBox": [gtbox], "GTLabel": [gtlabel]},
                     {"Loss": [loss]},
                     {"anchors": list(anchors), "class_num": class_num,
                      "ignore_thresh": ignore_thresh,
                      "downsample_ratio": downsample_ratio,
                      # ref yolov3_loss_op.h:387-392 scales each term
                      "loss_weight_xy": 1.0 if loss_weight_xy is None
                      else float(loss_weight_xy),
                      "loss_weight_wh": 1.0 if loss_weight_wh is None
                      else float(loss_weight_wh),
                      "loss_weight_conf_target":
                      1.0 if loss_weight_conf_target is None
                      else float(loss_weight_conf_target),
                      "loss_weight_conf_notarget":
                      1.0 if loss_weight_conf_notarget is None
                      else float(loss_weight_conf_notarget),
                      "loss_weight_class": 1.0 if loss_weight_class is None
                      else float(loss_weight_class)})
    return loss


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral"):
    """ref layers.detection_map (detection.py:515): VOC mAP over the
    fixed-size NMS output. label rows: (class, difficult, x1, y1, x2, y2),
    pad class < 0."""
    helper = LayerHelper("detection_map")
    out = helper.create_variable_for_type_inference("float32", (), True)
    helper.append_op("detection_map",
                     {"DetectRes": [detect_res], "Label": [label]},
                     {"MAP": [out]},
                     {"class_num": class_num,
                      "overlap_threshold": overlap_threshold,
                      "evaluate_difficult": evaluate_difficult,
                      "ap_version": ap_version})
    return out


def ssd_loss_stub(*a, **k):
    """Deprecated alias kept for earlier-round callers."""
    return ssd_loss(*a, **k)
