"""Tensor-creation layers.

Parity: python/paddle/fluid/layers/tensor.py (create_tensor, fill_constant,
concat, cast, assign, argmax/argsort live in nn here as in ref split).
"""
import numpy as np

from ..layer_helper import LayerHelper
from ..core.framework import default_main_program
from ..core.dtypes import convert_dtype

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "concat",
    "assign", "fill_constant", "fill_constant_batch_size_like",
    "ones", "zeros", "ones_like", "zeros_like", "reverse", "linspace",
    "range", "shape", "increment", "uniform_random", "gaussian_random",
    "sums",
    "autoincreased_step_counter", "get_tensor_from_selected_rows",
    "merge_selected_rows",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.block.create_var(name=name, dtype=dtype,
                                   persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..initializer import ConstantInitializer
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(shape, dtype, persistable=persistable,
                                        name=name)
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    xs = list(input)
    shape = list(xs[0].shape)
    ax = axis % len(shape)
    tot = 0
    for x in xs:
        if x.shape[ax] < 0:
            tot = -1
            break
        tot += x.shape[ax]
    shape[ax] = tot
    out = helper.create_variable_for_type_inference(xs[0].dtype, tuple(shape))
    helper.append_op("concat", {"X": xs}, {"Out": [out]}, {"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sums")
    xs = list(input)
    if out is None:
        out = helper.create_variable_for_type_inference(xs[0].dtype, xs[0].shape)
    helper.append_op("sum", {"X": xs}, {"Out": [out]}, {})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                str(input.dtype), input.shape)
        helper.append_op("assign_value", {}, {"Out": [output]},
                         {"shape": list(input.shape), "dtype": str(input.dtype),
                          "values": input.reshape(-1).tolist()})
        return output
    if output is None:
        output = helper.create_variable_for_type_inference(input.dtype,
                                                           input.shape)
    helper.append_op("assign", {"X": [input]}, {"Out": [output]}, {})
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    dtype = convert_dtype(dtype)
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype, tuple(shape), True)
    helper.append_op("fill_constant", {}, {"Out": [out]},
                     {"shape": [int(s) for s in shape], "dtype": dtype,
                      "value": float(value)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    shape2 = list(shape)
    shape2[output_dim_idx] = input.shape[input_dim_idx]
    out = helper.create_variable_for_type_inference(
        convert_dtype(dtype), tuple(shape2), True)
    helper.append_op("fill_constant_batch_size_like", {"Input": [input]},
                     {"Out": [out]},
                     {"shape": [int(s) for s in shape],
                      "dtype": convert_dtype(dtype), "value": float(value),
                      "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    return out


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype, x.shape, True)
    helper.append_op("fill_any_like", {"X": [x]}, {"Out": [out]},
                     {"value": 1.0})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype, x.shape, True)
    helper.append_op("fill_zeros_like", {"X": [x]}, {"Out": [out]}, {})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("reverse", {"X": [x]}, {"Out": [out]},
                     {"axis": [axis] if isinstance(axis, int) else list(axis)})
    return out


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace")
    out = helper.create_variable_for_type_inference(dtype, (num,), True)
    helper.append_op("linspace", {}, {"Out": [out]},
                     {"start": float(start), "stop": float(stop),
                      "num": int(num), "dtype": dtype})
    return out


def range(start, end, step, dtype="float32"):
    helper = LayerHelper("range")
    n = max(0, int(np.ceil((end - start) / step)))
    out = helper.create_variable_for_type_inference(dtype, (n,), True)
    helper.append_op("range", {}, {"Out": [out]},
                     {"start": start, "end": end, "step": step, "dtype": dtype})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference(
        "int32", (len(input.shape),), True)
    helper.append_op("shape", {"Input": [input]}, {"Out": [out]}, {})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("increment", {"X": [x]}, {"Out": [out]}, {"step": value})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape), True)
    helper.append_op("uniform_random", {}, {"Out": [out]},
                     {"shape": [int(s) for s in shape], "dtype": dtype,
                      "min": min, "max": max, "seed": seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape), True)
    helper.append_op("gaussian_random", {}, {"Out": [out]},
                     {"shape": [int(s) for s in shape], "dtype": dtype,
                      "mean": mean, "std": std, "seed": seed})
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """ref nn.py:5651 — persistable int64 counter incremented once per
    Executor.run (the increment op compiles into the step module)."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    block = helper.main_program.global_block()
    counter = block.vars.get(name)
    if counter is None:
        counter = create_global_var(
            [1], float(begin - step), "int64", persistable=True, name=name)
        helper.append_op("increment", {"X": [counter]}, {"Out": [counter]},
                         {"step": float(step), "is_train_only": True})
    return counter


def get_tensor_from_selected_rows(x, name=None):
    """ref get_tensor_from_selected_rows_op.cc. SelectedRows is the
    reference's sparse-gradient format; TPU gradients are dense arrays,
    so this is the identity (kept for API parity)."""
    helper = LayerHelper("get_tensor_from_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("assign", {"X": [x]}, {"Out": [out]}, {})
    return out


def merge_selected_rows(x, name=None):
    """ref merge_selected_rows_op.cc — duplicate-row reduction for sparse
    grads; dense on TPU, identity (see get_tensor_from_selected_rows)."""
    return get_tensor_from_selected_rows(x, name=name)
