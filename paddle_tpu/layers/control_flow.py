"""Control-flow layers.

Parity: python/paddle/fluid/layers/control_flow.py (While, Switch, cond,
array ops). The reference interprets sub-blocks op-by-op on the host;
here branches/bodies are captured as sub-Blocks and lowered to
lax.cond / lax.while_loop / lax.scan inside the SAME XLA module
(core/trace.py executes them functionally) — no host round-trips, which
is the only way control flow stays on-TPU.

API style follows the functional forms (cond(pred, true_fn, false_fn),
while_loop(cond_fn, body_fn, loop_vars)) — the reference's imperative
While/Switch blocks are host-interpreted and cannot compile to XLA.
"""
from ..layer_helper import LayerHelper
from ..core.framework import default_main_program

__all__ = ["cond", "while_loop", "case", "switch_case", "scan_layer",
           "array_write", "array_read", "create_array", "less_than",
           "less_equal", "greater_than", "greater_equal", "equal",
           "not_equal", "logical_and", "logical_or", "logical_not",
           "logical_xor"]


def _capture_block(fn, args):
    """Run fn (which appends ops) inside a fresh sub-block; return
    (block, outputs)."""
    program = default_main_program()
    blk = program.create_block()
    try:
        outs = fn(*args) if args else fn()
    finally:
        program.rollback()
    if outs is None:
        outs = []
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return blk, list(outs)


def cond(pred, true_fn, false_fn, name=None):
    """Functional conditional → lax.cond (both branches traced)."""
    helper = LayerHelper("cond", name=name)
    tb, touts = _capture_block(true_fn, ())
    fb, fouts = _capture_block(false_fn, ())
    if len(touts) != len(fouts):
        raise ValueError("cond branches must return same number of outputs")
    outs = [helper.create_variable_for_type_inference(t.dtype, t.shape)
            for t in touts]
    helper.append_op(
        "cond", {"Cond": [pred]}, {"Out": outs},
        {"true_block": tb.idx, "false_block": fb.idx,
         "true_outs": [t.name for t in touts],
         "false_outs": [f.name for f in fouts]})
    return outs[0] if len(outs) == 1 else outs


def case(pred_fn_pairs, default=None, name=None):
    """ref layers.case: chained conds."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default), name=name)
    if default is None:
        raise ValueError("case needs a default when preds may all be false")
    return cond(pred, fn, default, name=name)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """ref layers.switch_case → nested lax.cond chain."""
    from . import tensor as _t
    pairs = []
    items = branch_fns.items() if isinstance(branch_fns, dict) else enumerate(branch_fns)
    for i, fn in items:
        c = equal(branch_index, _t.fill_constant([1], branch_index.dtype, i))
        pairs.append((c, fn))
    return case(pairs, default, name=name)


def while_loop(cond_fn, body_fn, loop_vars, name=None):
    """Functional while → lax.while_loop. loop_vars: list of Variables;
    body must return same-shaped list."""
    helper = LayerHelper("while_loop", name=name)
    cb, couts = _capture_block(cond_fn, loop_vars)
    if len(couts) != 1:
        raise ValueError("while_loop cond must return one boolean scalar")
    bb, bouts = _capture_block(body_fn, loop_vars)
    if len(bouts) != len(loop_vars):
        raise ValueError("while_loop body must return one var per loop var")
    outs = [helper.create_variable_for_type_inference(v.dtype, v.shape)
            for v in loop_vars]
    helper.append_op(
        "while_loop", {"LoopVars": [v.name for v in loop_vars]},
        {"Out": outs},
        {"cond_block": cb.idx, "body_block": bb.idx,
         "cond_out": couts[0].name,
         "body_outs": [b.name for b in bouts],
         "carry_names": [v.name for v in loop_vars]})
    return outs


def scan_layer(body_fn, init, xs, name=None):
    """lax.scan exposure: body_fn(carry, x) -> (new_carry, y). xs is scanned
    over axis 0. TPU-native replacement for the reference's StaticRNN."""
    helper = LayerHelper("scan", name=name)
    carry_blk, carry_outs = _capture_block(lambda: body_fn(init, xs), ())
    if len(carry_outs) != 2:
        raise ValueError("scan body must return (carry, y)")
    new_c, y = carry_outs
    out_c = helper.create_variable_for_type_inference(new_c.dtype, new_c.shape)
    T = xs.shape[0]
    out_y = helper.create_variable_for_type_inference(
        y.dtype, (T,) + tuple(y.shape))
    helper.append_op(
        "scan", {"Init": [init], "Xs": [xs]},
        {"CarryOut": [out_c], "Ys": [out_y]},
        {"body_block": carry_blk.idx, "carry_out": new_c.name,
         "y_out": y.name, "init_name": init.name, "x_name": xs.name})
    return out_c, out_y


# --- tensor-array emulation (LoDTensorArray → stacked static array) -------
def create_array(dtype):
    raise NotImplementedError(
        "LoDTensorArray is host-side dynamic; use scan_layer / while_loop "
        "with fixed-size buffers on TPU (see SURVEY §6)")


def array_write(x, i, array=None):
    raise NotImplementedError("use scan_layer instead of array_write on TPU")


def array_read(array, i):
    raise NotImplementedError("use scan_layer instead of array_read on TPU")


# --- comparison layers (ref control_flow.py) -------------------------------
def _cmp_layer(op_type):
    def layer(x, y, cond=None):
        helper = LayerHelper(op_type)
        out = cond or helper.create_variable_for_type_inference(
            "bool", x.shape, True)
        helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [out]}, {})
        return out
    layer.__name__ = op_type
    return layer


less_than = _cmp_layer("less_than")
less_equal = _cmp_layer("less_equal")
greater_than = _cmp_layer("greater_than")
greater_equal = _cmp_layer("greater_equal")
equal = _cmp_layer("equal")
not_equal = _cmp_layer("not_equal")


def _logical_layer(op_type, binary=True):
    def layer(x, y=None, out=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if out is None:
            out = helper.create_variable_for_type_inference("bool", x.shape, True)
        ins = {"X": [x]}
        if binary:
            ins["Y"] = [y]
        helper.append_op(op_type, ins, {"Out": [out]}, {})
        return out
    layer.__name__ = op_type
    return layer


logical_and = _logical_layer("logical_and")
logical_or = _logical_layer("logical_or")
logical_xor = _logical_layer("logical_xor")
logical_not = _logical_layer("logical_not", binary=False)
