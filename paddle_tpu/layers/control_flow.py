"""Control-flow layers.

Parity: python/paddle/fluid/layers/control_flow.py (While, Switch, cond,
array ops). The reference interprets sub-blocks op-by-op on the host;
here branches/bodies are captured as sub-Blocks and lowered to
lax.cond / lax.while_loop / lax.scan inside the SAME XLA module
(core/trace.py executes them functionally) — no host round-trips, which
is the only way control flow stays on-TPU.

API style follows the functional forms (cond(pred, true_fn, false_fn),
while_loop(cond_fn, body_fn, loop_vars)) — the reference's imperative
While/Switch blocks are host-interpreted and cannot compile to XLA.
"""
from ..layer_helper import LayerHelper
from ..core.framework import default_main_program

from .. import unique_name

__all__ = ["cond", "while_loop", "case", "switch_case", "scan_layer",
           "array_write", "array_read", "create_array", "array_length",
           "tensor_array_to_tensor", "less_than",
           "less_equal", "greater_than", "greater_equal", "equal",
           "not_equal", "logical_and", "logical_or", "logical_not",
           "logical_xor", "While", "Switch", "IfElse", "StaticRNN",
           "DynamicRNN", "Print", "is_empty", "py_func",
           "reorder_lod_tensor_by_rank"]


def _capture_block(fn, args):
    """Run fn (which appends ops) inside a fresh sub-block; return
    (block, outputs)."""
    program = default_main_program()
    blk = program.create_block()
    try:
        outs = fn(*args) if args else fn()
    finally:
        program.rollback()
    if outs is None:
        outs = []
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return blk, list(outs)


def cond(pred, true_fn, false_fn, name=None):
    """Functional conditional → lax.cond (both branches traced)."""
    helper = LayerHelper("cond", name=name)
    tb, touts = _capture_block(true_fn, ())
    fb, fouts = _capture_block(false_fn, ())
    if len(touts) != len(fouts):
        raise ValueError("cond branches must return same number of outputs")
    outs = [helper.create_variable_for_type_inference(t.dtype, t.shape)
            for t in touts]
    helper.append_op(
        "cond", {"Cond": [pred]}, {"Out": outs},
        {"true_block": tb.idx, "false_block": fb.idx,
         "true_outs": [t.name for t in touts],
         "false_outs": [f.name for f in fouts]})
    return outs[0] if len(outs) == 1 else outs


def case(pred_fn_pairs, default=None, name=None):
    """ref layers.case: chained conds."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default), name=name)
    if default is None:
        raise ValueError("case needs a default when preds may all be false")
    return cond(pred, fn, default, name=name)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """ref layers.switch_case → nested lax.cond chain."""
    from . import tensor as _t
    pairs = []
    items = branch_fns.items() if isinstance(branch_fns, dict) else enumerate(branch_fns)
    for i, fn in items:
        c = equal(branch_index, _t.fill_constant([1], branch_index.dtype, i))
        pairs.append((c, fn))
    return case(pairs, default, name=name)


def while_loop(cond_fn, body_fn, loop_vars, name=None):
    """Functional while → lax.while_loop. loop_vars: list of Variables;
    body must return same-shaped list."""
    helper = LayerHelper("while_loop", name=name)
    cb, couts = _capture_block(cond_fn, loop_vars)
    if len(couts) != 1:
        raise ValueError("while_loop cond must return one boolean scalar")
    bb, bouts = _capture_block(body_fn, loop_vars)
    if len(bouts) != len(loop_vars):
        raise ValueError("while_loop body must return one var per loop var")
    outs = [helper.create_variable_for_type_inference(v.dtype, v.shape)
            for v in loop_vars]
    helper.append_op(
        "while_loop", {"LoopVars": [v.name for v in loop_vars]},
        {"Out": outs},
        {"cond_block": cb.idx, "body_block": bb.idx,
         "cond_out": couts[0].name,
         "body_outs": [b.name for b in bouts],
         "carry_names": [v.name for v in loop_vars]})
    return outs


def scan_layer(body_fn, init, xs, name=None):
    """lax.scan exposure: body_fn(carry, x) -> (new_carry, y). xs is scanned
    over axis 0. TPU-native replacement for the reference's StaticRNN."""
    helper = LayerHelper("scan", name=name)
    carry_blk, carry_outs = _capture_block(lambda: body_fn(init, xs), ())
    if len(carry_outs) != 2:
        raise ValueError("scan body must return (carry, y)")
    new_c, y = carry_outs
    out_c = helper.create_variable_for_type_inference(new_c.dtype, new_c.shape)
    T = xs.shape[0]
    out_y = helper.create_variable_for_type_inference(
        y.dtype, (T,) + tuple(y.shape))
    helper.append_op(
        "scan", {"Init": [init], "Xs": [xs]},
        {"CarryOut": [out_c], "Ys": [out_y]},
        {"body_block": carry_blk.idx, "carry_out": new_c.name,
         "y_out": y.name, "init_name": init.name, "x_name": xs.name})
    return out_c, out_y


# --- tensor arrays (ref LoDTensorArray + tensor_array_read_write ops) ------
# The reference's LoDTensorArray is a host-side growable vector of tensors.
# On TPU an array is a fixed-capacity device buffer [capacity, *elem] plus an
# int32 length scalar, so it can ride a lax.while_loop carry (static shapes).
# Pass element_shape to create_array when the array is used inside While.

def _alloc_array(helper, dtype, element_shape, capacity):
    arr = helper.create_variable_for_type_inference(
        dtype, (capacity,) + tuple(element_shape), True)
    ln = helper.create_variable_for_type_inference("int32", (), True)
    helper.append_op("alloc_array", {}, {"Array": [arr], "Len": [ln]},
                     {"element_shape": [int(s) for s in element_shape],
                      "capacity": int(capacity), "dtype": dtype})
    arr._array_len_var = ln
    return arr


def create_array(dtype, element_shape=None, capacity=64, name=None):
    helper = LayerHelper("array", name=name)
    if element_shape is not None:
        return _alloc_array(helper, dtype, element_shape, capacity)
    arr = helper.create_variable_for_type_inference(dtype, (), True)
    arr._array_lazy = {"dtype": dtype, "capacity": capacity}
    return arr


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    lazy = getattr(array, "_array_lazy", None)
    if lazy is not None:
        # allocate now that the element shape is known; keep the SAME
        # variable so earlier references stay valid
        real = _alloc_array(helper, lazy["dtype"], tuple(x.shape),
                            lazy["capacity"])
        # rebind: the freshly allocated buffer writes into array's name
        real_op = helper.block.ops[-1]
        real_op.outputs["Array"] = [array.name]
        array.shape = real.shape
        array._array_len_var = real._array_len_var
        del array._array_lazy
    ln = array._array_len_var
    helper.append_op("array_write",
                     {"X": [x], "I": [i], "Array": [array], "Len": [ln]},
                     {"ArrayOut": [array], "LenOut": [ln]}, {})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(
        array.dtype, tuple(array.shape[1:]), True)
    helper.append_op("array_read",
                     {"Array": [array], "I": [i],
                      "Len": [array._array_len_var]},
                     {"Out": [out]}, {})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int32", (), True)
    helper.append_op("assign", {"X": [array._array_len_var]},
                     {"Out": [out]}, {})
    return out


def tensor_array_to_tensor(input, axis=1, use_stack=False, name=None):
    """ref layers.tensor_array_to_tensor: concat/stack the array.
    Returns (tensor, length) — length is the number of valid entries
    (the tensor itself covers the full capacity; slice by length)."""
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    cap = int(input.shape[0])
    elem = tuple(input.shape[1:])
    if use_stack:
        shape = elem[:axis] + (cap,) + elem[axis:]
    else:
        shape = tuple(s * cap if d == axis else s
                      for d, s in enumerate(elem))
    out = helper.create_variable_for_type_inference(input.dtype, shape, True)
    idx = helper.create_variable_for_type_inference("int32", (), True)
    helper.append_op("tensor_array_to_tensor",
                     {"Array": [input], "Len": [input._array_len_var]},
                     {"Out": [out], "OutIndex": [idx]},
                     {"axis": axis, "use_stack": use_stack})
    return out, idx


# --- imperative control-flow classes ---------------------------------------
def _outer_written_names(program, sub):
    """Names written by ops in `sub` that are visible in an ancestor block —
    these become the loop/branch carry (fluid writes them in place)."""
    seen = []
    for op in sub.ops:
        for n in op.output_names():
            if n in seen:
                continue
            idx = sub.parent_idx
            while idx >= 0:
                b = program.blocks[idx]
                if n in b.vars:
                    seen.append(n)
                    break
                idx = b.parent_idx
    return seen


class While:
    """ref layers.While — imperative while block.

    The reference interprets the sub-block on the host each iteration
    (control_flow.py:While + while_op.cc); here the block is captured and
    lowered to ONE lax.while_loop whose carry is every outer variable the
    block writes (fluid's in-place writes, made functional). The condition
    variable must be updated inside the block (e.g. layers.less_than(...,
    cond=cond)) exactly as in the reference.
    """

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.program = default_main_program()
        self._sub = None

    def block(self):
        w = self

        class _Guard:
            def __enter__(g):
                w._sub = w.program.create_block()
                return w._sub

            def __exit__(g, et, ev, tb):
                w.program.rollback()
                if et is None:
                    w._complete()
                return False

        return _Guard()

    def _complete(self):
        sub = self._sub
        prog = self.program
        parent = prog.current_block()
        written = _outer_written_names(prog, sub)
        cond_name = self.cond_var.name
        carry = [cond_name] + [n for n in written if n != cond_name]
        # empty condition block: the carried cond value IS the predicate
        cond_blk = prog.create_block()
        prog.rollback()
        parent.append_op(
            "while_loop", {"LoopVars": list(carry)},
            {"Out": list(carry)},
            {"cond_block": cond_blk.idx, "body_block": sub.idx,
             "cond_out": cond_name, "body_outs": list(carry),
             "carry_names": list(carry)})


class Switch:
    """ref layers.Switch — first matching case wins (used by LR schedules).
    Lowered to a chain of lax.cond ops over the union of variables the
    case blocks write."""

    def __init__(self, name=None):
        self.program = default_main_program()
        self.cases = []
        self.default_block = None

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        if et is None:
            self._complete()
        return False

    def _capture(self, store):
        sw = self

        class _G:
            def __enter__(g):
                g.blk = sw.program.create_block()
                return g.blk

            def __exit__(g, et, ev, tb):
                sw.program.rollback()
                if et is None:
                    store(g.blk)
                return False

        return _G()

    def case(self, condition):
        cond_var = condition
        return self._capture(lambda blk: self.cases.append((cond_var, blk)))

    def default(self):
        def store(blk):
            self.default_block = blk
        return self._capture(store)

    def _complete(self):
        if not self.cases:
            raise ValueError("Switch needs at least one case")
        prog = self.program
        parent = prog.current_block()
        blocks = [b for _, b in self.cases]
        if self.default_block is not None:
            blocks.append(self.default_block)
        written = []
        for b in blocks:
            for n in _outer_written_names(prog, b):
                if n not in written:
                    written.append(n)
        if not written:
            return
        out_vars = list(written)
        if self.default_block is not None:
            next_idx = self.default_block.idx
        else:
            empty = prog.create_block()
            prog.rollback()
            next_idx = empty.idx
        # build the chain innermost-first; each wrapper block holds one cond
        for cond_var, case_blk in reversed(self.cases[1:]):
            w = prog.create_block()
            prog.rollback()
            w.append_op("cond", {"Cond": [cond_var]}, {"Out": out_vars},
                        {"true_block": case_blk.idx, "false_block": next_idx,
                         "true_outs": list(written),
                         "false_outs": list(written)})
            next_idx = w.idx
        cond_var, case_blk = self.cases[0]
        parent.append_op("cond", {"Cond": [cond_var]}, {"Out": out_vars},
                        {"true_block": case_blk.idx, "false_block": next_idx,
                         "true_outs": list(written),
                         "false_outs": list(written)})


class IfElse:
    """ref layers.IfElse — per-ROW conditional over a [N, 1] bool mask.

    The reference physically splits the batch by mask, runs each branch on
    its subset, and merges (conditional_block + split/merge_lod_tensor
    ops). On TPU both branches run on the FULL batch (static shapes; XLA
    fuses them) and outputs merge row-wise by the mask — numerically
    identical for row-independent branches, which is what the op requires
    anyway (rows can't see each other across the split).
    """

    def __init__(self, cond, name=None):
        self.cond = cond
        self.helper = LayerHelper("ifelse", name=name)
        self._outs = {True: [], False: []}
        self._branch = None

    def _guard(self, flag):
        ie = self

        class _G:
            def __enter__(g):
                ie._branch = flag
                return ie

            def __exit__(g, et, ev, tb):
                ie._branch = None
                return False

        return _G()

    def true_block(self):
        return self._guard(True)

    def false_block(self):
        return self._guard(False)

    def input(self, x):
        if self._branch is None:
            raise RuntimeError("IfElse.input outside a branch block")
        return x

    def output(self, *outs):
        if self._branch is None:
            raise RuntimeError("IfElse.output outside a branch block")
        self._outs[self._branch].extend(outs)

    def __call__(self):
        t, f = self._outs[True], self._outs[False]
        if len(t) != len(f):
            raise ValueError("IfElse branches produced different numbers "
                             f"of outputs ({len(t)} vs {len(f)})")
        res = []
        for tv, fv in zip(t, f):
            out = self.helper.create_variable_for_type_inference(
                tv.dtype, tv.shape, True)
            self.helper.append_op(
                "mask_merge", {"Mask": [self.cond], "X": [tv], "Y": [fv]},
                {"Out": [out]}, {})
            res.append(out)
        return res


class StaticRNN:
    """ref layers.StaticRNN — step over axis 0 of [T, B, ...] inputs.

    The reference unrolls the step block T times into the ProgramDesc
    (recurrent_op.cc); here the block is captured ONCE and lowered to
    lax.scan — compile time independent of T, and XLA pipelines the steps.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.program = default_main_program()
        self.seq_len = None
        self._x_map = []     # (outer_name, step_name)
        self._mem = []       # [init_name, prev_step_name, new_name|None]
        self._y_map = []     # (step_y_name, out_var)
        self._block = None
        self._in_step = False
        self._outputs = []

    def step(self):
        rnn = self

        class _G:
            def __enter__(g):
                rnn._block = rnn.program.create_block()
                rnn._in_step = True
                return rnn

            def __exit__(g, et, ev, tb):
                rnn._in_step = False
                rnn.program.rollback()
                if et is None:
                    rnn._complete()
                return False

        return _G()

    def _require_step(self):
        if not self._in_step:
            raise RuntimeError("call inside `with rnn.step():`")

    def step_input(self, x):
        self._require_step()
        T = int(x.shape[0])
        if self.seq_len is None:
            self.seq_len = T
        elif self.seq_len != T:
            raise ValueError(f"step inputs disagree on T: {self.seq_len} vs {T}")
        sv = self._block.create_var(
            name=unique_name.generate("rnn_step_in"),
            shape=tuple(x.shape[1:]), dtype=x.dtype, stop_gradient=False)
        self._x_map.append((x.name, sv.name))
        return sv

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._require_step()
        parent = self.program.blocks[self._block.parent_idx]
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory() needs init= or (shape=, batch_ref=)")
            resolved = [int(batch_ref.shape[0]) if int(s) < 0 else int(s)
                        for s in shape]
            init_var = parent.create_var(
                name=unique_name.generate("rnn_mem_init"),
                shape=tuple(resolved), dtype=batch_ref.dtype,
                stop_gradient=True)
            parent.append_op("fill_constant", {}, {"Out": [init_var]},
                             {"shape": resolved, "dtype": str(init_var.dtype),
                              "value": float(init_value)})
            init = init_var
        prev = self._block.create_var(
            name=unique_name.generate("rnn_mem_prev"),
            shape=tuple(init.shape), dtype=init.dtype, stop_gradient=False)
        self._mem.append([init.name, prev.name, None])
        return prev

    def update_memory(self, mem, x):
        self._require_step()
        for rec in self._mem:
            if rec[1] == mem.name:
                rec[2] = x.name
                return
        raise ValueError(f"{mem.name} is not a memory of this RNN")

    def step_output(self, o):
        self._require_step()
        parent = self.program.blocks[self._block.parent_idx]
        out = parent.create_var(
            name=unique_name.generate("rnn_out"),
            shape=(self.seq_len,) + tuple(o.shape), dtype=o.dtype,
            stop_gradient=False)
        self._y_map.append((o.name, out))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        if not self._x_map and self.seq_len is None:
            raise ValueError("StaticRNN needs at least one step_input")
        for rec in self._mem:
            if rec[2] is None:
                raise ValueError("memory never updated; call update_memory")
        parent = self.program.current_block()
        out_vars = [v for _, v in self._y_map]
        parent.append_op(
            "static_rnn",
            {"Xs": [o for o, _ in self._x_map],
             "MemInits": [i for i, _, _ in self._mem]},
            {"Ys": out_vars},
            {"step_block": self._block.idx,
             "x_map": [list(p) for p in self._x_map],
             "mem_map": [list(r) for r in self._mem],
             "y_map": [[s, v.name] for s, v in self._y_map]})
        self._outputs = out_vars

    def __call__(self):
        if len(self._outputs) == 1:
            return self._outputs[0]
        return self._outputs


class DynamicRNN(StaticRNN):
    """ref layers.DynamicRNN — variable-length sequences.

    The reference shrinks the effective batch as short sequences finish
    (lod_rank_table + shrink_memory, host-side). With padded [B, T, ...]
    arrays the TPU version scans the full T and MASKS memory updates past
    each row's length, which computes the same final states/outputs on
    static shapes. Pass the per-row lengths as `seq_len` ([B] int vector,
    the LoD substitute); padded output steps are zeroed.
    """

    def __init__(self, seq_len=None, name=None):
        super().__init__(name=name)
        self._lengths = seq_len
        self._t_step = None
        self._mask = None

    def block(self):
        return self.step()

    def _time_mask(self):
        """[B] bool mask: t < seq_len, built lazily inside the step block."""
        if self._mask is not None or self._lengths is None:
            return self._mask
        parent = self.program.blocks[self._block.parent_idx]
        tidx = parent.create_var(
            name=unique_name.generate("drnn_t"), shape=(self.seq_len,),
            dtype="int32", stop_gradient=True)
        parent.append_op("range", {}, {"Out": [tidx]},
                         {"start": 0, "end": int(self.seq_len), "step": 1,
                          "dtype": "int32"})
        t_step = self._block.create_var(
            name=unique_name.generate("drnn_t_step"), shape=(),
            dtype="int32", stop_gradient=True)
        self._x_map.append((tidx.name, t_step.name))
        mask = self._block.create_var(
            name=unique_name.generate("drnn_mask"),
            shape=(int(self._lengths.shape[0]),), dtype="bool",
            stop_gradient=True)
        self._block.append_op("less_than",
                              {"X": [t_step], "Y": [self._lengths]},
                              {"Out": [mask]}, {})
        self._mask = mask
        return mask

    def step_input(self, x, level=0):
        # x is batch-major [B, T, ...] in the padded world → scan over T
        self._require_step()
        B, T = int(x.shape[0]), int(x.shape[1])
        parent = self.program.blocks[self._block.parent_idx]
        xt = parent.create_var(
            name=unique_name.generate("drnn_in_tmajor"),
            shape=(T, B) + tuple(x.shape[2:]), dtype=x.dtype,
            stop_gradient=False)
        perm = [1, 0] + list(range(2, len(x.shape)))
        parent.append_op("transpose", {"X": [x]}, {"Out": [xt]},
                         {"axis": perm})
        return super().step_input(xt)

    def static_input(self, x):
        return x

    def update_memory(self, mem, x):
        self._require_step()
        mask = self._time_mask()
        if mask is None:
            return super().update_memory(mem, x)
        merged = self._block.create_var(
            name=unique_name.generate("drnn_mem_upd"),
            shape=tuple(x.shape), dtype=x.dtype, stop_gradient=False)
        self._block.append_op("mask_merge",
                              {"Mask": [mask], "X": [x], "Y": [mem]},
                              {"Out": [merged]}, {})
        return super().update_memory(mem, merged)

    def step_output(self, o):
        self._require_step()
        mask = self._time_mask()
        if mask is not None:
            zeros = self._block.create_var(
                name=unique_name.generate("drnn_zeros"),
                shape=tuple(o.shape), dtype=o.dtype, stop_gradient=True)
            self._block.append_op("fill_zeros_like", {"X": [o]},
                                  {"Out": [zeros]}, {})
            masked = self._block.create_var(
                name=unique_name.generate("drnn_y_masked"),
                shape=tuple(o.shape), dtype=o.dtype, stop_gradient=False)
            self._block.append_op("mask_merge",
                                  {"Mask": [mask], "X": [o], "Y": [zeros]},
                                  {"Out": [masked]}, {})
            o = masked
        super().step_output(o)

    def _complete(self):
        super()._complete()
        # transpose outputs back to batch-major [B, T, ...]
        parent = self.program.current_block()
        bm = []
        for _, tv in self._y_map:
            shape = tuple(tv.shape)
            out = parent.create_var(
                name=unique_name.generate("drnn_out"),
                shape=(shape[1], shape[0]) + shape[2:], dtype=tv.dtype,
                stop_gradient=False)
            perm = [1, 0] + list(range(2, len(shape)))
            parent.append_op("transpose", {"X": [tv]}, {"Out": [out]},
                             {"axis": perm})
            bm.append(out)
        self._outputs = bm


# --- misc (Print / is_empty / py_func / reorder) ---------------------------
def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """ref layers.Print → jax.debug.print inside the compiled module."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape,
                                                    input.stop_gradient)
    msg = message or ""
    if print_tensor_name:
        msg = f"{msg} {input.name}".strip()
    helper.append_op("print", {"X": [input]}, {"Out": [out]},
                     {"message": msg, "summarize": summarize,
                      "print_tensor_type": print_tensor_type,
                      "print_tensor_shape": print_tensor_shape,
                      "print_tensor_value": True})
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    out = cond or helper.create_variable_for_type_inference("bool", (), True)
    helper.append_op("is_empty", {"X": [x]}, {"Out": [out]}, {})
    return out


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None, name=None):
    """ref layers.py_func — host-python escape hatch.

    The reference re-enters the Python interpreter from the C++ executor
    (py_func_op.cc); here the callable runs via jax.pure_callback so it
    composes with jit (XLA inserts the host round-trip). backward_func,
    if given, becomes a custom VJP the same way.
    """
    from ..ops.kernels_control import register_py_func
    helper = LayerHelper("py_func", name=name)
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    attrs = {"func_id": register_py_func(func),
             "out_shapes": [list(int(s) for s in o.shape) for o in outs],
             "out_dtypes": [str(o.dtype) for o in outs],
             "backward_func_id": (register_py_func(backward_func)
                                  if backward_func else -1)}
    helper.append_op("py_func", {"X": xs}, {"Out": outs}, attrs)
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """ref reorder_lod_tensor_by_rank: sort batch rows by descending
    sequence length. `rank_table` is the [B] length vector (the
    lod_rank_table analog in the padded world)."""
    helper = LayerHelper("reorder_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape,
                                                    x.stop_gradient)
    order = helper.create_variable_for_type_inference(
        "int32", (x.shape[0],), True)
    helper.append_op("reorder_by_rank",
                     {"X": [x], "RankTable": [rank_table]},
                     {"Out": [out], "Order": [order]}, {})
    return out


# --- comparison layers (ref control_flow.py) -------------------------------
def _cmp_layer(op_type):
    def layer(x, y, cond=None):
        helper = LayerHelper(op_type)
        out = cond or helper.create_variable_for_type_inference(
            "bool", x.shape, True)
        helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [out]}, {})
        return out
    layer.__name__ = op_type
    return layer


less_than = _cmp_layer("less_than")
less_equal = _cmp_layer("less_equal")
greater_than = _cmp_layer("greater_than")
greater_equal = _cmp_layer("greater_equal")
equal = _cmp_layer("equal")
not_equal = _cmp_layer("not_equal")


def _logical_layer(op_type, binary=True):
    def layer(x, y=None, out=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if out is None:
            out = helper.create_variable_for_type_inference("bool", x.shape, True)
        ins = {"X": [x]}
        if binary:
            ins["Y"] = [y]
        helper.append_op(op_type, ins, {"Out": [out]}, {})
        return out
    layer.__name__ = op_type
    return layer


logical_and = _logical_layer("logical_and")
logical_or = _logical_layer("logical_or")
logical_xor = _logical_layer("logical_xor")
logical_not = _logical_layer("logical_not", binary=False)
