"""Layer utility helpers.

Parity: python/paddle/fluid/layers/utils.py — convert_to_list
normalizes int-or-sequence arguments (kernel sizes, strides, paddings)
exactly like the reference's conv/pool layers expect.
"""
import numpy as np

__all__ = ["convert_to_list"]


def convert_to_list(value, n, name, dtype=int):
    """int -> [value]*n; sequence -> validated list of length n.

    Strict like the reference: floats/strings/bools are rejected, not
    coerced — a typo'd conv stride must raise, not silently change the
    geometry."""
    if isinstance(value, bool):
        raise ValueError(f"{name} must be an int, got bool {value!r}")
    if isinstance(value, (int, np.integer)):
        return [dtype(value)] * n
    try:
        value_list = list(value)
    except TypeError:
        raise ValueError(
            f"{name} must be an int or an iterable of {n} ints; "
            f"got {value!r}")
    if len(value_list) != n:
        raise ValueError(
            f"{name} must have {n} elements; got {len(value_list)}")
    for v in value_list:
        if isinstance(v, bool) or not isinstance(v, (int, np.integer)):
            raise ValueError(
                f"{name} elements must be ints; got {v!r}")
    return [dtype(v) for v in value_list]
