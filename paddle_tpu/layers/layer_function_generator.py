"""Layer-function generation helpers.

Parity: python/paddle/fluid/layers/layer_function_generator.py — doc
decorators (autodoc/templatedoc/deprecated) and generate_layer_fn, which
builds a layer function straight from a registered op type (the
reference generates them from the C++ OpProto; here the kernel registry
is the source of truth and the generated layer uses the common
X→Out slot convention).
"""
import re

from ..layer_helper import LayerHelper
from ..ops.registry import has_kernel

__all__ = ["autodoc", "templatedoc", "deprecated", "generate_layer_fn",
           "generate_layer_fn_noattr"]


def autodoc(comment=""):
    def deco(func):
        func.__doc__ = comment + (func.__doc__ or "")
        return func
    return deco


def templatedoc(op_type=None):
    """Fill {comment}-style placeholders in the docstring (the reference
    pulls text from the OpProto; the placeholders are simply stripped
    when no proto text exists)."""
    def deco(func):
        if func.__doc__:
            func.__doc__ = re.sub(r"\$\{[\w.]+\}", "", func.__doc__)
        return func
    return deco


def deprecated(since="", instead="", extra_message=""):
    # single implementation lives in annotations.py (the reference's home
    # for it); this name is kept because layers code imports it from here
    from ..annotations import deprecated as _deprecated
    return _deprecated(since, instead, extra_message)


def generate_layer_fn(op_type):
    """Build `layer(x, ..., **attrs) -> out` for a registered op that
    follows the X→Out slot convention (activations, unary math...)."""
    if not has_kernel(op_type):
        raise ValueError(f"unknown op type {op_type!r}")

    def layer(*args, **kwargs):
        helper = LayerHelper(op_type, name=kwargs.pop("name", None))
        if len(args) != 1:
            raise ValueError(
                f"{op_type} generated layer takes exactly one input "
                f"variable (X→Out convention), got {len(args)}")
        x = args[0]
        out = kwargs.pop("out", None) or \
            helper.create_variable_for_type_inference(x.dtype, x.shape)
        helper.append_op(op_type, {"X": [x]}, {"Out": [out]}, dict(kwargs))
        return out

    layer.__name__ = op_type
    layer.__doc__ = f"Auto-generated layer for the {op_type!r} op."
    return layer


def generate_layer_fn_noattr(op_type):
    fn = generate_layer_fn(op_type)

    def layer(x, name=None):
        return fn(x, name=name)

    layer.__name__ = op_type
    return layer
