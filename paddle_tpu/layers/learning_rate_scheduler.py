"""Learning-rate schedulers.

Parity: python/paddle/fluid/layers/learning_rate_scheduler.py — each
scheduler appends ops that compute this step's LR from a persistable
global step counter (@LR_DECAY_COUNTER@) which is incremented in-graph,
exactly like the reference; the whole schedule compiles into the train
step's XLA module.
"""
import math

from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer
from . import tensor
from . import nn
from . import control_flow

__all__ = [
    "exponential_decay", "natural_exp_decay", "inverse_time_decay",
    "polynomial_decay", "piecewise_decay", "noam_decay", "cosine_decay",
    "linear_lr_warmup", "append_LARS"]

LR_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _global_step_counter():
    """Find-or-create the persistable step counter, incremented per step."""
    helper = LayerHelper("lr_counter")
    block = helper.main_program.global_block()
    if block.has_var(LR_COUNTER_NAME):
        return block.var(LR_COUNTER_NAME)
    counter = helper.create_global_variable(
        [1], "float32", persistable=True, name=LR_COUNTER_NAME)
    helper.set_variable_initializer(counter, ConstantInitializer(0.0))
    helper.block.prepend_op("increment", {"X": [counter]},
                            {"Out": [counter]},
                            {"step": 1.0, "is_train_only": True})
    return counter


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step_counter()
    div = nn.scale(step, 1.0 / decay_steps)
    if staircase:
        from . import ops
        div = ops.floor(div)
    return nn.scale(nn.elementwise_pow(
        tensor.fill_constant([1], "float32", decay_rate), div), learning_rate)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from . import ops
    step = _global_step_counter()
    div = nn.scale(step, 1.0 / decay_steps)
    if staircase:
        div = ops.floor(div)
    return nn.scale(ops.exp(nn.scale(div, -decay_rate)), learning_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from . import ops
    step = _global_step_counter()
    div = nn.scale(step, 1.0 / decay_steps)
    if staircase:
        div = ops.floor(div)
    denom = nn.scale(div, decay_rate, bias=1.0)
    return nn.elementwise_div(
        tensor.fill_constant([1], "float32", learning_rate), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from . import ops
    step = _global_step_counter()
    if cycle:
        ratio = nn.scale(step, 1.0 / decay_steps)
        ceil_r = ops.ceil(nn.elementwise_max(
            ratio, tensor.fill_constant([1], "float32", 1.0)))
        decay_var = nn.scale(ceil_r, float(decay_steps))
    else:
        decay_var = tensor.fill_constant([1], "float32", float(decay_steps))
        step = nn.elementwise_min(step, decay_var)
    frac = nn.elementwise_sub(
        tensor.fill_constant([1], "float32", 1.0),
        nn.elementwise_div(step, decay_var))
    return nn.scale(nn.elementwise_pow(
        frac, tensor.fill_constant([1], "float32", power)),
        learning_rate - end_learning_rate, bias=end_learning_rate)


def piecewise_decay(boundaries, values):
    """Step-function LR via nested where ops (no host control flow)."""
    if len(values) != len(boundaries) + 1:
        raise ValueError("len(values) must be len(boundaries)+1")
    step = _global_step_counter()
    lr = tensor.fill_constant([1], "float32", values[-1])
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        is_before = control_flow.less_than(
            step, tensor.fill_constant([1], "float32", float(b)))
        lr = nn.where(is_before, tensor.fill_constant([1], "float32", v), lr)
    return lr


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr = lr0 * d_model^-0.5 * min(step^-0.5, step*warmup^-1.5)
    (ref learning_rate_scheduler.py:noam_decay; Transformer schedule)."""
    step = _global_step_counter()
    a = nn.elementwise_pow(step, tensor.fill_constant([1], "float32", -0.5))
    b = nn.scale(step, warmup_steps ** -1.5)
    return nn.scale(nn.elementwise_min(a, b),
                    learning_rate * (d_model ** -0.5))


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from . import ops
    step = _global_step_counter()
    epoch = ops.floor(nn.scale(step, 1.0 / step_each_epoch))
    decay = nn.scale(
        ops.cos(nn.scale(epoch, math.pi / epochs)), 0.5, bias=0.5)
    return nn.scale(decay, learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _global_step_counter()
    in_warmup = control_flow.less_than(
        step, tensor.fill_constant([1], "float32", float(warmup_steps)))
    warm = nn.scale(step, (end_lr - start_lr) / warmup_steps, bias=start_lr)
    if hasattr(learning_rate, "name"):
        base = learning_rate
    else:
        base = tensor.fill_constant([1], "float32", learning_rate)
    return nn.where(in_warmup, warm, base)


def append_LARS(params_grads, learning_rate, weight_decay):
    """ref learning_rate_scheduler.py:310 — layer-wise adaptive rate
    scaling: lr_i = lr * ||w|| / (||g|| + wd * ||w||) per parameter.
    Returns the list of per-parameter decayed LR variables."""
    from . import nn as _nn
    from . import ops as _ops
    out = []
    for param, grad in params_grads:
        pn = _ops.sqrt(_nn.reduce_sum(_ops.square(param)))
        gn = _ops.sqrt(_nn.reduce_sum(_ops.square(grad)))
        # wd == 1.0 matches the reference's _balanced_weight special case:
        # denom = ||g|| + ||w|| (identical to the generic formula at 1.0)
        denom = gn + weight_decay * pn
        out.append(learning_rate * pn / denom)
    return out
