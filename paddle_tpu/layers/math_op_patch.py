"""Operator overloading for Variables.

Parity: python/paddle/fluid/layers/math_op_patch.py — patches __add__ etc.
onto Variable so `a + b`, `a * 2`, `a < b` append elementwise ops.
"""
from ..core.framework import Variable
from ..layer_helper import LayerHelper

_patched = False


def _scalar_to_var(value, ref):
    from . import tensor
    shape = [1]
    return tensor.fill_constant(shape, ref.dtype, float(value))


def _binary(op_type, reverse=False):
    def impl(self, other):
        from . import nn
        if isinstance(other, (int, float)):
            if op_type == "elementwise_add":
                return nn.scale(self, 1.0, bias=float(other))
            if op_type == "elementwise_sub" and not reverse:
                return nn.scale(self, 1.0, bias=-float(other))
            if op_type == "elementwise_mul":
                return nn.scale(self, float(other))
            other = _scalar_to_var(other, self)
        x, y = (other, self) if reverse else (self, other)
        helper = LayerHelper(op_type)
        out = helper.create_variable_for_type_inference(
            x.dtype, x.shape if len(x.shape) >= len(y.shape) else y.shape)
        helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [out]},
                         {"axis": -1})
        return out
    return impl


def _cmp(op_type):
    def impl(self, other):
        if isinstance(other, (int, float)):
            other = _scalar_to_var(other, self)
        helper = LayerHelper(op_type)
        out = helper.create_variable_for_type_inference("bool", self.shape, True)
        helper.append_op(op_type, {"X": [self], "Y": [other]}, {"Out": [out]}, {})
        return out
    return impl


def monkey_patch_variable():
    global _patched
    if _patched:
        return
    _patched = True
    Variable.__add__ = _binary("elementwise_add")
    Variable.__radd__ = _binary("elementwise_add")
    Variable.__sub__ = _binary("elementwise_sub")
    Variable.__rsub__ = _binary("elementwise_sub", reverse=True)
    Variable.__mul__ = _binary("elementwise_mul")
    Variable.__rmul__ = _binary("elementwise_mul")
    Variable.__truediv__ = _binary("elementwise_div")
    Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
    Variable.__pow__ = _binary("elementwise_pow")
    Variable.__mod__ = _binary("elementwise_mod")
    Variable.__neg__ = lambda self: __import__(
        "paddle_tpu.layers.nn", fromlist=["scale"]).scale(self, -1.0)
    Variable.__lt__ = _cmp("less_than")
    Variable.__le__ = _cmp("less_equal")
    Variable.__gt__ = _cmp("greater_than")
    Variable.__ge__ = _cmp("greater_equal")
