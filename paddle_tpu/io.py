"""Model save/load + inference model export.

Parity: python/paddle/fluid/io.py — save_vars/save_params/
save_persistables, save_inference_model/load_inference_model, plus
incremental train checkpoints (program desc as JSON + params as .npz;
layout is orbax-style dir with a manifest).
"""
import json
import os
import numpy as np

from .core.framework import Program, Parameter
from .core.scope import global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save_checkpoint", "load_checkpoint",
]

PARAMS_FILE = "params.npz"
DESC_FILE = "__model__.json"
META_FILE = "checkpoint.json"


def _collect(program, predicate, scope):
    out = {}
    for v in program.persistable_vars():
        if predicate(v):
            val = scope.get(v.name)
            if val is not None:
                out[v.name] = np.asarray(val)
    return out


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from .core.framework import default_main_program
    program = main_program or default_main_program()
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if vars is not None:
        arrays = {v.name if hasattr(v, "name") else v:
                  np.asarray(scope.get(v.name if hasattr(v, "name") else v))
                  for v in vars}
    else:
        arrays = _collect(program, predicate or (lambda v: True), scope)
    np.savez(os.path.join(dirname, filename or PARAMS_FILE), **arrays)
    return sorted(arrays)


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: isinstance(v, Parameter),
                     filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: v.persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    scope = global_scope()
    path = os.path.join(dirname, filename or PARAMS_FILE)
    with np.load(path, allow_pickle=False) as data:
        names = set(data.files)
        if vars is not None:
            wanted = {v.name if hasattr(v, "name") else v for v in vars}
        else:
            wanted = names
        for name in names & wanted:
            scope.set(name, np.asarray(data[name]))
    return sorted(names & wanted)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def _prune_for_inference(program, feed_names, fetch_names):
    """Keep only ops needed to compute fetch_names from feed_names
    (ref io.py:prune + inference transpiler)."""
    test_prog = program.clone(for_test=True)
    block = test_prog.global_block()
    needed = set(fetch_names)
    kept = []
    for op in reversed(block.ops):
        if set(op.output_names()) & needed:
            kept.append(op)
            needed |= set(op.input_names())
    block.ops = list(reversed(kept))
    test_prog._bump_version()
    return test_prog


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    """ref io.py:save_inference_model — pruned program desc + params."""
    from .core.framework import default_main_program
    program = main_program or default_main_program()
    fetch_names = [v.name if hasattr(v, "name") else v for v in target_vars]
    pruned = _prune_for_inference(program, feeded_var_names, fetch_names)
    os.makedirs(dirname, exist_ok=True)
    desc = pruned.to_desc()
    desc["feed_names"] = list(feeded_var_names)
    desc["fetch_names"] = fetch_names
    with open(os.path.join(dirname, model_filename or DESC_FILE), "w") as f:
        json.dump(desc, f)
    # all persistables, not just Parameters: batch-norm moving stats, AUC
    # histograms etc. are inputs of the pruned program too
    save_persistables(executor, dirname, program, filename=params_filename)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """Returns (program, feed_names, fetch_vars) like the reference."""
    with open(os.path.join(dirname, model_filename or DESC_FILE)) as f:
        desc = json.load(f)
    program = Program.from_desc(desc)
    program._is_test = True
    load_params(executor, dirname, program, filename=params_filename)
    block = program.global_block()
    fetch_vars = [block.var(n) for n in desc["fetch_names"]]
    return program, desc["feed_names"], fetch_vars


# ---------------------------------------------------------------------------
# train checkpoints (resume training: params + opt state + counters)
# ---------------------------------------------------------------------------
def save_checkpoint(executor, dirname, main_program=None, step=0,
                    extra=None):
    names = save_persistables(executor, dirname, main_program)
    meta = {"step": int(step), "vars": names, "extra": extra or {}}
    with open(os.path.join(dirname, META_FILE), "w") as f:
        json.dump(meta, f)
    return meta


def load_checkpoint(executor, dirname, main_program=None):
    load_persistables(executor, dirname, main_program)
    with open(os.path.join(dirname, META_FILE)) as f:
        return json.load(f)
