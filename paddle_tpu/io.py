"""Model save/load + inference model export.

Parity: python/paddle/fluid/io.py — save_vars/save_params/
save_persistables, save_inference_model/load_inference_model, plus
incremental train checkpoints (program desc as JSON + params as .npz;
layout is a directory with an npz payload + JSON manifest).
"""
import glob
import json
import os
import numpy as np

from .core.framework import Program, Parameter
from .core.scope import global_scope
from .resilience import checkpoint as _rckpt
from .resilience.checkpoint import CheckpointError

__all__ = ["CheckpointSaver", "latest_checkpoint", "CheckpointError",
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save_checkpoint", "load_checkpoint",
    "save_sharded_checkpoint", "load_sharded_checkpoint",
]

PARAMS_FILE = "params.npz"
DESC_FILE = "__model__.json"
META_FILE = "checkpoint.json"


def _collect(program, predicate, scope):
    out = {}
    for v in program.persistable_vars():
        if predicate(v):
            val = scope.get(v.name)
            if val is not None:
                out[v.name] = np.asarray(val)
    return out


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from .core.framework import default_main_program
    program = main_program or default_main_program()
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if vars is not None:
        arrays = {v.name if hasattr(v, "name") else v:
                  np.asarray(scope.get(v.name if hasattr(v, "name") else v))
                  for v in vars}
    else:
        arrays = _collect(program, predicate or (lambda v: True), scope)
    np.savez(os.path.join(dirname, filename or PARAMS_FILE), **arrays)
    return sorted(arrays)


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: isinstance(v, Parameter),
                     filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: v.persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    scope = global_scope()
    path = os.path.join(dirname, filename or PARAMS_FILE)
    with np.load(path, allow_pickle=False) as data:
        names = set(data.files)
        if vars is not None:
            wanted = {v.name if hasattr(v, "name") else v for v in vars}
        else:
            wanted = names
        for name in names & wanted:
            scope.set(name, np.asarray(data[name]))
    return sorted(names & wanted)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def _prune_for_inference(program, feed_names, fetch_names):
    """Keep only ops needed to compute fetch_names from feed_names
    (ref io.py:prune + inference transpiler)."""
    test_prog = program.clone(for_test=True)
    block = test_prog.global_block()
    needed = set(fetch_names)
    kept = []
    for op in reversed(block.ops):
        if set(op.output_names()) & needed:
            kept.append(op)
            needed |= set(op.input_names())
    block.ops = list(reversed(kept))
    test_prog._bump_version()
    return test_prog


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_format="json"):
    """ref io.py:save_inference_model — pruned program desc + params.

    program_format: "json" (native desc + params.npz) or "fluid"
    (the reference's binary ProgramDesc `__model__` + LoDTensor-stream
    parameter files, loadable by real Fluid — core/fluid_proto.py)."""
    from .core.framework import default_main_program
    program = main_program or default_main_program()
    fetch_names = [v.name if hasattr(v, "name") else v for v in target_vars]
    pruned = _prune_for_inference(program, feeded_var_names, fetch_names)
    os.makedirs(dirname, exist_ok=True)
    if program_format == "fluid":
        from .core import fluid_proto
        blob = fluid_proto.program_to_fluid(
            pruned, feed_names=list(feeded_var_names),
            fetch_names=fetch_names)
        with open(os.path.join(dirname, model_filename or "__model__"),
                  "wb") as f:
            f.write(blob)
        scope = global_scope()
        arrays = _collect(pruned, lambda v: v.persistable, scope)
        # every persistable var in the emitted desc must have a value:
        # the load side walks ALL of them, and a silent gap would shift
        # every later tensor in a combined stream
        missing = [v.name for v in pruned.persistable_vars()
                   if v.name not in arrays]
        if missing:
            raise RuntimeError(
                "fluid export: persistable vars have no value in the "
                f"scope (run the startup program first?): {missing}")
        # combined-file order must equal the load side's walk of the
        # program's persistable vars (load_combine_op semantics) — and
        # that walk is SORTED BY NAME on both sides, the reference's
        # save_vars/load_vars convention (io.py sorts the var list
        # before save_combine). Declaration order is builder-dependent,
        # so a combined file exchanged with real Fluid would otherwise
        # bind tensors to the wrong variables.
        order = sorted(v.name for v in pruned.persistable_vars()
                       if v.name in arrays)
        fluid_proto.save_fluid_params(dirname, arrays,
                                      filename=params_filename,
                                      order=order)
        return fetch_names
    desc = pruned.to_desc()
    desc["feed_names"] = list(feeded_var_names)
    desc["fetch_names"] = fetch_names
    with open(os.path.join(dirname, model_filename or DESC_FILE), "w") as f:
        json.dump(desc, f)
    # all persistables, not just Parameters: batch-norm moving stats, AUC
    # histograms etc. are inputs of the pruned program too
    save_persistables(executor, dirname, program, filename=params_filename)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """Returns (program, feed_names, fetch_vars) like the reference.

    Accepts BOTH model layouts: the native JSON desc + params.npz, and
    a directory saved by real Fluid (binary protobuf `__model__` +
    LoDTensor-stream param files / combined file — core/fluid_proto.py),
    auto-detected from what's on disk."""
    path = os.path.join(dirname, model_filename or DESC_FILE)
    if model_filename is None and not os.path.exists(path) \
            and os.path.exists(os.path.join(dirname, "__model__")):
        path = os.path.join(dirname, "__model__")
    with open(path, "rb") as f:
        raw = f.read()
    # NO lstrip: a ProgramDesc blob starts with tag 0x0A, which
    # bytes.lstrip() would eat as whitespace; json.dump output starts
    # with '{' at byte 0
    if raw[:1] != b"{":
        return _load_fluid_inference_model(dirname, raw, params_filename)
    desc = json.loads(raw.decode("utf-8"))
    program = Program.from_desc(desc)
    program._is_test = True
    load_params(executor, dirname, program, filename=params_filename)
    block = program.global_block()
    fetch_vars = [block.var(n) for n in desc["fetch_names"]]
    return program, desc["feed_names"], fetch_vars


def _load_fluid_inference_model(dirname, blob, params_filename):
    """Load a reference-format (binary ProgramDesc) model directory."""
    from .core import fluid_proto
    program, feed_names, fetch_names = fluid_proto.program_from_fluid(blob)
    program._is_test = True
    # load_combine order = persistable vars SORTED BY NAME (the
    # reference's save_vars/load_vars convention — must mirror
    # save_inference_model's fluid export exactly, or a combined
    # stream binds tensors to the wrong variables)
    names = sorted(v.name for v in program.persistable_vars())
    arrays = fluid_proto.load_fluid_params(dirname, names,
                                           filename=params_filename)
    scope = global_scope()
    for name, arr in arrays.items():
        scope.set(name, arr)
    block = program.global_block()
    fetch_vars = [block.var(n) for n in fetch_names]
    return program, feed_names, fetch_vars


# ---------------------------------------------------------------------------
# train checkpoints (resume training: params + opt state + counters)
# ---------------------------------------------------------------------------
def _elastic_snapshot(executor, scope):
    """(layout, shard_files) for a topology-independent save: with a
    sparse engine attached (ParallelExecutor sparse=...), every
    mod-sharded row var is snapshotted one shard file per mesh member
    (each host copies only its addressable 1/N — never the gathered
    [V, D]) and described by a logical `layout` record, so a
    checkpoint written at world N restores at ANY world M through the
    elastic streaming shuffle. Plain executors (no engine) return
    empty — the checkpoint format is byte-identical to the pre-elastic
    one then (bench-contract pin)."""
    engine = getattr(executor, "sparse_engine", None)
    if engine is None:
        return {}, {}
    layout, files = engine.export_shards(scope)
    # npz/npy have no bfloat16: shards take the same uint16 disk view
    # as sharded checkpoints; layout records the true dtype
    return layout, {fn: _np_to_disk(a)[0] for fn, a in files.items()}


def _checkpoint_meta(arrays, layout, engine_world, step, extra):
    """The checkpoint meta/manifest record. `world_size` and `layout`
    are ADDITIVE (pre-elastic readers ignore them; a manifest without
    them still loads): world_size is the shard world of the layout
    files — 1 when everything is logical."""
    meta = {"step": int(step), "vars": sorted(arrays),
            "extra": extra or {},
            "world_size": int(engine_world) if layout else 1}
    if layout:
        meta["layout"] = layout
    return meta


def save_checkpoint(executor, dirname, main_program=None, step=0,
                    extra=None):
    """Crash-safe checkpoint: params + meta + checksum manifest are
    written to a temp sibling, fsync'd per file, and published into
    `dirname` by one atomic rename — a crash at any byte leaves either
    the previous checkpoint or the new one, never a torn mix (the
    pre-manifest writer saved in place: a crash mid-savez left a
    checkpoint.json pointing at an unreadable npz that load_checkpoint
    would happily open). Topology-independent: dense persistables are
    saved in their logical layout, and an attached sparse engine's
    mod-sharded tables go one shard file per member with a `layout`
    manifest record, so the checkpoint restores at any world size."""
    from .core.framework import default_main_program
    program = main_program or default_main_program()
    scope = global_scope()
    layout, shard_files = _elastic_snapshot(executor, scope)
    arrays = _collect(program, lambda v: v.name not in layout, scope)
    meta = _checkpoint_meta(
        arrays, layout,
        getattr(getattr(executor, "sparse_engine", None), "n", 1),
        step, extra)
    parent = os.path.dirname(os.path.abspath(dirname)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = dirname + f".tmp.{os.getpid()}"
    if os.path.isdir(tmp):
        import shutil
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    _rckpt.write_payload(tmp, arrays, meta, PARAMS_FILE, META_FILE,
                         extra_files=shard_files)
    _rckpt.atomic_publish(tmp, dirname)
    return meta


def _recovery_candidates(dirname):
    """Crash leftovers that may hold a complete checkpoint when
    `dirname` itself is torn/missing: the .old swap-out from
    atomic_publish (crash between its two renames) and fully-written
    .tmp.<pid> dirs (crash after payload, before publish)."""
    return [dirname + ".old"] + sorted(
        glob.glob(glob.escape(dirname) + ".tmp.*"), reverse=True)


def load_checkpoint(executor, dirname, main_program=None):
    """Load a checkpoint dir — or, for a CheckpointSaver root holding
    rotated checkpoint_N subdirs, the newest VALID one. Torn or
    corrupt candidates (checksum-manifest verified) are skipped; a
    flat dir that fails validation falls back to the writer's crash
    leftovers before raising CheckpointError."""
    latest = latest_checkpoint(dirname)
    if latest is not None:
        dirname = latest
    else:
        ok, reason = _rckpt.validate(dirname)
        if not ok:
            for cand in _recovery_candidates(dirname):
                if _rckpt.is_valid(cand):
                    dirname = cand
                    break
            else:
                raise CheckpointError(
                    f"checkpoint {dirname!r} failed validation "
                    f"({reason}) and no valid recovery candidate "
                    "exists")
    load_persistables(executor, dirname, main_program)
    with open(os.path.join(dirname, META_FILE)) as f:
        meta = json.load(f)
    layout = meta.get("layout")
    if layout:
        # topology-independent tables: re-shard r%N -> r%M into the
        # executor's engine placement (or assemble logically for a
        # plain executor) — resilience/elastic.py, imported only when
        # a checkpoint actually carries a layout (off-path pin)
        from .resilience import elastic as _elastic
        _elastic.restore_layout(executor, dirname, layout,
                                global_scope())
    return meta


def _list_checkpoints(root):
    """[(step, name)] for every checkpoint_N subdir, sorted by step —
    the ONE parser shared by latest_checkpoint and CheckpointSaver."""
    out = []
    if os.path.isdir(root):
        for name in os.listdir(root):
            if name.startswith("checkpoint_"):
                suffix = name[len("checkpoint_"):]
                if suffix.isdigit():
                    out.append((int(suffix), name))
    return sorted(out)


def latest_checkpoint(root):
    """Newest VALID checkpoint_N subdir of a CheckpointSaver root
    (torn/corrupt candidates are skipped, newest-first), or None if
    `root` is itself a flat checkpoint dir or holds no valid
    checkpoint."""
    if os.path.exists(os.path.join(root, META_FILE)):
        return None
    for _, name in reversed(_list_checkpoints(root)):
        path = os.path.join(root, name)
        if _rckpt.is_valid(path):
            return path
    return None


class CheckpointSaver:
    """Async, atomic, rotating checkpoints (orbax-style semantics).

    save() snapshots the persistables to HOST memory on the calling
    thread (a device->host DMA — the training loop can immediately keep
    mutating/donating device buffers), then serializes + fsyncs + renames
    on a background thread so checkpoint IO overlaps the next steps.
    Writes go to a hidden tmp dir and are os.replace()d into
    `root/checkpoint_<step>` — a crash mid-write never corrupts a
    visible checkpoint. Keeps the newest `max_to_keep`.

    The reference era blocks training for the whole save
    (io.py:save_persistables); this removes the serialization from the
    step critical path.
    """

    def __init__(self, root, max_to_keep=3, async_save=True):
        self.root = root
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self._thread = None
        self._error = None
        os.makedirs(root, exist_ok=True)
        self._clean_orphans()

    def _clean_orphans(self):
        """Recover from a crashed writer: drop torn .tmp_checkpoint_*
        dirs, and resolve checkpoint_N.old swap leftovers — if the
        crash landed between atomic_publish's two renames, the .old IS
        the checkpoint and gets its real name back."""
        import shutil
        for name in os.listdir(self.root):
            path = os.path.join(self.root, name)
            if name.startswith(".tmp_checkpoint_"):
                shutil.rmtree(path, ignore_errors=True)
            elif name.startswith("checkpoint_") and name.endswith(".old"):
                final = path[:-len(".old")]
                if _rckpt.is_valid(final):
                    shutil.rmtree(path, ignore_errors=True)
                elif _rckpt.is_valid(path) and not os.path.exists(final):
                    os.rename(path, final)

    def save(self, executor, main_program=None, step=0, extra=None):
        from .core.framework import default_main_program
        program = main_program or default_main_program()
        scope = global_scope()
        # topology-independent snapshot of any engine-sharded tables
        # (one host copy per addressable shard) — taken NOW for the
        # same donation reason as the dense arrays below
        layout, shard_files = _elastic_snapshot(executor, scope)
        # device -> host snapshot NOW, with an explicit COPY: np.asarray
        # can alias a CPU jax.Array (or a numpy value already in scope),
        # and the executor donates the persist dict — an aliased buffer
        # would be rewritten by the next step while the writer runs
        arrays = {v.name: np.array(scope.get(v.name), copy=True)
                  for v in program.persistable_vars()
                  if scope.get(v.name) is not None
                  and v.name not in layout}
        meta = _checkpoint_meta(
            arrays, layout,
            getattr(getattr(executor, "sparse_engine", None), "n", 1),
            step, extra)
        self.wait()                      # one in-flight save at a time
        if self.async_save:
            import threading
            self._thread = threading.Thread(
                target=self._write, args=(arrays, meta, step,
                                          shard_files), daemon=True)
            self._thread.start()
        else:
            self._write(arrays, meta, step, shard_files)
            if self._error is not None:   # sync mode: fail loudly NOW
                err, self._error = self._error, None
                raise RuntimeError(f"checkpoint write failed: {err}")
        return meta

    def _write(self, arrays, meta, step, shard_files=None):
        try:
            tmp = os.path.join(self.root, f".tmp_checkpoint_{step}")
            final = os.path.join(self.root, f"checkpoint_{step}")
            if os.path.isdir(tmp):
                import shutil
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            # payload + checksum manifest, fsync'd per file; the
            # checkpoint.write chaos point can tear the npz here —
            # exactly like a writer killed mid-write, the torn state
            # stays in tmp and never becomes visible
            _rckpt.write_payload(tmp, arrays, meta, PARAMS_FILE,
                                 META_FILE, extra_files=shard_files)
            # publish atomically and make the rename durable before
            # pruning — a crash here must leave SOME valid checkpoint
            _rckpt.atomic_publish(tmp, final)
            self._prune()
        except Exception as e:            # surfaced on next wait()/save()
            self._error = e

    def _prune(self):
        """Rotate down to max_to_keep — but NEVER delete the newest
        valid checkpoint, even when everything newer than it is torn:
        rotation GC must not be the thing that destroys the last
        restore point."""
        import shutil
        entries = _list_checkpoints(self.root)
        if len(entries) <= self.max_to_keep:
            return
        newest_valid = None
        for _, name in reversed(entries):
            if _rckpt.is_valid(os.path.join(self.root, name)):
                newest_valid = name
                break
        keep = {name for _, name in entries[-self.max_to_keep:]}
        if newest_valid is not None:
            keep.add(newest_valid)
        for _, name in entries:
            if name not in keep:
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"async checkpoint write failed: {err}")


# ---------------------------------------------------------------------------
# Sharded checkpoints (multi-host-scale state: per-shard files, no
# full-array gather)
# ---------------------------------------------------------------------------
def _np_to_disk(a):
    """npz has no bfloat16: view 2-byte non-numeric dtypes as uint16
    and record the true dtype (mirrors inference.save_compiled)."""
    a = np.asarray(a)
    dtype = str(a.dtype)
    if a.dtype.kind not in "biufc":
        a = a.view(np.uint16 if a.dtype.itemsize == 2 else np.uint8)
    return a, dtype


def _np_from_disk(a, dtype):
    import jax.numpy as jnp
    if str(a.dtype) != dtype:
        a = a.view(jnp.dtype(dtype))
    return a


def save_sharded_checkpoint(dirname, persist, step=0, extra=None,
                            publish=True):
    """Write jax.Arrays shard-by-shard: each host saves only ITS
    addressable shards (`.addressable_shards` — a device->host copy of
    1/N of the state, never a full-array gather), plus a manifest with
    the global shape/dtype and the mesh/PartitionSpec layout. At pod
    scale this is what makes checkpointing feasible: the gather-based
    save_persistables would pull the full model through every host.

    `persist` is {name: jax.Array} (e.g. a ParallelExecutor scope's
    values). Replicated-over-some-axes arrays dedupe shards by index.

    Publishing (the tmp -> dirname rename on host 0) happens only after
    a cross-host barrier when process_count() > 1, so no host can still
    be writing its shards into tmp when the rename lands (the reference
    sequences this through the pserver checkpoint RPC instead —
    paddle/fluid/operators/checkpoint_notify_op.cc). Pass publish=False
    to keep the shards in `dirname + ".tmp"` and control the rename
    yourself (returns the manifest either way).
    """
    import jax

    tmp = dirname + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    manifest = {"step": int(step), "extra": extra or {}, "vars": {}}
    pid = jax.process_index()
    for name, arr in persist.items():
        if not isinstance(arr, jax.Array):
            arr = jax.numpy.asarray(arr)
        sh = arr.sharding
        spec = list(getattr(sh, "spec", ())) if hasattr(sh, "spec") else []
        mesh = getattr(sh, "mesh", None)
        entry = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "spec": [list(s) if isinstance(s, tuple) else s
                     for s in spec],
            "mesh_axes": list(mesh.axis_names) if mesh is not None else [],
            "mesh_shape": [int(mesh.shape[a]) for a in mesh.axis_names]
            if mesh is not None else [],
            "shards": [],
        }
        seen = set()
        fname_base = name.replace("/", "__")
        for i, shard in enumerate(arr.addressable_shards):
            # normalize slice(None) (unsharded dims) to explicit bounds
            # so save and load key shards identically
            key = tuple(
                (s.start if s.start is not None else 0,
                 s.stop if s.stop is not None else arr.shape[d])
                for d, s in enumerate(shard.index))
            if key in seen:
                continue  # replicated copy of an already-saved shard
            seen.add(key)
            data, true_dtype = _np_to_disk(shard.data)
            fn = f"{fname_base}.p{pid}.s{i}.npy"
            np.save(os.path.join(tmp, fn), data)
            entry["shards"].append({
                "file": fn,
                "index": [list(k) for k in key],
                "disk_dtype": str(data.dtype),
            })
        entry["true_dtype"] = true_dtype
        manifest["vars"][name] = entry
    with open(os.path.join(tmp, f"manifest.p{pid}.json"), "w") as f:
        json.dump(manifest, f)
    if not publish:
        return manifest
    if jax.process_count() > 1:
        # every host must finish writing into tmp before host 0 renames
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(
            f"save_sharded_checkpoint:{dirname}")
    if pid == 0:
        if os.path.exists(dirname):
            import shutil
            shutil.rmtree(dirname)
        os.replace(tmp, dirname)
    if jax.process_count() > 1:
        # second barrier: no host may return (and e.g. immediately
        # load_sharded_checkpoint) until the rename has landed
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(
            f"save_sharded_checkpoint:published:{dirname}")
    return manifest


def load_sharded_checkpoint(dirname, mesh=None):
    """Restore {name: jax.Array} with the ORIGINAL shardings: each
    device loads only the shard file covering its index
    (jax.make_array_from_single_device_arrays — no host ever holds a
    full copy of a sharded array). `mesh` must provide the axis names
    recorded in the manifest (defaults to reconstructing one from the
    local devices in manifest order)."""
    import glob as _glob

    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    manifests = sorted(_glob.glob(os.path.join(dirname, "manifest.p*.json")))
    if not manifests:
        raise IOError(f"no sharded checkpoint manifests in {dirname}")
    merged = None
    for mpath in manifests:
        with open(mpath) as f:
            m = json.load(f)
        if merged is None:
            merged = m
        else:
            for n, e in m["vars"].items():
                merged["vars"].setdefault(n, e)["shards"].extend(
                    s for s in e["shards"]
                    if s not in merged["vars"][n]["shards"])
    out = {}
    for name, e in merged["vars"].items():
        shape = tuple(e["shape"])
        if e["mesh_axes"]:
            if mesh is None or list(mesh.axis_names) != e["mesh_axes"]:
                devs = np.array(jax.devices()[:int(np.prod(
                    e["mesh_shape"]))]).reshape(e["mesh_shape"])
                mesh_v = Mesh(devs, tuple(e["mesh_axes"]))
            else:
                mesh_v = mesh
            spec = P(*[tuple(s) if isinstance(s, list) else s
                       for s in e["spec"]])
            sh = NamedSharding(mesh_v, spec)
        else:
            sh = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        by_index = {}
        for srec in e["shards"]:
            data = np.load(os.path.join(dirname, srec["file"]))
            data = _np_from_disk(data, e["true_dtype"])
            key = tuple(tuple(ix) for ix in srec["index"])
            by_index[key] = data
        dev_map = sh.addressable_devices_indices_map(shape)
        singles = []
        for dev, index in dev_map.items():
            key = tuple((s.start if s.start is not None else 0,
                         s.stop if s.stop is not None else shape[d])
                        for d, s in enumerate(index))
            if key not in by_index:
                raise IOError(
                    f"{name}: no shard file for index {key} "
                    f"(checkpoint saved with a different layout?)")
            singles.append(jax.device_put(by_index[key], dev))
        out[name] = jax.make_array_from_single_device_arrays(
            shape, sh, singles)
    return out, {"step": merged["step"], "extra": merged["extra"]}
