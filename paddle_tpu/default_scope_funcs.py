"""Default scope function stack.

Parity: python/paddle/fluid/default_scope_funcs.py — a thread-local
stack of Scopes with enter/leave + scoped_function.
"""
import threading

from .core.scope import Scope, global_scope

__all__ = ["get_cur_scope", "enter_local_scope", "leave_local_scope",
           "var", "find_var", "scoped_function"]

_local = threading.local()


def _stack():
    if not hasattr(_local, "stack"):
        _local.stack = [global_scope()]
    return _local.stack


def get_cur_scope():
    return _stack()[-1]


def enter_local_scope():
    child = get_cur_scope().new_scope()
    _stack().append(child)
    return child


def leave_local_scope():
    stack = _stack()
    if len(stack) > 1:
        stack.pop()


def var(name):
    """Get or create a variable slot in the current scope."""
    sc = get_cur_scope()
    if sc.get(name) is None:
        sc.set(name, None)
    return sc.get(name)


def find_var(name):
    return get_cur_scope().get(name)


def scoped_function(func):
    """Run func inside a fresh local scope (ref scoped_function)."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
