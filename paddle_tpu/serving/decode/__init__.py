"""paddle_tpu.serving.decode — tpudecode: continuous-batching
autoregressive decode with a static-shape KV-cache slot pool and
multi-tenant QoS.

PR 3's DynamicBatcher coalesces fixed-shape one-shot requests; real
traffic is autoregressive, and the legacy path (`greedy_decode`)
re-runs the whole [B, T] inference program once per token — O(T^2)
compute, O(T*V) logits readback per step, and a request that finishes
early rides the batch to the end. This package is the iteration-level
fix, kept inside the repo's static-shapes discipline:

- `DecodeEngine` (engine.py): compiled executables around
  `models.transformer.IncrementalDecoder` — one bucketed prefill per
  row bucket + exactly ONE single-token step function over a
  `[num_slots, T_max, heads, dim]` KV-cache with in-graph
  argmax/top-k sampling. Only [num_slots] token ids cross the host
  boundary per step.
- `SlotPool` (slots.py): host bookkeeping for the static decode
  batch; join/leave is scatter/gather over pre-allocated rows, with a
  leak-check invariant the chaos tests drive across injected crashes.
- `ContinuousScheduler` (scheduler.py): per-iteration
  retire-on-eos-or-deadline / admit-into-free-slots / one compiled
  step, with bounded-queue admission control and a supervised,
  crash-respawning loop thread (`worker_crash` chaos point).
- `QosPolicy` (qos.py): weighted-fair-queuing admission classes with
  optional fair-share preemption (`PreemptedError` -> HTTP 429,
  distinct from deadline's 504), per-tenant `serving.decode.*`
  telemetry flowing into tpustat.

The package is imported lazily by the rest of serving/ (bench-contract
pins that decode-off paths never pull it in); `ModelServer.attach_
decoder` and the HTTP frontend's `max_new_tokens` field opt a model
into the tier. CLI: `tools/tpuserve.py --bench-decode /
--selftest-decode`.
"""
from .engine import DecodeEngine, DecodeEngineConfig
from .qos import QosPolicy, TenantClass
from .scheduler import (ContinuousScheduler, DecodeConfig,
                        DecodeRequest, DecodeResult)
from .slots import Slot, SlotPool
from ..batcher import PreemptedError

__all__ = ["DecodeEngine", "DecodeEngineConfig", "QosPolicy",
           "TenantClass", "ContinuousScheduler", "DecodeConfig",
           "DecodeRequest", "DecodeResult", "Slot", "SlotPool",
           "PreemptedError"]
