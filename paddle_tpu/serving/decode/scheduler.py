"""Continuous (iteration-level) batching scheduler for tpudecode.

The reference served autoregressive models the Paddle Serving way: one
request = one predictor run, batch membership frozen at admission, a
request that finishes early rides the batch until the longest member
is done. This scheduler replaces that with the iteration-level model:
every decode **step** is a scheduling opportunity —

    retire   slots whose request hit eos / its token budget / its
             deadline (the row is free *this* iteration, not at batch
             end);
    admit    queued requests into the freed rows, picked by weighted
             fair queuing (`qos.QosPolicy`), prefilled through the
             bucketed encoder executables;
    step     ONE compiled step function over all `num_slots` rows;
             only [num_slots] token ids cross the host boundary.

Admission control mirrors PR 3's batcher: bounded queue (fast
`RejectedError` on overload), per-request deadlines (`DeadlineExceeded`
— HTTP 504), plus QoS preemption (`PreemptedError` — HTTP 429).

The loop thread is supervised the same way ModelServer workers are:
a crash (including the injected `worker_crash` chaos fault at the
``serving.worker`` point) fails the in-flight requests, returns every
slot to the pool — leak-free, pinned by tests — and respawns.

Tests can skip the thread entirely: construct, `submit`, and call
`run_iteration()` by hand for a fully deterministic drive.
"""
import logging
import threading
import time

import numpy as np

from ... import telemetry as _tm
from ...resilience import chaos as _chaos
from ..batcher import (CancelledError, DeadlineExceeded, Future,
                       PreemptedError, RejectedError, ServerClosed)
from .qos import QosPolicy
from .slots import SlotPool

_LOG = logging.getLogger("paddle_tpu.serving.decode")

__all__ = ["DecodeConfig", "DecodeRequest", "DecodeResult",
           "ContinuousScheduler"]


class DecodeConfig:
    def __init__(self, max_queue_requests=256, default_deadline_ms=None,
                 default_max_new_tokens=None, bos=0, eos=None,
                 idle_wait_s=0.05):
        self.max_queue_requests = int(max_queue_requests)
        self.default_deadline_ms = default_deadline_ms
        self.default_max_new_tokens = default_max_new_tokens
        self.bos = int(bos)
        self.eos = eos if eos is None else int(eos)
        self.idle_wait_s = float(idle_wait_s)


class DecodeRequest:
    __slots__ = ("src", "src_len", "tenant", "max_new_tokens",
                 "deadline", "enqueue_t", "future", "request_id",
                 "cancelled", "poisoned")

    def __init__(self, src, src_len, tenant, max_new_tokens, deadline,
                 request_id=None, poisoned=False):
        self.src = src
        self.src_len = src_len
        self.tenant = tenant
        self.max_new_tokens = max_new_tokens
        self.deadline = deadline           # monotonic seconds or None
        self.enqueue_t = time.monotonic()
        self.future = Future(deadline)
        self.request_id = request_id
        # set by cancel(): the iteration loop retires the slot (it is
        # the slot pool's single writer; cancel never frees directly)
        self.cancelled = False
        # set by the request_poison chaos fault: stepping this request
        # crashes its replica (rides resubmissions by design)
        self.poisoned = poisoned

    def expired(self, now):
        return self.deadline is not None and now >= self.deadline


class DecodeResult:
    """What a decode future resolves to."""

    __slots__ = ("tokens", "finish_reason", "tenant", "ttft_s",
                 "decode_s")

    def __init__(self, tokens, finish_reason, tenant, ttft_s, decode_s):
        self.tokens = tokens                # np.int32 [n_generated]
        self.finish_reason = finish_reason  # "eos" | "length"
        self.tenant = tenant
        self.ttft_s = ttft_s
        self.decode_s = decode_s

    def __repr__(self):
        return (f"DecodeResult({len(self.tokens)} tokens, "
                f"{self.finish_reason!r}, tenant={self.tenant!r})")


class ContinuousScheduler:
    """Continuous-batching decode over one `DecodeEngine`."""

    def __init__(self, engine, qos=None, config=None, name="decoder",
                 warmup=True):
        self.engine = engine
        self.qos = qos or QosPolicy()
        self.config = config or DecodeConfig()
        self.name = name
        self.pool = SlotPool(engine.num_slots)
        self.state = engine.init_state()
        # host mirrors of the per-slot decode cursor; free slots hold 0
        self._ids = np.zeros(engine.num_slots, np.int64)
        self._pos = np.zeros(engine.num_slots, np.int64)
        self._queues = {}            # tenant -> list of DecodeRequest
        self._queued = 0
        self._cond = threading.Condition()
        self._closed = False
        self._thread = None
        self._iteration = 0
        self._started = False
        self.restarts = 0
        self.preemptions = 0
        self.tokens_generated = 0    # lifetime tokens (goodput gauges)
        # set by serving.farm: this scheduler's replica index, carried
        # into the chaos ctx (worker_crash replica=R targeting) and
        # the serving.replica.<i>.* telemetry
        self.replica_index = None
        if warmup:
            engine.warmup()

    # ------------------------------------------------------ caller side
    def submit(self, src, src_len=None, tenant="default",
               max_new_tokens=None, deadline_ms=None,
               request_id=None, poison=False):
        """Enqueue one sequence; returns a Future resolving to a
        `DecodeResult`. Sheds immediately on a full queue or an
        oversized source (RejectedError) — overload never builds an
        unbounded backlog."""
        src = np.asarray(src, np.int64).reshape(-1)
        if src_len is None:
            src_len = len(src)
        src_len = int(src_len)
        if len(src) > self.engine.src_max_len:
            raise RejectedError(
                f"source of {len(src)} tokens exceeds the decode "
                f"tier's src_max_len {self.engine.src_max_len}")
        cap = self.engine.max_new_tokens
        if max_new_tokens is None:
            max_new_tokens = self.config.default_max_new_tokens or cap
        max_new_tokens = max(1, min(int(max_new_tokens), cap))
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        tenant = str(tenant)
        self.qos.tenant(tenant)        # strict mode rejects here
        req = DecodeRequest(src, src_len, tenant, max_new_tokens,
                            deadline, request_id=request_id,
                            poisoned=poison)
        with self._cond:
            if self._closed:
                raise ServerClosed("decoder is draining; not "
                                   "accepting new requests")
            if self._queued >= self.config.max_queue_requests:
                if _tm.enabled():
                    _tm.counter(
                        "serving.decode.rejected_queue_full").inc()
                raise RejectedError(
                    f"decode queue full "
                    f"({self.config.max_queue_requests} requests); "
                    f"retry later")
            backlogged = [t for t, q in self._queues.items() if q]
            if tenant not in backlogged:
                self.qos.on_backlogged(
                    tenant, backlogged
                    + list(self.pool.held_by_tenant()))
            self._queues.setdefault(tenant, []).append(req)
            self._queued += 1
            depth = self._queued
            if _tm.enabled():
                _tm.counter("serving.decode.requests").inc()
                _tm.gauge("serving.decode.queue_depth").set(depth)
            self._cond.notify()
        if request_id and _tm.reqtrace_enabled():
            _tm.reqtrace.event(request_id, "decode.enqueue",
                               replica=self.replica_index,
                               tenant=tenant, queue_depth=depth)
        return req.future

    def decode(self, src, timeout=None, **kw):
        """Blocking convenience: submit + wait -> DecodeResult."""
        return self.submit(src, **kw).result(timeout=timeout)

    def cancel(self, future):
        """Best-effort cancellation of the request behind `future`
        (the losing leg of a hedged request). A still-queued request
        is removed and failed with CancelledError right here; an
        admitted one is only FLAGGED — the iteration loop retires it
        and reclaims the slot at the next retire pass, because the
        slot pool has exactly one writer. Either way the future
        resolves exactly once: the queue removal happens under the
        same lock `_admit` pops under, and a flagged slot is touched
        only by the loop thread. Returns True when the request was
        found (still pending somewhere), False when it already
        finished or was never ours."""
        with self._cond:
            for tenant, q in self._queues.items():
                for req in q:
                    if req.future is future:
                        q.remove(req)
                        self._queued -= 1
                        req.future.set_error(CancelledError(
                            "cancelled while queued"))
                        if _tm.enabled():
                            _tm.counter(
                                "serving.decode.cancelled_queued").inc()
                        return True
        slot = self.pool.find(future)
        if slot is not None:
            req = slot.request      # snapshot: loop may retire it
            if req is not None:
                req.cancelled = True
                return True
        return False

    # ------------------------------------------------------- iteration
    def run_iteration(self):
        """One retire/admit/step cycle. Returns the number of active
        slots stepped (0 = nothing to do). Single-threaded by
        contract: either the started loop thread calls this, or a
        test drives it by hand — never both."""
        now = time.monotonic()
        self._retire_deadlines(now)
        self._drop_expired_queued(now)
        had_work = self.pool.active_count() > 0 or self._queued > 0
        if had_work and _chaos.armed():
            # the serving.worker chaos point (worker_crash /
            # replica_slow / replica_flap faults): counted per working
            # iteration, like ModelServer counts per dequeued batch —
            # deterministic under load
            try:
                _chaos.check("serving.worker",
                             detail=f"decode loop {self.name}",
                             replica=self.replica_index)
            except _chaos.ChaosFault:
                if _tm.reqtrace_enabled():
                    # every request riding this replica is about to
                    # die with it — a chaos fault is a capture trigger
                    for slot in self.pool.active():
                        r = slot.request
                        if r is not None and r.request_id:
                            _tm.reqtrace.flag(r.request_id, "chaos")
                            _tm.reqtrace.event(
                                r.request_id, "chaos.fault",
                                replica=self.replica_index,
                                slot=slot.index)
                raise
            # a poisoned request (request_poison fault, tagged at farm
            # submit so the tag rides resubmissions) kills the replica
            # that stepped it — the blast the guard must contain
            for slot in self.pool.active():
                r = slot.request
                if r is not None and r.poisoned:
                    if r.request_id and _tm.reqtrace_enabled():
                        _tm.reqtrace.flag(r.request_id, "chaos")
                        _tm.reqtrace.event(
                            r.request_id, "chaos.request_poison",
                            replica=self.replica_index,
                            slot=slot.index)
                    raise _chaos.ChaosFault(
                        {"name": "request_poison",
                         "point": "serving.request"},
                        f"poisoned request in slot {slot.index} of "
                        f"{self.name}")
        self._admit()
        return self._step_active()

    def _retire_deadlines(self, now):
        for slot in self.pool.active():
            req = slot.request
            if req.cancelled:
                if not req.future.done():
                    req.future.set_error(CancelledError(
                        f"cancelled after {len(slot.tokens)} "
                        f"generated tokens; slot reclaimed"))
                self._finish_slot(slot, delivered=False,
                                  reason="cancelled")
                continue
            if req.expired(now):
                req.future.set_error(DeadlineExceeded(
                    f"deadline expired after {len(slot.tokens)} "
                    f"generated tokens"))
                self._finish_slot(slot, delivered=False,
                                  reason="deadline")
                if _tm.enabled():
                    _tm.counter("serving.decode.deadline_retired").inc()

    def _drop_expired_queued(self, now):
        with self._cond:
            for tenant, q in self._queues.items():
                live = []
                for req in q:
                    if req.expired(now):
                        req.future.set_error(DeadlineExceeded(
                            "deadline expired in decode queue"))
                        self._queued -= 1
                        if _tm.enabled():
                            _tm.counter(
                                "serving.decode.rejected_deadline").inc()
                    else:
                        live.append(req)
                self._queues[tenant] = live
            if _tm.enabled():
                _tm.gauge("serving.decode.queue_depth").set(
                    self._queued)

    def _admit(self):
        """Fill free slots from the queues by WFQ; preempt if allowed
        and somebody is starving below their fair share."""
        batch, slots = [], []
        while True:
            with self._cond:
                queued = [t for t, q in self._queues.items() if q]
                if not queued:
                    break
                held = self.pool.held_by_tenant()
                if self.pool.free_count() == 0:
                    victim_slot = self._pick_preemption(queued, held)
                    if victim_slot is None:
                        break
                    self._preempt(victim_slot)
                    held = self.pool.held_by_tenant()
                tenant = self.qos.pick_tenant(queued, held)
                if tenant is None:
                    break
                req = self._queues[tenant].pop(0)
                self._queued -= 1
            # WFQ charge at admission: the packet length is the
            # request's reserved token budget, so virtual time moves
            # BETWEEN picks and tenants interleave within one wave;
            # unconsumed budget is refunded at retirement
            self.qos.charge(tenant, req.max_new_tokens)
            slot = self.pool.alloc(req, self._iteration)
            self._ids[slot.index] = self.config.bos
            self._pos[slot.index] = 0
            batch.append(req)
            slots.append(slot.index)
            if _tm.enabled():
                _tm.histogram(
                    "serving.decode.queue_wait_seconds").observe(
                    time.monotonic() - req.enqueue_t)
                # admit marker on the timeline, carrying the caller's
                # request id so a trace can be searched by it
                _tm.instant_event("serving.decode.admit",
                                  tenant=req.tenant, slot=slot.index,
                                  request_id=req.request_id)
            if req.request_id and _tm.reqtrace_enabled():
                _tm.reqtrace.event(
                    req.request_id, "decode.admit",
                    replica=self.replica_index, slot=slot.index,
                    tenant=req.tenant,
                    queue_wait_ms=round(
                        (time.monotonic() - req.enqueue_t) * 1e3, 3))
        if batch:
            self.state = self.engine.admit(self.state, batch, slots)
            if _tm.enabled():
                _tm.counter("serving.decode.admitted").inc(len(batch))
                _tm.gauge("serving.decode.queue_depth").set(
                    self._queued)

    def _pick_preemption(self, queued, held):
        starved = self.qos.pick_tenant(queued, held)
        victim = self.qos.preemption_victim(
            starved, queued, held, self.pool.num_slots)
        if victim is None:
            return None
        cands = [s for s in self.pool.active()
                 if s.request.tenant == victim]
        if not cands:
            return None
        # evict the youngest slot: least generated work destroyed
        return max(cands, key=lambda s: (s.joined_iter, s.index))

    def _preempt(self, slot):
        req = slot.request
        req.future.set_error(PreemptedError(
            f"preempted after {len(slot.tokens)} generated tokens to "
            f"admit a tenant below its fair share; retry"))
        self._finish_slot(slot, delivered=False, reason="preempted")
        self.preemptions += 1
        if _tm.enabled():
            _tm.counter("serving.decode.preemptions").inc()
            _tm.counter(
                f"serving.decode.tenant.{req.tenant}.preemptions").inc()

    def _step_active(self):
        active = self.pool.active()
        if not active:
            if _tm.enabled():
                _tm.gauge("serving.decode.slot_occupancy").set(0.0)
            return 0
        self._iteration += 1
        nxt = self.engine.step(self.state, self._ids, self._pos,
                               seed=self._iteration)
        now = time.monotonic()
        eos = self.config.eos
        trace = _tm.reqtrace_enabled()
        occupancy = self.pool.occupancy() if trace else None
        for slot in active:
            req = slot.request
            tok = int(nxt[slot.index])
            if trace and req.request_id:
                # per-iteration slot occupancy on the request's
                # timeline: which step, in how full a pool
                _tm.reqtrace.event(
                    req.request_id, "decode.step",
                    replica=self.replica_index, slot=slot.index,
                    iteration=self._iteration, occupancy=occupancy)
            if slot.first_token_t is None:
                slot.first_token_t = now
                if _tm.enabled():
                    _tm.histogram("serving.decode.ttft_seconds").observe(
                        now - req.enqueue_t)
                if trace and req.request_id:
                    _tm.reqtrace.event(
                        req.request_id, "decode.first_token",
                        replica=self.replica_index, slot=slot.index,
                        ttft_ms=round((now - req.enqueue_t) * 1e3, 3))
            slot.tokens.append(tok)
            self.tokens_generated += 1
            if _tm.enabled():
                _tm.counter("serving.decode.tokens_total").inc()
                _tm.counter(
                    f"serving.decode.tenant.{req.tenant}.tokens").inc()
            if eos is not None and tok == eos:
                self._deliver(slot, "eos", now)
            elif len(slot.tokens) >= req.max_new_tokens:
                self._deliver(slot, "length", now)
            else:
                self._ids[slot.index] = tok
                self._pos[slot.index] += 1
        if _tm.enabled():
            _tm.gauge("serving.decode.slot_occupancy").set(
                self.pool.occupancy())
        return len(active)

    def _deliver(self, slot, reason, now):
        req = slot.request
        req.future.set_result(DecodeResult(
            tokens=np.asarray(slot.tokens, np.int32),
            finish_reason=reason, tenant=req.tenant,
            ttft_s=(slot.first_token_t - req.enqueue_t
                    if slot.first_token_t else None),
            decode_s=now - slot.joined_t))
        self._finish_slot(slot, delivered=True, reason=reason)

    def _finish_slot(self, slot, delivered, reason):
        req = slot.request
        unused = req.max_new_tokens - len(slot.tokens or ())
        if unused > 0:
            self.qos.refund(req.tenant, unused)
        if req.request_id and _tm.reqtrace_enabled():
            if reason == "deadline":
                _tm.reqtrace.flag(req.request_id, "deadline")
            # the slot's admit->retire lifetime as one span, stamped
            # at retirement (the admit instant anchors its start)
            dur_us = int((time.monotonic() - slot.joined_t) * 1e6)
            _tm.reqtrace.span_at(
                req.request_id, "decode.slot",
                _tm.now_us() - dur_us, dur_us,
                replica=self.replica_index, slot=slot.index,
                reason=reason, delivered=delivered,
                tokens=len(slot.tokens or ()))
            _tm.reqtrace.event(
                req.request_id, "decode.retire",
                replica=self.replica_index, slot=slot.index,
                reason=reason, delivered=delivered)
        self.pool.release(slot)
        self._ids[slot.index] = 0
        self._pos[slot.index] = 0
        if _tm.enabled():
            _tm.counter("serving.decode.retired").inc()
            _tm.counter(f"serving.decode.retired_{reason}").inc()
            _tm.instant_event("serving.decode.retire",
                              tenant=req.tenant, slot=slot.index,
                              reason=reason, delivered=delivered,
                              request_id=req.request_id)

    # ------------------------------------------------------- lifecycle
    def start(self):
        """Spawn the supervised decode loop thread."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._started = True
        self._thread = threading.Thread(
            target=self._loop_guarded,
            name=f"tpudecode-{self.name}", daemon=True)
        self._thread.start()
        return self

    def _loop_guarded(self):
        try:
            self._loop()
        except BaseException as e:      # noqa: BLE001 — thread death
            if self._closed:
                return
            self._crash_recover(e)
            self.restarts += 1
            if _tm.enabled():
                _tm.counter("serving.decode.worker_restarts").inc()
            _LOG.warning(
                "tpudecode loop %s died (%s: %s) — slots reclaimed, "
                "restarting", self.name, type(e).__name__, e)
            # the dying thread IS self._thread and still alive here;
            # drop the reference so start() actually respawns
            self._thread = None
            self.start()

    def _loop(self):
        while True:
            stepped = self.run_iteration()
            if stepped:
                continue
            with self._cond:
                if self._closed and self._queued == 0 \
                        and self.pool.active_count() == 0:
                    return
                # stepped == 0 means nothing active and nothing
                # admissible; park until a submit notifies (bounded
                # wait so close/cap changes are re-checked)
                self._cond.wait(self.config.idle_wait_s)

    def _crash_recover(self, exc):
        """Leak-free crash cleanup: every bound slot's request fails
        with the crash error and its row returns to the pool; queued
        requests stay queued for the respawned loop."""
        for slot in self.pool.active():
            if not slot.request.future.done():
                slot.request.future.set_error(exc)
            self._finish_slot(slot, delivered=False, reason="crash")
        self.pool.check()

    def stop(self, drain=True, timeout=30.0):
        """Stop admitting; optionally let the loop drain queued +
        in-flight work before joining."""
        with self._cond:
            self._closed = True
            if not drain:
                for q in self._queues.values():
                    for req in q:
                        req.future.set_error(ServerClosed(
                            "decoder shut down before this request "
                            "ran"))
                    q.clear()
                self._queued = 0
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        if not drain:
            for slot in self.pool.active():
                slot.request.future.set_error(ServerClosed(
                    "decoder shut down mid-generation"))
                self._finish_slot(slot, delivered=False,
                                  reason="shutdown")

    @property
    def queued(self):
        with self._cond:
            return self._queued

    @property
    def alive(self):
        """False exactly in the crashed-and-not-yet-respawned window
        of a started loop (the farm router's skip signal). A scheduler
        that was never start()ed is driven by hand — always alive."""
        if not self._started:
            return True
        t = self._thread
        return t is not None and t.is_alive()
