"""Multi-tenant QoS: weighted-fair-queuing admission over decode slots.

The unit of service in continuous decode is the *slot-iteration* (one
slot held for one decode step — one token's worth of the machine).
A request's "packet length" is its reserved token budget
(`max_new_tokens`): the scheduler charges it at ADMISSION — so
normalized virtual time moves between picks within a single admission
wave and tenants interleave at request granularity, not pool-sized
bursts — and refunds whatever an early eos/deadline/preemption leaves
unconsumed. Admission always goes to the backlogged tenant with the
least `service / weight`; over any saturated interval each tenant's
token share converges to `weight_i / sum(weights of backlogged
tenants)` — classic start-time fair queuing.

Idle tenants don't bank credit: on re-backlog a tenant's virtual time
is lifted to the minimum over currently-backlogged tenants (the SFQ
"catch up to system virtual time" rule), so a tenant that slept for an
hour competes fairly, not catastrophically.

Preemption (opt-in): when admission finds no free slot and a
backlogged tenant holds strictly less than its weighted fair share
while another holds strictly more, the over-share tenant's youngest
slot is evicted (least progress destroyed). The scheduler maps the
eviction to `PreemptedError` — HTTP 429, distinct from deadline's 504
— so clients can tell "retry later" from "too slow".
"""
import threading

__all__ = ["TenantClass", "QosPolicy"]


class TenantClass:
    """Admission class for one tenant."""

    __slots__ = ("name", "weight", "max_slots", "vtime")

    def __init__(self, name, weight=1.0, max_slots=None):
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        self.name = name
        self.weight = float(weight)
        self.max_slots = max_slots if max_slots is None \
            else int(max_slots)
        self.vtime = 0.0            # normalized service accrued

    def __repr__(self):
        return (f"TenantClass({self.name!r}, weight={self.weight}, "
                f"max_slots={self.max_slots})")


class QosPolicy:
    """WFQ accounting + admission/preemption decisions.

    Unknown tenants are auto-registered at `default_weight` (serving
    millions of users means the tenant set is open); pass
    `strict=True` to reject unknown tenants at submit instead.
    """

    def __init__(self, tenants=None, default_weight=1.0,
                 preemption=False, strict=False):
        self._tenants = {}
        self.default_weight = float(default_weight)
        self.preemption = bool(preemption)
        self.strict = bool(strict)
        self._lock = threading.Lock()
        for t in tenants or ():
            if not isinstance(t, TenantClass):
                t = TenantClass(*t) if isinstance(t, tuple) \
                    else TenantClass(t)
            self._tenants[t.name] = t

    # ------------------------------------------------------- accounts
    def tenant(self, name):
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                if self.strict:
                    raise KeyError(
                        f"unknown tenant {name!r} (strict QoS; "
                        f"classes: {sorted(self._tenants)})")
                t = TenantClass(name, weight=self.default_weight)
                self._tenants[t.name] = t
            return t

    def tenants(self):
        with self._lock:
            return dict(self._tenants)

    def weights(self):
        """name -> weight snapshot of the registered classes."""
        with self._lock:
            return {n: t.weight for n, t in self._tenants.items()}

    def lowest_classes(self):
        """Registered class names sharing the minimum weight — the
        brownout controller's shed set. Empty when every class weighs
        the same: "shed the lowest class" must never mean "shed
        everyone"."""
        with self._lock:
            ws = {t.weight for t in self._tenants.values()}
            if len(ws) < 2:
                return set()
            lo = min(ws)
            return {n for n, t in self._tenants.items()
                    if t.weight == lo}

    def charge(self, name, slot_iterations):
        """Accrue service: `slot_iterations` of machine time
        reserved/used."""
        t = self.tenant(name)
        t.vtime += slot_iterations / t.weight

    def refund(self, name, slot_iterations):
        """Give back reserved service a request did not consume
        (early eos, deadline retire, preemption)."""
        t = self.tenant(name)
        t.vtime -= slot_iterations / t.weight

    def on_backlogged(self, name, backlogged_names):
        """Idle -> backlogged transition: lift the tenant's virtual
        time to the floor of the currently-backlogged set so idle
        periods don't bank unbounded credit."""
        t = self.tenant(name)
        floor = [self.tenant(o).vtime for o in backlogged_names
                 if o != name]
        if floor:
            t.vtime = max(t.vtime, min(floor))

    # ------------------------------------------------------ decisions
    def pick_tenant(self, queued_tenants, held):
        """The backlogged tenant that should get the next slot: least
        normalized service, ties broken by name for determinism.
        Tenants at their max_slots cap are skipped. Returns None when
        nobody is eligible."""
        best = None
        for name in sorted(set(queued_tenants)):
            t = self.tenant(name)
            if t.max_slots is not None \
                    and held.get(name, 0) >= t.max_slots:
                continue
            if best is None or t.vtime < best.vtime:
                best = t
        return best.name if best is not None else None

    def fair_share(self, name, demand_tenants, num_slots):
        """`name`'s weighted share of the slot pool over the tenants
        that currently want slots (hold or queue)."""
        total = sum(self.tenant(o).weight for o in set(demand_tenants))
        if total <= 0:
            return float(num_slots)
        return num_slots * self.tenant(name).weight / total

    def preemption_victim(self, starved, queued_tenants, held,
                          num_slots):
        """Which tenant (if any) should lose a slot so `starved` can
        join? Only fires when starved is strictly under its fair share
        and the victim strictly over its own — so steady fair states
        never thrash. Returns a tenant name or None."""
        if not self.preemption or starved is None:
            return None
        demand = set(queued_tenants) | set(held)
        if held.get(starved, 0) + 1 \
                > self.fair_share(starved, demand, num_slots):
            return None                     # would overshoot its share
        victim, excess = None, 0.0
        for name, n in held.items():
            if name == starved:
                continue
            over = n - self.fair_share(name, demand, num_slots)
            if over > excess + 1e-9:
                victim, excess = name, over
        return victim
