"""Slot pool: host-side bookkeeping for the static decode batch.

The device side of tpudecode is a fixed `[num_slots, ...]` KV-cache
(see `models.transformer.IncrementalDecoder`); this module tracks which
of those rows currently belongs to which request. Joining the batch is
`alloc` + a device scatter of the prefilled caches; leaving is `free` —
no reshape, no recompile, ever.

The pool is deliberately paranoid about leaks: a slot row that is
neither free nor bound to a live request is serving capacity silently
lost forever (the moral equivalent of a leaked file descriptor), so
`check()` asserts the partition invariant and the chaos test drives it
across injected scheduler crashes.
"""
import time

__all__ = ["Slot", "SlotPool"]


class Slot:
    """One row of the decode batch, bound to at most one request."""

    __slots__ = ("index", "request", "tokens", "joined_iter",
                 "joined_t", "first_token_t")

    def __init__(self, index):
        self.index = index
        self.request = None
        self.tokens = None          # generated token ids (host list)
        self.joined_iter = -1
        self.joined_t = 0.0
        self.first_token_t = None

    @property
    def busy(self):
        return self.request is not None

    def bind(self, request, iteration):
        self.request = request
        self.tokens = []
        self.joined_iter = iteration
        self.joined_t = time.monotonic()
        self.first_token_t = None

    def clear(self):
        self.request = None
        self.tokens = None
        self.joined_iter = -1
        self.first_token_t = None


class SlotPool:
    """Fixed set of `num_slots` slots; free-list allocation.

    Not thread-safe by itself — the continuous scheduler is the single
    writer (its iteration loop owns admit/retire); everyone else reads
    coarse counters.
    """

    def __init__(self, num_slots):
        self.num_slots = int(num_slots)
        if self.num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self._slots = [Slot(i) for i in range(self.num_slots)]
        self._free = list(range(self.num_slots - 1, -1, -1))

    # ------------------------------------------------------ allocation
    def free_count(self):
        return len(self._free)

    def active_count(self):
        return self.num_slots - len(self._free)

    def alloc(self, request, iteration):
        """Bind `request` to a free slot; raises IndexError when full
        (callers gate on free_count)."""
        idx = self._free.pop()
        slot = self._slots[idx]
        slot.bind(request, iteration)
        return slot

    def release(self, slot):
        """Return a slot to the free list (idempotence is a bug: a
        double free would hand one row to two requests)."""
        if not slot.busy and slot.index in self._free:
            raise RuntimeError(f"double free of slot {slot.index}")
        slot.clear()
        self._free.append(slot.index)

    # ------------------------------------------------------ inspection
    def active(self):
        """Busy slots in index order (deterministic iteration)."""
        return [s for s in self._slots if s.busy]

    def slot(self, index):
        return self._slots[index]

    def find(self, future):
        """The busy slot whose bound request resolves `future`, or
        None (the cancel path's lookup; also the hedge-leak tests')."""
        for s in self._slots:
            r = s.request        # snapshot: callers read cross-thread
            if r is not None and r.future is future:
                return s
        return None

    def held_by_tenant(self):
        held = {}
        for s in self._slots:
            r = s.request        # snapshot: submit() reads cross-thread
            if r is not None:
                held[r.tenant] = held.get(r.tenant, 0) + 1
        return held

    def occupancy(self):
        return self.active_count() / self.num_slots

    def check(self):
        """Assert the free/busy partition invariant; returns True or
        raises (the slot-leak acid test after injected crashes)."""
        free = set(self._free)
        busy = {s.index for s in self._slots if s.busy}
        if free & busy or len(free) + len(busy) != self.num_slots \
                or len(free) != len(self._free):
            raise RuntimeError(
                f"slot pool corrupt: free={sorted(free)} "
                f"busy={sorted(busy)} of {self.num_slots}")
        return True
