"""DecodeEngine: the serving face of the incremental decoder.

Wraps `models.transformer.IncrementalDecoder` with everything the
continuous scheduler needs and nothing it doesn't:

- **bucketed prefill**: admitted requests are padded row-wise to a
  fixed bucket set (powers of two up to `num_slots` by default, the
  same discipline as `inference.default_buckets`), so the executable
  count stays `len(prefill_buckets) + 1` — pinned by
  `tpuserve --selftest-decode` and surfaced as the
  `serving.decode.compile_count` gauge;
- **warmup**: every prefill bucket and the step function compile on
  zero feeds at attach time, so live traffic never eats a compile
  stall (the PR 3 warmup story, extended to the decode tier);
- **telemetry**: prefill/step/warmup spans and counters in the
  `serving.decode.*` namespace, flowing into tpustat like every other
  subsystem.

The scheduler talks to this class through a deliberately narrow,
duck-typeable surface (``num_slots / max_new_tokens / init_state /
admit / step / compile_count``) so QoS and slot logic unit-test
against a fake engine in microseconds.
"""
import numpy as np

from ... import telemetry as _tm
from ...inference import default_buckets, next_bucket

__all__ = ["DecodeEngineConfig", "DecodeEngine"]


class DecodeEngineConfig:
    """Knobs for one model's decode tier.

    num_slots: decode batch rows (the KV-cache's slot dimension).
    max_len: decode cache length (generated capacity = max_len - 1);
        defaults to the model config's max_len.
    src_max_len: encoder pad length; defaults to max_len.
    prefill_buckets: admitted-row buckets (default: powers of two up
        to num_slots).
    topk / temperature: in-graph sampling (0 = greedy argmax).
    kv_quant / kv_block: opt-in int8 block-quantized self-attn KV
        cache (None keeps fp32 — the byte-identical default); block
        defaults to the head dim.
    """

    def __init__(self, num_slots=8, max_len=None, src_max_len=None,
                 prefill_buckets=None, topk=0, temperature=1.0,
                 kv_quant=None, kv_block=None):
        self.num_slots = int(num_slots)
        self.max_len = max_len
        self.src_max_len = src_max_len
        self.prefill_buckets = tuple(sorted(
            int(b) for b in (prefill_buckets
                             or default_buckets(self.num_slots))))
        if self.prefill_buckets[-1] < self.num_slots:
            raise ValueError(
                f"largest prefill bucket {self.prefill_buckets[-1]} "
                f"< num_slots {self.num_slots}: a full admission wave "
                f"must fit one prefill")
        self.topk = int(topk)
        self.temperature = float(temperature)
        self.kv_quant = kv_quant
        self.kv_block = kv_block


class DecodeEngine:
    """Compiled continuous-decode executables for one transformer.

    Replica-serving knobs (all default-off, single-engine path
    unchanged): ``device`` pins the decode executables + slot state to
    one device (a farm replica's slice primary); ``prefill_device``
    DISAGGREGATES prefill — a second decoder on a dedicated device
    runs the encoder executables and its KV output is handed
    device-to-device into this engine's slot pool (`jax.device_put`:
    ICI/DMA on TPU, a host copy fallback on CPU), so long-prompt
    prefills stop stalling the token loop's device; ``build_cache``
    shares jit traces across same-config replicas."""

    def __init__(self, model_cfg, params, config=None, device=None,
                 prefill_device=None, build_cache=None):
        from ...models.transformer import IncrementalDecoder
        self.config = config or DecodeEngineConfig()
        self.model_cfg = model_cfg
        self.device = device
        self.decoder = IncrementalDecoder(
            model_cfg, params,
            num_slots=self.config.num_slots,
            max_len=self.config.max_len,
            src_max_len=self.config.src_max_len,
            topk=self.config.topk,
            temperature=self.config.temperature,
            device=device,
            kv_quant=self.config.kv_quant,
            kv_block=self.config.kv_block,
            build_cache=build_cache)
        self.prefill_decoder = None
        if prefill_device is not None:
            # prefill never touches the decode-side KV cache, so the
            # prefill worker stays fp32 regardless of kv_quant; it
            # shares the build cache (prefill keys exclude step-only
            # knobs, so pooled and disaggregated replicas share the
            # same encoder traces)
            self.prefill_decoder = IncrementalDecoder(
                model_cfg, params,
                num_slots=self.config.num_slots,
                max_len=self.config.max_len,
                src_max_len=self.config.src_max_len,
                device=prefill_device,
                build_cache=build_cache)
        if _tm.memledger_enabled():
            self._register_params()

    def _register_params(self, owner=None):
        """Attribute the decoder-held device weight copies. Owner is
        re-stamped at init_state time once the farm has assigned a
        replica index (registration by id moves, never duplicates)."""
        from ...telemetry import memledger as _ml
        if owner is None:
            owner = ("decode" if self.replica_index is None
                     else f"replica{self.replica_index}")
        _ml.register("params", owner, self.decoder.params)
        if self.prefill_decoder is not None:
            _ml.register("params", owner, self.prefill_decoder.params)

    # ----------------------------------------------------- constructors
    @classmethod
    def from_inference_engine(cls, engine, model_cfg, config=None,
                              **kw):
        """Share a served `InferenceEngine`'s parameters (same arrays,
        no copy): the prefill/step executables and the full-program
        predict path serve one checkpoint."""
        return cls(model_cfg, engine.params(), config=config, **kw)

    @classmethod
    def from_scope(cls, scope, model_cfg, config=None, names=None,
                   **kw):
        """Pull parameters out of a training/infer scope by name
        (`names` defaults to every var the scope can produce for the
        decode set — see `models.transformer.decode_params`)."""
        from ...models.transformer import decode_params
        if names is None:
            probe = {}
            for n in _decode_name_universe(model_cfg):
                v = scope.get(n) if hasattr(scope, "get") else None
                if v is not None:
                    probe[n] = np.asarray(v)
            arrays = probe
        else:
            arrays = {n: np.asarray(scope.get(n)) for n in names}
        return cls(model_cfg, decode_params(arrays, model_cfg),
                   config=config, **kw)

    # set by serving.farm at spawn (like ContinuousScheduler's): lands
    # engine-side trace events (prefill, KV handoff) on the right
    # replica pid of a request exemplar; None for single engines
    replica_index = None

    # ------------------------------------------------------- properties
    @property
    def num_slots(self):
        return self.config.num_slots

    @property
    def max_new_tokens(self):
        return self.decoder.max_new_tokens

    @property
    def src_max_len(self):
        return self.decoder.src_max_len

    @property
    def compile_count(self):
        n = self.decoder.compile_count
        if self.prefill_decoder is not None:
            n += self.prefill_decoder.compile_count
        return n

    @property
    def kv_cache_bytes(self):
        """Slot-state footprint (see IncrementalDecoder.kv_cache_bytes)."""
        return self.decoder.kv_cache_bytes()

    # -------------------------------------------------------- lifecycle
    def init_state(self):
        state = self.decoder.init_state()
        if _tm.memledger_enabled():
            # creation site of the KV-cache blocks: owner is the
            # replica (once the farm assigned one), quant rides as
            # metadata so an OOM hint knows fp32 from int8
            from ...telemetry import memledger as _ml
            owner = ("decode" if self.replica_index is None
                     else f"replica{self.replica_index}")
            _ml.register("kv_cache", owner, state,
                         quant=self.config.kv_quant)
            self._register_params(owner)
        return state

    def set_params(self, arrays):
        """Rolling weight update: swap the parameter set under the
        compiled executables (shapes must match -> zero recompile).
        Covers the disaggregated prefill decoder too, atomically from
        the caller's point of view — the replica is drained while this
        runs, so no request sees mixed versions."""
        self.decoder.load_params(arrays)
        if self.prefill_decoder is not None:
            self.prefill_decoder.load_params(arrays)
        if _tm.memledger_enabled():
            self._register_params()

    def warmup(self):
        """Compile every prefill bucket + the step on zero feeds.
        Returns the executable count (== len(prefill_buckets) + 1 when
        this engine built everything itself; shared build caches and
        disaggregation split the count across decoders but the sum is
        pinned at the group level)."""
        pf = self.prefill_decoder or self.decoder
        Ts = self.decoder.src_max_len
        for b in self.config.prefill_buckets:
            with _tm.span("serving.decode.warmup", bucket=b):
                pf.prefill(np.zeros((b, Ts), np.int64),
                           np.ones((b,), np.int64))
            if _tm.enabled():
                _tm.counter("serving.decode.warmup_runs").inc()
        state = self.init_state()
        with _tm.span("serving.decode.warmup", bucket="step"):
            self.decoder.step(state, np.zeros(self.num_slots, np.int64),
                              np.zeros(self.num_slots, np.int64))
        if _tm.enabled():
            _tm.gauge("serving.decode.compile_count").set(
                self.compile_count)
            _tm.gauge("serving.decode.kv_cache_bytes").set(
                self.kv_cache_bytes)
            # kern-registry evidence from the step trace (read via
            # sys.modules — registry-off runs must not import kern)
            import sys
            kr = sys.modules.get("paddle_tpu.ops.kern.registry")
            if kr is not None:
                _tm.gauge("serving.decode.kern_dispatches").set(
                    kr.STATS["dispatches"])
                _tm.gauge("serving.decode.kern_accepted").set(
                    kr.STATS["accepted"])
        return self.compile_count

    # ---------------------------------------------------------- serving
    def admit(self, state, requests, slots):
        """Prefill `requests` (same count as `slots`) and scatter the
        encoder caches into their slot rows. Rows are padded to the
        next prefill bucket so the jit cache sees only bucket shapes.
        With a disaggregated prefill decoder, the encoder runs on its
        dedicated device and the KV output is handed off to the decode
        device before the scatter."""
        n = len(requests)
        Ts = self.decoder.src_max_len
        bucket = next_bucket(n, self.config.prefill_buckets)
        src = np.zeros((bucket, Ts), np.int64)
        src_len = np.ones((bucket,), np.int64)   # pad rows attend pos 0
        for j, r in enumerate(requests):
            s = np.asarray(r.src, np.int64).reshape(-1)
            src[j, :len(s)] = s
            src_len[j] = min(Ts, max(1, int(r.src_len)))
        pf = self.prefill_decoder or self.decoder
        trace = _tm.reqtrace_enabled()
        t0 = _tm.now_us() if trace else 0
        with _tm.span("serving.decode.prefill", rows=n, bucket=bucket):
            out = pf.prefill(src, src_len)
        if trace:
            dur = _tm.now_us() - t0
            for r in requests:
                if r.request_id:
                    _tm.reqtrace.span_at(
                        r.request_id, "engine.prefill", t0, dur,
                        replica=self.replica_index, rows=n,
                        bucket=bucket,
                        disaggregated=self.prefill_decoder is not None)
        if self.prefill_decoder is not None:
            out = self._handoff(out, requests)
        if _tm.enabled():
            _tm.counter("serving.decode.prefill_rows").inc(n)
            _tm.counter("serving.decode.prefill_pad_rows").inc(
                bucket - n)
            _tm.gauge("serving.decode.compile_count").set(
                self.compile_count)
        return self.decoder.write_slots(state, out, slots)

    def _handoff(self, out, requests=()):
        """Move prefilled KV state (ck, cv, src_bias) from the prefill
        device onto the decode device. `jax.device_put` is the one
        transfer op that lowers to whatever the platform has —
        device-to-device DMA over ICI on TPU, a host round-trip
        fallback on CPU — so the slot scatter always sees colocated
        operands."""
        import jax
        ck, cv, src_bias = out
        nbytes = int(ck.nbytes + cv.nbytes + src_bias.nbytes)
        if _tm.enabled():
            _tm.counter("serving.decode.handoff_bytes").inc(nbytes)
            _tm.counter("serving.decode.handoffs").inc()
        dev = self.device if self.device is not None \
            else jax.devices()[0]
        trace = _tm.reqtrace_enabled()
        t0 = _tm.now_us() if trace else 0
        with _tm.span("serving.decode.handoff"):
            moved = jax.device_put((ck, cv, src_bias), dev)
        if trace:
            dur = _tm.now_us() - t0
            for r in requests:
                if r.request_id:
                    _tm.reqtrace.span_at(
                        r.request_id, "engine.kv_handoff", t0, dur,
                        replica=self.replica_index, bytes=nbytes)
        return moved

    def step(self, state, ids, pos, seed=0):
        """One decode iteration over all slots -> next ids [S]."""
        try:
            nxt = self.decoder.step(state, ids, pos, seed=seed)
        except Exception as e:
            if _tm.memledger_enabled():
                from ...telemetry import memledger as _ml
                _ml.handle_possible_oom(
                    e, context={"site": "decode.step",
                                "replica": self.replica_index})
            raise
        if _tm.enabled():
            _tm.counter("serving.decode.steps").inc()
            _tm.gauge("serving.decode.compile_count").set(
                self.compile_count)
        if _tm.memledger_enabled():
            from ...telemetry import memledger as _ml
            _ml.on_step(context={"site": "decode.step",
                                 "replica": self.replica_index})
        return nxt


def _decode_name_universe(cfg):
    """Every parameter name decode could need, in either checkpoint
    layout (union of unfused + fused names; absent ones just don't
    resolve in the scope)."""
    names = ["src_emb.w_0", "trg_emb.w_0", "proj.w_0"]
    for i in range(cfg.n_layer):
        names += [f"enc{i}_{p}.w_0" for p in "qkvo"]
        names += [f"dec{i}_self_{p}.w_0" for p in "qkvo"]
        names += [f"dec{i}_cross_{p}.w_0" for p in "qkvo"]
        names += [f"enc{i}_qkv.w_0", f"dec{i}_self_qkv.w_0",
                  f"dec{i}_cross_kv.w_0", f"dec{i}_cross_q.w_0"]
        for part in (f"enc{i}_ffn", f"dec{i}_ffn"):
            names += [f"{part}_fc1.w_0", f"{part}_fc1.b_0",
                      f"{part}_fc2.w_0", f"{part}_fc2.b_0"]
    for j in range(5 * cfg.n_layer):
        names += [f"layer_norm_{j}.w_0", f"layer_norm_{j}.b_0"]
    return names
