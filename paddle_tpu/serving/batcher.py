"""Dynamic batcher: bounded queue + shape-bucket coalescing.

Requests (each a {name: array} feed with a leading batch dim) enter a
bounded FIFO queue; worker threads pull a *batch* of compatible
requests — same feed names, feature shapes, and dtypes — coalesced up
to `max_batch_size` rows or `max_wait_ms` of age, whichever comes
first. The assembled batch is padded up to the next configured shape
bucket (`inference.bucket_feed`), run once, and the fetch rows are
scattered back to callers in submission order.

Admission control is the point, not an afterthought:

- the queue is bounded (`max_queue_requests`); a submit against a full
  queue raises `RejectedError` immediately — overload sheds load in
  microseconds instead of growing an unbounded backlog;
- every request may carry a deadline; `Future.result` stops waiting at
  the deadline and workers drop already-expired requests without
  running them (`DeadlineExceeded`).

The batcher is engine-agnostic: it never imports jax and can be unit
tested with a fake "engine" that echoes its input.
"""
import collections
import threading
import time

import numpy as np

from .. import telemetry as _tm
from ..inference import bucket_feed, default_buckets

__all__ = ["BatchConfig", "DynamicBatcher", "Batch", "Future",
           "RejectedError", "DeadlineExceeded", "ServerClosed",
           "PreemptedError", "CancelledError", "RetryBudgetExhausted",
           "BrownoutShed"]

# fixed edges for the batch-size histogram: the registry freezes bucket
# edges at first creation, so this must not vary with BatchConfig
_ROWS_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


class RejectedError(RuntimeError):
    """Request refused by admission control (queue full / oversized)."""


class ServerClosed(RejectedError):
    """Request refused because the server is draining or stopped."""


class DeadlineExceeded(RejectedError):
    """Request deadline expired before a result was produced."""


class PreemptedError(RejectedError):
    """Request evicted from its decode slot by a QoS admission in
    favor of a tenant below its fair share (HTTP 429: retry — the
    service is up, this tenant is just over its share right now).
    Lives here with the rest of the admission-control vocabulary so
    the HTTP layer never has to import the decode package."""


class CancelledError(RejectedError):
    """Request cancelled by the caller side — normally the losing leg
    of a hedged request after the other replica already delivered.
    Clients never see this; it resolves the abandoned future so
    nothing blocks on it forever."""


class RetryBudgetExhausted(RejectedError):
    """Resubmission/hedge refused: the group-wide retry token bucket
    is empty. A retry storm (mass replica death, poisoned request
    resubmitting forever) degrades into fast typed rejections instead
    of amplifying the overload (HTTP 429, kind "retry_budget")."""


class BrownoutShed(RejectedError):
    """Request shed by the brownout controller: the group is over its
    queue-depth / deadline-miss thresholds and this tenant is in the
    lowest QoS class (HTTP 429, kind "brownout"). `retry_after_s` is
    the hint surfaced as the Retry-After header."""

    def __init__(self, msg, retry_after_s=1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class BatchConfig:
    """Knobs for one model's batcher.

    buckets defaults to powers of two up to max_batch_size, so the
    compiled-signature count is bounded by log2(max_batch_size)+1.
    """

    def __init__(self, max_batch_size=64, max_wait_ms=5.0, buckets=None,
                 max_queue_requests=256):
        self.max_batch_size = int(max_batch_size)
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self.max_wait_ms = float(max_wait_ms)
        self.buckets = tuple(sorted(int(b) for b in (
            buckets or default_buckets(self.max_batch_size))))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"bad buckets {self.buckets}")
        if self.buckets[-1] < self.max_batch_size:
            # a full batch must land in some bucket
            self.max_batch_size = self.buckets[-1]
        self.max_queue_requests = int(max_queue_requests)

    def __repr__(self):
        return (f"BatchConfig(max_batch_size={self.max_batch_size}, "
                f"max_wait_ms={self.max_wait_ms}, "
                f"buckets={self.buckets}, "
                f"max_queue_requests={self.max_queue_requests})")


class Future:
    """Caller-side handle for one queued request."""

    __slots__ = ("_event", "_result", "_error", "_deadline")

    def __init__(self, deadline):
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._deadline = deadline          # monotonic seconds or None

    def done(self):
        return self._event.is_set()

    def set_result(self, result):
        self._result = result
        self._event.set()

    def set_error(self, exc):
        self._error = exc
        self._event.set()

    def result(self, timeout=None):
        """Block for the fetch rows. Respects the request deadline:
        waiting never outlives it by more than a scheduling tick."""
        wait = timeout
        if self._deadline is not None:
            to_deadline = max(0.0, self._deadline - time.monotonic())
            # small grace so a worker that *just* made the deadline can
            # still deliver instead of racing the waiter
            to_deadline += 0.05
            wait = to_deadline if wait is None else min(wait, to_deadline)
        if not self._event.wait(wait):
            if self._deadline is not None \
                    and time.monotonic() >= self._deadline:
                raise DeadlineExceeded("request deadline expired while "
                                       "waiting for a worker")
            raise TimeoutError("timed out waiting for result")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("feed", "rows", "group", "deadline", "enqueue_t",
                 "future", "request_id")

    def __init__(self, feed, rows, group, deadline, request_id=None):
        self.feed = feed
        self.rows = rows
        self.group = group
        self.deadline = deadline
        self.enqueue_t = time.monotonic()
        self.future = Future(deadline)
        self.request_id = request_id

    def expired(self, now=None):
        return self.deadline is not None \
            and (now or time.monotonic()) >= self.deadline


def _group_key(feed):
    """Requests are batchable iff they agree on everything but the
    batch dim: feed names, per-feed feature shapes, and dtypes."""
    return tuple(sorted(
        (k, tuple(np.shape(v)[1:]), str(np.asarray(v).dtype))
        for k, v in feed.items()))


class Batch:
    """A coalesced group of requests plus scatter-back bookkeeping."""

    __slots__ = ("requests", "group", "formed_t")

    def __init__(self, requests):
        self.requests = list(requests)
        self.group = requests[0].group
        self.formed_t = time.monotonic()

    @property
    def rows(self):
        return sum(r.rows for r in self.requests)

    def drop_expired(self, now=None):
        """Fail requests whose deadline passed while queued; returns
        the number dropped. Never runs compute for a dead caller."""
        now = now or time.monotonic()
        live, dropped = [], 0
        for r in self.requests:
            if r.expired(now):
                r.future.set_error(DeadlineExceeded(
                    "deadline expired in queue"))
                dropped += 1
                if r.request_id and _tm.reqtrace_enabled():
                    _tm.reqtrace.flag(r.request_id, "deadline")
                    _tm.reqtrace.event(r.request_id,
                                       "batch.deadline_drop")
            else:
                live.append(r)
        self.requests = live
        if dropped and _tm.enabled():
            _tm.counter("serving.rejected_deadline").inc(dropped)
        return dropped

    def assemble(self, buckets):
        """Concatenate request feeds row-wise and pad to the bucket.
        Returns (padded_feed, true_rows, bucket)."""
        names = [k for k, _shape, _dt in self.group]
        arrays = {
            k: np.concatenate(
                [np.asarray(r.feed[k]) for r in self.requests], axis=0)
            for k in names}
        padded, true_rows, mask = bucket_feed(arrays, buckets)
        return padded, true_rows, len(mask)

    def scatter(self, outs, bucket):
        """Slice each caller's rows back out of the batch fetches, in
        submission order. Fetches without a leading batch dim (e.g.
        scalar reductions) are handed to every caller whole."""
        off = 0
        for r in self.requests:
            rows = []
            for o in outs:
                if getattr(o, "ndim", 0) >= 1 and o.shape[0] == bucket:
                    rows.append(o[off:off + r.rows])
                else:
                    rows.append(o)
            r.future.set_result(rows)
            off += r.rows

    def fail(self, exc):
        for r in self.requests:
            if not r.future.done():
                r.future.set_error(exc)


class DynamicBatcher:
    """Bounded request queue with shape-bucket batch formation."""

    def __init__(self, config=None, name="model"):
        self.config = config or BatchConfig()
        self.name = name
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closed = False

    # ---------------------------------------------------- caller side
    def submit(self, feed, deadline_ms=None, request_id=None):
        """Enqueue one request; returns a Future. Raises RejectedError
        (queue full / oversized / closed) instead of blocking — the
        caller learns about overload immediately. `request_id` rides
        along for span/trace attribution."""
        if not feed:
            raise ValueError("empty feed")
        rows_set = {int(np.shape(v)[0]) if np.ndim(v) >= 1 else None
                    for v in feed.values()}
        rows_set.discard(None)
        if len(rows_set) != 1:
            raise ValueError(
                f"feed arrays disagree on the batch dim: "
                f"{ {k: np.shape(v) for k, v in feed.items()} }")
        rows = rows_set.pop()
        if rows > self.config.max_batch_size:
            raise RejectedError(
                f"request of {rows} rows exceeds max_batch_size "
                f"{self.config.max_batch_size}")
        deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        req = _Request(feed, rows, _group_key(feed), deadline,
                       request_id=request_id)
        with self._cond:
            if self._closed:
                raise ServerClosed("server is draining; not accepting "
                                   "new requests")
            if len(self._queue) >= self.config.max_queue_requests:
                if _tm.enabled():
                    _tm.counter("serving.rejected_queue_full").inc()
                raise RejectedError(
                    f"queue full ({self.config.max_queue_requests} "
                    f"requests); retry later")
            self._queue.append(req)
            depth = len(self._queue)
            if _tm.enabled():
                _tm.counter("serving.requests").inc()
                _tm.gauge("serving.queue_depth").set(depth)
            self._cond.notify()
        if request_id and _tm.reqtrace_enabled():
            _tm.reqtrace.event(request_id, "batcher.enqueue",
                               rows=rows, queue_depth=depth)
        return req.future

    # ---------------------------------------------------- worker side
    def next_batch(self, timeout=None):
        """Block up to `timeout` for work, then coalesce one batch.

        The batch closes when it reaches max_batch_size rows or when
        the oldest member has waited max_wait_ms — classic TF-Serving
        batching. Returns None on timeout or when closed and drained.
        """
        arrival_deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cond:
            while True:
                while not self._queue:
                    if self._closed:
                        return None
                    if arrival_deadline is None:
                        self._cond.wait()
                    else:
                        remaining = arrival_deadline - time.monotonic()
                        if remaining <= 0:
                            return None
                        self._cond.wait(remaining)
                head = self._queue[0]
                close_t = head.enqueue_t + self.config.max_wait_ms / 1e3
                while self._queue and self._queue[0] is head:
                    ready = sum(r.rows for r in self._queue
                                if r.group == head.group)
                    if ready >= self.config.max_batch_size \
                            or self._closed:
                        break
                    remaining = close_t - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                if self._queue and self._queue[0] is head:
                    break
                # another worker drained our head while we waited —
                # start over against the current queue front
            take, skipped, total = [], [], 0
            for r in self._queue:
                if r.group == head.group \
                        and total + r.rows <= self.config.max_batch_size:
                    take.append(r)
                    total += r.rows
                else:
                    skipped.append(r)
            self._queue = collections.deque(skipped)
            if _tm.enabled():
                _tm.gauge("serving.queue_depth").set(len(self._queue))
            if skipped:
                self._cond.notify()  # leftover work for another worker
        batch = Batch(take)
        if _tm.enabled():
            _tm.counter("serving.batches").inc()
            _tm.histogram("serving.batch_rows",
                          buckets=_ROWS_BUCKETS).observe(batch.rows)
            _tm.histogram("serving.batch_form_seconds").observe(
                batch.formed_t - head.enqueue_t)
        return batch

    # --------------------------------------------------------- control
    def pending(self):
        with self._cond:
            return len(self._queue)

    @property
    def closed(self):
        return self._closed

    def close(self):
        """Stop admitting; queued work stays drainable by workers."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def fail_pending(self, exc=None):
        """Complete every queued request with an error (non-drain
        shutdown). Returns the number failed."""
        exc = exc or ServerClosed("server shut down before this "
                                  "request ran")
        with self._cond:
            dropped = list(self._queue)
            self._queue.clear()
        for r in dropped:
            r.future.set_error(exc)
        return len(dropped)
