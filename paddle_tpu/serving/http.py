"""Stdlib HTTP frontend for ModelServer.

Endpoints (TF-Serving-shaped):

- ``POST /v1/models/<name>:predict`` — body
  ``{"inputs": {feed: nested list}, "deadline_ms": opt, "version": opt,
  "tenant": opt, "max_new_tokens": opt}``
  (also ``/v1/models/<name>/versions/<v>:predict``); response
  ``{"outputs": [...], "model": name, "version": v}``.

  With ``max_new_tokens`` set and a decode tier attached
  (`ModelServer.attach_decoder`), the request routes to continuous
  decode instead of the fixed-shape batcher: ``inputs`` carries one
  sequence (``{"src": [ids...], "src_len": opt}``) and the response is
  ``{"outputs": [[token ids...]], "finish_reason": "eos"|"length",
  "tenant": t, ...}``. ``tenant`` names the QoS admission class.
- ``GET /healthz`` — 200 ``{"status": "ok"}`` while serving, 503 while
  draining (load balancers stop routing before shutdown completes).
- ``GET /metrics`` — the telemetry registry in Prometheus text format.
- ``GET /v1/models`` — registered names and versions.
- ``GET /v1/farm`` — per-replica stats for every attached decode tier
  that is a replica group (slots in use, queue depth, KV bytes,
  goodput, versions); ``{}`` when serving single engines only.
- ``GET /v1/memory`` — the live device-memory ledger (per-category
  bytes, peaks, per-replica footprints, last OOM post-mortem) when
  ``PADDLE_TPU_MEMLEDGER`` is on; ``{"enabled": false}`` plus raw
  device watermarks otherwise.

Every POST carries a correlation id: ``X-Request-Id`` header or
``request_id`` body field if the caller sent one, generated otherwise.
It is echoed in the response header and in success AND error bodies,
and threaded through the batcher / decode-scheduler spans so a request
can be found on the Chrome timeline by id.

Error mapping keeps overload semantics visible to clients, with a
machine-readable ``kind`` in every error body: queue-full and
oversized requests are 429 ``rejected`` (back off / retry elsewhere),
QoS slot evictions are 429 ``preempted`` (the tenant is over its fair
share right now — distinct from 504 so clients can tell "retry" from
"too slow"), brownout sheds are 429 ``brownout`` and an exhausted
retry budget 429 ``retry_budget`` (the guard tier's typed verdicts),
expired deadlines are 504 ``deadline``, draining is 503, unknown
models 404, malformed bodies 400. Every 429/503 carries a
``Retry-After`` header (seconds, integer-rounded up) so well-behaved
clients and proxies back off instead of hammering — the brownout
controller's ``retry_after_s`` hint when it shed, 1s otherwise.
``GET /healthz`` reports ``"browned_out"`` (still 200 — the balancer
keeps routing, paying tenants still flow) while any attached guard is
shedding. A `ThreadingHTTPServer`
thread-per-connection model is plenty here: the handler only parses
JSON and blocks on a future; the real concurrency story is the
batcher/scheduler, not the socket layer.
"""
import json
import math
import re
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import telemetry as _tm
from .batcher import (BrownoutShed, DeadlineExceeded, PreemptedError,
                      RejectedError, RetryBudgetExhausted,
                      ServerClosed)

__all__ = ["HttpFrontend"]

_PREDICT_RE = re.compile(
    r"^/v1/models/(?P<name>[^/:]+)"
    r"(?:/versions/(?P<version>\d+))?:predict$")


def _coerce_inputs(engine, inputs):
    """JSON nested lists -> numpy arrays with the program's dtypes."""
    if not isinstance(inputs, dict):
        raise ValueError('"inputs" must be an object of '
                         '{feed_name: tensor}')
    specs = engine.feed_specs()
    feed = {}
    for k, v in inputs.items():
        dt = specs.get(k, ((-1,), "float32"))[1]
        feed[k] = np.asarray(v, dtype=np.dtype(dt))
    return feed


class _Handler(BaseHTTPRequestHandler):
    # set by HttpFrontend subclassing
    model_server = None
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):      # quiet by default
        pass

    # per-request correlation id (satellite of tpuscope): accepted via
    # X-Request-Id header or a "request_id" body field, generated
    # otherwise, threaded through batcher/decode spans, and echoed in
    # every success and error body + response header
    _request_id = None

    def _reply(self, code, payload, content_type="application/json",
               retry_after=None):
        body = payload if isinstance(payload, bytes) \
            else json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        if retry_after is not None:
            # RFC 9110 delay-seconds: integer, rounded up so a 0.5s
            # hint never becomes "retry immediately"
            self.send_header("Retry-After",
                             str(max(1, math.ceil(retry_after))))
        if self._request_id:
            self.send_header("X-Request-Id", self._request_id)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _finish_trace(self, status, trigger=None):
        """Complete the request's reqtrace context (no-op when tracing
        is off — the gate is one bool, the module is never imported)."""
        if not (self._request_id and _tm.reqtrace_enabled()):
            return
        rt = _tm.reqtrace
        if trigger:
            rt.flag(self._request_id, trigger)
        rt.trace_end(self._request_id, status=status)

    def _error(self, code, msg, kind=None, retry_after=None):
        if _tm.enabled():
            _tm.counter("serving.http_errors").inc()
        body = {"error": msg}
        if kind:
            body["kind"] = kind
        if self._request_id:
            body["request_id"] = self._request_id
        if retry_after is None and code in (429, 503):
            retry_after = 1.0      # overload always hints a back-off
        self._reply(code, body, retry_after=retry_after)

    def do_GET(self):
        self._request_id = None      # keep-alive reuse: never stale
        if _tm.enabled():
            _tm.counter("serving.http_requests").inc()
        if self.path == "/healthz":
            if self.model_server.healthy:
                status = "browned_out" \
                    if self.model_server.overloaded else "ok"
                self._reply(200, {"status": status})
            else:
                self._reply(503, {"status": "draining"},
                            retry_after=1.0)
        elif self.path == "/metrics":
            self._reply(200, _tm.prometheus_text().encode("utf-8"),
                        content_type="text/plain; version=0.0.4")
        elif self.path == "/v1/models":
            self._reply(200, {"models":
                              self.model_server.registry.models()})
        elif self.path == "/v1/farm":
            farms = {name: dec.stats()
                     for name, dec in
                     self.model_server.decoders().items()
                     if hasattr(dec, "stats")}
            self._reply(200, {"farms": farms})
        elif self.path == "/v1/memory":
            if _tm.memledger_enabled():
                payload = _tm.memledger.snapshot_report()
                rep = _tm.memledger.last_report()
                if rep is not None:
                    payload["last_report"] = rep.to_dict()
            else:
                # ledger off: the device watermarks are all the truth
                # there is (empty on stats-less backends)
                payload = {"enabled": False,
                           "device": _tm.sample_device_memory()}
            self._reply(200, payload)
        elif self.path == "/v1/traces":
            if _tm.reqtrace_enabled():
                self._reply(200, _tm.reqtrace.snapshot())
            else:
                self._reply(200, {"enabled": False, "seen": 0,
                                  "kept": 0, "stored": 0,
                                  "triggers": {}, "traces": []})
        elif self.path.startswith("/v1/traces/"):
            tid = self.path[len("/v1/traces/"):]
            exemplar = (_tm.reqtrace.chrome_trace(tid)
                        if _tm.reqtrace_enabled() else None)
            if exemplar is None:
                self._error(404, f"no captured trace {tid!r}")
            else:
                self._reply(200, exemplar)
        else:
            self._error(404, f"no route {self.path!r}")

    def do_POST(self):
        # header id is captured before body parse so even a 400 for
        # malformed JSON echoes the caller's correlation id
        self._request_id = \
            (self.headers.get("X-Request-Id") or "").strip() or None
        if _tm.enabled():
            _tm.counter("serving.http_requests").inc()
        m = _PREDICT_RE.match(self.path)
        if not m:
            self._error(404, f"no route {self.path!r} (want "
                        f"/v1/models/<name>:predict)")
            return
        name = m.group("name")
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            rid = body.get("request_id") or self._request_id \
                or uuid.uuid4().hex[:16]
            self._request_id = rid = str(rid)
            if _tm.reqtrace_enabled():
                _tm.reqtrace.trace_begin(rid, path=self.path,
                                         model=name)
            version = body.get("version", m.group("version"))
            if body.get("max_new_tokens") is not None:
                with _tm.span("serving.http.predict", model=name,
                              request_id=rid, route="decode"):
                    payload = self._decode_request(name, body, version)
            else:
                engine, version = self.model_server.registry.get(
                    name, version)
                feed = _coerce_inputs(engine, body.get("inputs") or {})
                with _tm.span("serving.http.predict", model=name,
                              request_id=rid, route="batch"):
                    outs = self.model_server.predict(
                        name, feed, version=version,
                        deadline_ms=body.get("deadline_ms"),
                        request_id=rid)
                payload = {
                    "outputs": [np.asarray(o).tolist() for o in outs],
                    "model": name, "version": version,
                    "request_id": rid}
        except KeyError as e:
            self._finish_trace("not_found")
            self._error(404, str(e))
        except DeadlineExceeded as e:
            self._finish_trace("deadline", trigger="deadline")
            self._error(504, str(e), kind="deadline")
        except PreemptedError as e:
            self._finish_trace("preempted")
            self._error(429, str(e), kind="preempted")
        except ServerClosed as e:
            self._finish_trace("draining")
            self._error(503, str(e), kind="draining")
        except BrownoutShed as e:
            self._finish_trace("shed", trigger="shed")
            self._error(429, str(e), kind="brownout",
                        retry_after=e.retry_after_s)
        except RetryBudgetExhausted as e:
            self._finish_trace("retry_budget", trigger="budget")
            self._error(429, str(e), kind="retry_budget")
        except RejectedError as e:
            self._finish_trace("rejected", trigger="shed")
            self._error(429, str(e), kind="rejected")
        except (ValueError, TypeError) as e:
            self._finish_trace("bad_request")
            self._error(400, f"bad request: {e}")
        except Exception as e:              # noqa: BLE001 — last resort
            self._finish_trace("internal")
            self._error(500, f"{type(e).__name__}: {e}")
        else:
            self._finish_trace("ok")
            self._reply(200, payload)

    def _decode_request(self, name, body, version):
        """Continuous-decode leg of the predict route: one sequence
        in, generated token ids out."""
        if self.model_server.decoder(name) is None:
            raise KeyError(f"model {name!r} has no decode tier "
                           f"(max_new_tokens set on a predict-only "
                           f"model?)")
        inputs = body.get("inputs") or {}
        if "src" not in inputs:
            raise ValueError('decode request needs "inputs": '
                             '{"src": [token ids...]}')
        src = np.asarray(inputs["src"], dtype=np.int64).reshape(-1)
        src_len = inputs.get("src_len")
        if src_len is not None:
            src_len = int(np.asarray(src_len).reshape(-1)[0])
        result = self.model_server.decode(
            name, src, src_len=src_len,
            tenant=str(body.get("tenant", "default")),
            max_new_tokens=int(body["max_new_tokens"]),
            deadline_ms=body.get("deadline_ms"),
            request_id=self._request_id)
        return {"outputs": [np.asarray(result.tokens).tolist()],
                "finish_reason": result.finish_reason,
                "tenant": result.tenant,
                "model": name,
                "version": int(version) if version is not None else 1,
                "request_id": self._request_id}


class HttpFrontend:
    """Owns a ThreadingHTTPServer bound to (host, port); port=0 picks
    an ephemeral port (exposed as `.port` once constructed)."""

    def __init__(self, model_server, host="127.0.0.1", port=8500):
        handler = type("BoundHandler", (_Handler,),
                       {"model_server": model_server})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"tpuserve-http:{self.port}", daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self._httpd.serve_forever()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
