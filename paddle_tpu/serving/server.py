"""ModelServer: registry + worker threads + warmup + graceful drain.

One `DynamicBatcher` and a pool of worker threads per registered
(model, version). Workers drain the batcher, drop requests whose
deadline expired while queued, pad the batch to its shape bucket, run
the `InferenceEngine` once, and scatter fetch rows back to callers.

Warmup runs at model load: one zero-feed inference per configured
bucket, so every serving-path signature is compiled *before* the first
real request — traffic never eats a compile stall, and the selftest
gate "compile_count <= bucket count" follows from serving only ever
presenting bucket-shaped batches.
"""
import logging
import threading
import time

import numpy as np

from .. import telemetry as _tm
from ..inference import InferenceEngine
from ..resilience import chaos as _chaos
from .batcher import (BatchConfig, DynamicBatcher, ServerClosed)

_LOG = logging.getLogger("paddle_tpu.serving")

__all__ = ["ModelRegistry", "ModelServer", "ServerConfig"]


class ServerConfig:
    def __init__(self, batch=None, workers=2, default_deadline_ms=None,
                 warmup=True):
        self.batch = batch or BatchConfig()
        self.workers = max(1, int(workers))
        self.default_deadline_ms = default_deadline_ms
        self.warmup = bool(warmup)


class ModelRegistry:
    """name -> version -> InferenceEngine; thread-safe."""

    def __init__(self):
        self._models = {}
        self._lock = threading.Lock()

    def register(self, name, engine, version=None):
        with self._lock:
            versions = self._models.setdefault(name, {})
            if version is None:
                version = max(versions, default=0) + 1
            version = int(version)
            if version in versions:
                raise ValueError(f"model {name!r} version {version} "
                                 f"already registered")
            versions[version] = engine
        return version

    def get(self, name, version=None):
        """Latest version when `version` is None. KeyError with the
        available names/versions on a miss (the HTTP 404 payload)."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise KeyError(f"no model {name!r}; serving "
                               f"{sorted(self._models)}")
            if version is None:
                version = max(versions)
            engine = versions.get(int(version))
            if engine is None:
                raise KeyError(f"model {name!r} has versions "
                               f"{sorted(versions)}, not {version}")
        return engine, int(version)

    def models(self):
        with self._lock:
            return {n: sorted(v) for n, v in self._models.items()}


class _Served:
    """One (name, version)'s batcher + workers."""

    __slots__ = ("name", "version", "engine", "batcher", "threads",
                 "restarts")

    def __init__(self, name, version, engine, batch_config):
        self.name = name
        self.version = version
        self.engine = engine
        self.batcher = DynamicBatcher(batch_config,
                                      name=f"{name}/{version}")
        self.threads = []
        self.restarts = 0       # crashed-worker respawns (observability)


class ModelServer:
    """Serve registered InferenceEngines with dynamic batching."""

    def __init__(self, config=None):
        self.config = config or ServerConfig()
        self.registry = ModelRegistry()
        self._served = {}            # (name, version) -> _Served
        self._decoders = {}          # name -> ContinuousScheduler
        self._lock = threading.Lock()
        self._stopping = False
        self._draining = False

    # ------------------------------------------------------- lifecycle
    def load(self, name, dirname, version=None, place=None,
             analysis_config=None):
        """Load a save_inference_model dir and serve it."""
        engine = InferenceEngine.from_dir(dirname, place=place,
                                          config=analysis_config)
        return self.register(name, engine, version=version)

    def register(self, name, engine, version=None):
        """Register an engine, warm it up, start its workers. Returns
        the assigned version."""
        if self._stopping:
            raise ServerClosed("server is shutting down")
        version = self.registry.register(name, engine, version=version)
        served = _Served(name, version, engine, self.config.batch)
        with self._lock:
            self._served[(name, version)] = served
        if self.config.warmup:
            self.warmup(name, version)
        for i in range(self.config.workers):
            self._spawn_worker(served, i)
        return version

    def warmup(self, name, version=None):
        """Pre-compile every shape bucket with a zero feed. Returns the
        engine's signature count afterwards — with warmup as the first
        caller this equals len(buckets)."""
        engine, version = self.registry.get(name, version)
        specs = engine.feed_specs()
        for b in self.config.batch.buckets:
            shapes = {n: (b,) + tuple(
                d if d != -1 else 1 for d in shape[1:])
                for n, (shape, _dt) in specs.items()}
            with _tm.span("serving.warmup", model=name, bucket=b):
                engine.run(engine._zero_feed(shapes))
            if _tm.enabled():
                _tm.counter("serving.warmup_runs").inc()
        return engine.signature_count()

    def attach_decoder(self, name, decoder, start=True):
        """Attach a continuous-batching decode tier under `name` — a
        `serving.decode.ContinuousScheduler`, or a whole
        `serving.farm.ReplicaGroup` (same duck-typed surface), so one
        registry name fans out across N replicas. Predict requests
        carrying `max_new_tokens` route to it; fixed-shape requests
        keep using the registered InferenceEngine (if any) — a model
        can serve both tiers at once."""
        if self._stopping:
            raise ServerClosed("server is shutting down")
        with self._lock:
            if name in self._decoders:
                raise ValueError(f"model {name!r} already has a "
                                 f"decoder attached")
            self._decoders[name] = decoder
        if start:
            decoder.start()
        return decoder

    def decoder(self, name):
        """The attached decode tier for `name`, or None."""
        with self._lock:
            return self._decoders.get(name)

    def decoders(self):
        """Snapshot of all attached decode tiers (name -> scheduler
        or replica group) — the /v1/farm introspection surface."""
        with self._lock:
            return dict(self._decoders)

    def rolling_update(self, name, params=None, checkpoint_dir=None,
                       version=None, **kw):
        """Rolling weight update on `name`'s replica group: each
        replica drains and flips to the new version in turn while the
        rest keep serving (see `serving.farm.ReplicaGroup
        .rolling_update`). Raises KeyError when `name` has no decode
        tier and TypeError when its decoder is a single scheduler
        (nothing to roll — restart it instead)."""
        decoder = self.decoder(name)
        if decoder is None:
            raise KeyError(f"model {name!r} has no decode tier; "
                           f"decoders: {sorted(self._decoders)}")
        if not hasattr(decoder, "rolling_update"):
            raise TypeError(
                f"decoder for {name!r} is a single engine, not a "
                f"replica group; rolling updates need "
                f"serving.farm.ReplicaGroup")
        return decoder.rolling_update(params=params,
                                      checkpoint_dir=checkpoint_dir,
                                      version=version, **kw)

    def decode(self, name, src, src_len=None, tenant="default",
               max_new_tokens=None, deadline_ms=None, timeout=None,
               request_id=None):
        """Blocking continuous-decode: submit one sequence, wait for
        its `DecodeResult`. KeyError when no decoder is attached (the
        HTTP 404/400 discriminator)."""
        if self._stopping:
            raise ServerClosed("server is draining")
        with self._lock:
            decoder = self._decoders.get(name)
        if decoder is None:
            raise KeyError(f"model {name!r} has no decode tier; "
                           f"decoders: {sorted(self._decoders)}")
        t0 = time.perf_counter()
        if request_id and _tm.reqtrace_enabled():
            _tm.reqtrace.trace_begin(request_id, model=name)
            _tm.reqtrace.event(request_id, "server.decode.submit",
                               model=name, tenant=tenant,
                               max_new_tokens=max_new_tokens)
        future = decoder.submit(src, src_len=src_len, tenant=tenant,
                                max_new_tokens=max_new_tokens,
                                deadline_ms=deadline_ms,
                                request_id=request_id)
        out = future.result(timeout=timeout)
        if _tm.enabled():
            _tm.histogram("serving.decode.request_latency_seconds") \
               .observe(time.perf_counter() - t0)
        return out

    def shutdown(self, drain=True, timeout=30.0):
        """Stop accepting; optionally drain queued work, then join
        workers. With drain=False pending requests fail fast."""
        with self._lock:
            self._stopping = True
            self._draining = drain
            served = list(self._served.values())
            decoders = list(self._decoders.values())
        for s in served:
            s.batcher.close()
            if not drain:
                s.batcher.fail_pending()
        for d in decoders:
            d.stop(drain=drain, timeout=timeout)
        deadline = time.monotonic() + timeout
        for s in served:
            for t in s.threads:
                t.join(max(0.0, deadline - time.monotonic()))

    @property
    def healthy(self):
        return not self._stopping

    @property
    def overloaded(self):
        """True while any attached decode tier's guard is in brownout
        — the /healthz "browned_out" discriminator. Duck-typed: single
        engines and guard-less groups simply have no `guard`."""
        with self._lock:
            decoders = list(self._decoders.values())
        for d in decoders:
            g = getattr(d, "guard", None)
            if g is not None and g.brownout.active:
                return True
        return False

    @property
    def worker_restarts(self):
        """Total crashed-worker respawns across all served models."""
        with self._lock:
            return sum(s.restarts for s in self._served.values())

    # --------------------------------------------------------- serving
    def submit(self, name, feed, version=None, deadline_ms=None,
               request_id=None):
        """Async path: returns (Future, version)."""
        if self._stopping:
            raise ServerClosed("server is draining")
        engine, version = self.registry.get(name, version)
        served = self._served[(name, version)]
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        if request_id and _tm.reqtrace_enabled():
            _tm.reqtrace.trace_begin(request_id, model=name)
            _tm.reqtrace.event(request_id, "server.submit",
                               model=name, version=version)
        return served.batcher.submit(feed, deadline_ms=deadline_ms,
                                     request_id=request_id), \
            version

    def predict(self, name, feed, version=None, deadline_ms=None,
                timeout=None, request_id=None):
        """Blocking convenience: submit + wait. Returns the fetch list
        (numpy arrays, rows matching the request's batch dim)."""
        t0 = time.perf_counter()
        future, _version = self.submit(name, feed, version=version,
                                       deadline_ms=deadline_ms,
                                       request_id=request_id)
        outs = future.result(timeout=timeout)
        if _tm.enabled():
            _tm.histogram("serving.request_latency_seconds").observe(
                time.perf_counter() - t0)
        return outs

    # ---------------------------------------------------------- worker
    def _spawn_worker(self, served, idx):
        t = threading.Thread(
            target=self._worker_guarded, args=(served, idx),
            name=f"tpuserve-{served.name}/{served.version}-{idx}",
            daemon=True)
        t.start()
        served.threads.append(t)

    def _worker_guarded(self, served, idx):
        """Supervisor shell: a worker that dies to anything but a
        clean drain is respawned, so a single thread crash degrades
        one batch instead of silently losing 1/N of the model's
        serving capacity forever. Respawns are counted in
        serving.worker_restarts (surfaced in /metrics)."""
        try:
            self._worker(served)
        except BaseException as e:          # noqa: BLE001 — thread death
            if self._stopping:
                return
            served.restarts += 1
            if _tm.enabled():
                _tm.counter("serving.worker_restarts").inc()
            _LOG.warning(
                "tpuserve worker %s/%s-%d died (%s: %s) — restarting",
                served.name, served.version, idx, type(e).__name__, e)
            self._spawn_worker(served, idx)

    def _worker(self, served):
        batcher = served.batcher
        while True:
            batch = batcher.next_batch(timeout=0.05)
            if batch is None:
                if batcher.closed and batcher.pending() == 0:
                    return
                continue
            try:
                # chaos serving.worker point: counted per dequeued
                # batch (deterministic), not per idle poll (timing)
                if _chaos.armed():
                    _chaos.check(
                        "serving.worker",
                        detail=f"worker {served.name}/{served.version}")
                self._run_batch(served, batch)
            except Exception as e:
                # per-batch errors are handled inside _run_batch; an
                # exception HERE is worker-fatal (e.g. injected crash):
                # fail the in-flight batch so callers see an error
                # instead of a deadline hang, then die -> respawned
                batch.fail(e)
                raise

    def _run_batch(self, served, batch):
        batch.drop_expired()
        if not batch.requests:
            return
        try:
            padded, true_rows, bucket = batch.assemble(
                served.batcher.config.buckets)
            with _tm.span("serving.batch", model=served.name,
                          rows=true_rows, bucket=bucket,
                          requests=len(batch.requests),
                          request_ids=[r.request_id
                                       for r in batch.requests
                                       if r.request_id] or None):
                outs = served.engine.run(padded)
            if _tm.reqtrace_enabled():
                for r in batch.requests:
                    if r.request_id:
                        _tm.reqtrace.event(
                            r.request_id, "batch.run", rows=true_rows,
                            bucket=bucket, model=served.name)
            if _tm.enabled():
                _tm.counter("serving.batch_rows_total").inc(true_rows)
                _tm.counter("serving.pad_rows_total").inc(
                    bucket - true_rows)
            batch.scatter(outs, bucket)
        except Exception as e:            # noqa: BLE001 — to callers
            if _tm.enabled():
                _tm.counter("serving.batch_errors").inc()
            batch.fail(e)
