"""ScaleController: the loop that closes tpuscope -> tpuguard ->
tpufarm into traffic-proportional capacity.

Each `tick()`:

1. builds the signal snapshot (queue depth, deadline-miss EWMA,
   goodput, free-slot ratio — `policy.SIGNALS`) from the live group,
2. evaluates the policy's triggers under dwell + cooldown flap
   control,
3. hands "up"/"down" to the `ScalePlanner` (verify-gated grow through
   the shared build cache / drain-then-release shrink),
4. relays headroom to tpuguard: while another exclusive slice exists
   below `max_replicas`, brownout entry is DEFERRED (scale-out beats
   shedding); at the ceiling the deferral lifts and shedding is the
   correct last resort,
5. publishes `scale.*` telemetry for tpustat --fleet/--watch and the
   fleet rollup.

Drive it either way: `start(interval_s)` runs a daemon loop;
`tick()` by hand is the deterministic mode every test and the
`--selftest-scale` gate use (same discipline as the farm's
run_iteration). Attaching sets `group.scale = self`, so
`group.stats()` carries the controller's view without the farm ever
importing this package.
"""
import threading
import time

from ... import telemetry as _tm
from .planner import ScalePlanner, ScalePlanRejected
from .policy import ScalePolicy

__all__ = ["ScaleController", "ScaleDecision", "DECISION_CODES"]

# gauge encoding for scale.last_decision (tpustat decodes it)
DECISION_CODES = {"hold": 0.0, "up": 1.0, "down": 2.0, "ceiling": 3.0,
                  "rejected": 4.0, "cooldown": 5.0}


class ScaleDecision:
    """One tick's verdict: what happened and why."""

    __slots__ = ("action", "reason", "rule", "target", "live",
                 "at_ceiling")

    def __init__(self, action, reason, rule=None, target=None,
                 live=None, at_ceiling=False):
        self.action = action        # a DECISION_CODES key
        self.reason = reason        # human-readable why
        self.rule = rule            # policy rule index, or None
        self.target = target
        self.live = live
        self.at_ceiling = at_ceiling

    def to_dict(self):
        return {"action": self.action, "reason": self.reason,
                "rule": self.rule, "target": self.target,
                "live": self.live, "at_ceiling": self.at_ceiling}

    def __repr__(self):
        return (f"ScaleDecision({self.action}, {self.reason!r}, "
                f"live={self.live}->{self.target})")


class ScaleController:
    """SLO-driven autoscaler for one ReplicaGroup."""

    def __init__(self, group, policy, planner=None,
                 clock=time.monotonic):
        if not isinstance(policy, ScalePolicy):
            policy = ScalePolicy(policy)
        self.group = group
        self.policy = policy
        self.planner = planner or ScalePlanner(group)
        self._clock = clock
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self._cooldown_until = 0.0
        self._up_streak = 0
        self._down_streak = 0
        self._last = ScaleDecision("hold", "no tick yet",
                                   live=len(group.replicas),
                                   target=len(group.replicas))
        self.ticks = 0
        self.decisions = {"up": 0, "down": 0, "hold": 0,
                          "ceiling": 0, "rejected": 0, "cooldown": 0}
        group.scale = self          # farm stats pick this up, no import

    # -------------------------------------------------------- signals
    def signals(self):
        """The fleet-shaped snapshot policy conditions read (see
        policy.SIGNALS for the vocabulary)."""
        g = self.group
        live = len(g.replicas)
        slots = g.num_slots
        free = g.free_slots
        miss = 0.0
        if g.guard is not None:
            miss = g.guard.brownout.miss_ewma
        goodput = 0.0
        for r in list(g.replicas):
            goodput += g._goodput(r)
        return {
            "queue_depth": float(g.queued),
            "queue_per_replica": g.queued / max(1, live),
            "free_slot_ratio": free / max(1, slots),
            "miss_ewma": float(miss),
            "goodput_tps": float(goodput),
            "replicas": float(live),
        }

    # ----------------------------------------------------------- tick
    def tick(self, drive=False):
        """One evaluate-and-maybe-act pass. Returns the
        ScaleDecision. `drive=True` pumps the group's run_iteration
        while a shrink drains (manual/deterministic mode)."""
        sig = self.signals()
        live = len(self.group.replicas)
        now = self._clock()
        self.ticks += 1
        up_i, up_rule = self.policy.first_triggered("up", sig)
        down_i, down_rule = self.policy.first_triggered("down", sig)
        with self._lock:
            self._up_streak = self._up_streak + 1 \
                if up_rule is not None else 0
            # an up-trigger vetoes any down-dwell in progress
            self._down_streak = self._down_streak + 1 \
                if down_rule is not None and up_rule is None else 0
            up_streak, down_streak = self._up_streak, self._down_streak
            cooling = now < self._cooldown_until

        decision = None
        if up_rule is not None and up_streak >= self.policy.up_dwell:
            decision = self._try_grow(up_i, up_rule, live, cooling)
        elif down_rule is not None \
                and down_streak >= self.policy.down_dwell:
            decision = self._try_shrink(down_i, down_rule, live,
                                        cooling, drive)
        if decision is None:
            decision = ScaleDecision(
                "hold", "no trigger", live=live, target=live,
                at_ceiling=self._ceiling(live))
        self._settle(decision)
        return decision

    def _ceiling(self, live):
        """At the ceiling = the next grow is impossible, by policy
        bound or by physical device exhaustion."""
        return (live >= self.policy.max_replicas
                or self.planner.at_ceiling())

    def _try_grow(self, i, rule, live, cooling):
        target = min(live + rule.step, self.policy.max_replicas)
        if cooling and target > live:
            return ScaleDecision(
                "cooldown", f"up trigger {rule.text!r} held by "
                f"cooldown", rule=i, live=live, target=live,
                at_ceiling=self._ceiling(live))
        if target <= live or self.planner.at_ceiling():
            # wanted to grow, can't: THE ceiling moment — brownout
            # deferral lifts (see _settle -> headroom False)
            return ScaleDecision(
                "ceiling", f"up trigger {rule.text!r} at the "
                f"device ceiling (live={live}, "
                f"max={self.policy.max_replicas}, free="
                f"{self.planner.free_devices()})", rule=i,
                live=live, target=live, at_ceiling=True)
        try:
            self.planner.grow(target - live)
        except ScalePlanRejected as e:
            # "measured" = the memory ledger ruled the grow out — that
            # is a ceiling for the brownout headroom relay too: another
            # slice physically won't fit, so shedding is allowed
            return ScaleDecision(
                "rejected", f"grow to {target} rejected: {e}",
                rule=i, live=live, target=live,
                at_ceiling=e.reason in ("ceiling", "measured"))
        with self._lock:
            self._cooldown_until = (self._clock()
                                    + self.policy.up_cooldown_s)
            self._up_streak = 0
        return ScaleDecision(
            "up", f"{rule.text!r} grew {live}->{target}", rule=i,
            live=len(self.group.replicas), target=target,
            at_ceiling=self._ceiling(target))

    def _try_shrink(self, i, rule, live, cooling, drive):
        target = max(live - rule.step, self.policy.min_replicas)
        if target >= live:
            return None            # already at the floor: plain hold
        if cooling:
            return ScaleDecision(
                "cooldown", f"down trigger {rule.text!r} held by "
                f"cooldown", rule=i, live=live, target=live,
                at_ceiling=self._ceiling(live))
        self.planner.shrink(live - target, drive=drive)
        with self._lock:
            self._cooldown_until = (self._clock()
                                    + self.policy.down_cooldown_s)
            self._down_streak = 0
        live_now = len(self.group.replicas)
        return ScaleDecision(
            "down", f"{rule.text!r} shrank {live}->{live_now}",
            rule=i, live=live_now, target=target,
            at_ceiling=self._ceiling(live_now))

    def _settle(self, decision):
        """Bookkeeping every tick ends with: decision counters, the
        guard headroom relay, telemetry."""
        with self._lock:
            self._last = decision
        self.decisions[decision.action] = \
            self.decisions.get(decision.action, 0) + 1
        if self.group.guard is not None:
            # headroom == another slice exists below the ceiling;
            # False exactly when the planner/policy report the ceiling
            self.group.guard.set_scale_headroom(
                not decision.at_ceiling)
        self.publish(decision)

    # ------------------------------------------------------ loop mode
    def start(self, interval_s=0.5):
        """Background control loop (daemon). Manual tick() keeps
        working — the lock serializes transitions."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception:       # noqa: BLE001 — keep looping
                    import logging
                    logging.getLogger(
                        "paddle_tpu.serving.scale").exception(
                        "scale tick failed")

        self._thread = threading.Thread(
            target=loop, name="tpuscale", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)

    # ------------------------------------------------------ telemetry
    def cooldown_remaining_s(self):
        with self._lock:
            return max(0.0, self._cooldown_until - self._clock())

    @property
    def last_decision(self):
        with self._lock:
            return self._last

    def stats(self):
        last = self.last_decision
        return {"policy": self.policy.describe(),
                "ticks": self.ticks,
                "decisions": dict(self.decisions),
                "live_replicas": len(self.group.replicas),
                "target_replicas": last.target,
                "last": last.to_dict(),
                "cooldown_remaining_s": round(
                    self.cooldown_remaining_s(), 3),
                "planner": self.planner.stats()}

    def publish(self, decision=None):
        if not _tm.enabled():
            return
        last = decision or self.last_decision
        _tm.gauge("scale.live_replicas").set(
            float(len(self.group.replicas)))
        _tm.gauge("scale.target_replicas").set(
            float(last.target if last.target is not None
                  else len(self.group.replicas)))
        _tm.gauge("scale.last_decision").set(
            DECISION_CODES.get(last.action, 0.0))
        _tm.gauge("scale.last_rule").set(
            -1.0 if last.rule is None else float(last.rule))
        _tm.gauge("scale.at_ceiling").set(
            1.0 if last.at_ceiling else 0.0)
        _tm.gauge("scale.cooldown_remaining_s").set(
            self.cooldown_remaining_s())
        _tm.gauge("scale.free_devices").set(
            float(self.planner.free_devices()))
        _tm.counter("scale.ticks").inc()
        if last.action in ("up", "down"):
            _tm.counter(f"scale.{last.action}s").inc()
            _tm.instant_event(
                "scale.transition", farm=self.group.name,
                action=last.action, reason=last.reason,
                live=len(self.group.replicas))
