"""ScalePolicy: tpuscope's SLO-rule grammar, extended with actions.

A tpuscope rule (`telemetry.slo`) is an assertion — ``"step_ms.p99 <
250"`` PASSES or FAILS. A scale rule is a *trigger*: the same
``metric[.stat] OP value`` condition syntax, plus an arrow naming what
to do when the condition HOLDS::

    "queue_per_replica > 6 -> up"        # grow by 1
    "queue_per_replica > 20 -> up:2"     # grow by 2 (steeper surge)
    "free_slot_ratio > 0.8 -> down"      # shrink by 1

Conditions are evaluated against the controller's signal snapshot —
the fleet-merge-shaped dict of serving signals (`SIGNALS` below), not
the raw metric registry, so a policy reads the same whether the
controller watches a live group or a fleet report.

Flap control is structural, not advisory:

- **hysteresis bands** — up and down conditions are separate rules,
  so the quiet band between their thresholds is explicit in the
  policy text;
- **dwell (consecutive-evaluation) hysteresis** — `up_dwell` /
  `down_dwell` require a rule to hold for that many *consecutive*
  controller ticks before acting (down defaults to 3: growing is
  urgent, shrinking is a savings optimization that can wait);
- **cooldowns** — `up_cooldown_s` / `down_cooldown_s` freeze further
  action after a transition so its effect is measurable before the
  next decision;
- **bounds** — `min_replicas` / `max_replicas` clamp every target;
  `max_replicas` is the policy's share of the device ceiling (the
  planner may report a lower, physical one).
"""
from ...telemetry.slo import _OPS, parse_rule

__all__ = ["ScaleRule", "ScalePolicy", "SIGNALS", "parse_scale_rule"]

# the signal vocabulary scale conditions are written against; the
# controller builds this snapshot each tick (see
# ScaleController.signals)
SIGNALS = {
    "queue_depth": "total queued requests across the group",
    "queue_per_replica": "queue_depth / live replicas",
    "free_slot_ratio": "free decode slots / total slots (0..1)",
    "miss_ewma": "deadline-miss EWMA from the guard's brownout "
                 "controller (0 without a guard)",
    "goodput_tps": "group tokens/s (sum of per-replica goodput)",
    "replicas": "live replica count",
}


class ScaleRule:
    """One parsed trigger: a tpuscope condition + an action."""

    __slots__ = ("text", "rule", "action", "step")

    def __init__(self, text, rule, action, step):
        self.text = text
        self.rule = rule          # telemetry.slo.Rule (the condition)
        self.action = action      # "up" | "down"
        self.step = step          # replicas per firing

    def triggered(self, signals):
        """Does the condition HOLD against this snapshot? Missing
        signals never trigger (a policy watching guard-only signals
        stays quiet on a guardless group)."""
        val = signals.get(self.rule.metric)
        if val is None:
            return False
        return _OPS[self.rule.op](float(val) * self.rule.scale,
                                  self.rule.threshold)

    def __repr__(self):
        return f"ScaleRule({self.text!r})"


def parse_scale_rule(text):
    """``"cond -> up[:step]"`` -> ScaleRule. The condition half reuses
    `telemetry.slo.parse_rule` verbatim — one grammar, two engines."""
    cond, sep, act = text.partition("->")
    if not sep:
        raise ValueError(
            f"bad scale rule {text!r}: want 'metric[.stat] OP value "
            f"-> up|down[:step]'")
    act = act.strip()
    action, _, step_s = act.partition(":")
    action = action.strip()
    if action not in ("up", "down"):
        raise ValueError(
            f"bad scale rule {text!r}: action {action!r} not in "
            f"('up', 'down')")
    try:
        step = int(step_s) if step_s.strip() else 1
    except ValueError:
        raise ValueError(
            f"bad scale rule {text!r}: step {step_s!r} is not an int")
    if step < 1:
        raise ValueError(
            f"bad scale rule {text!r}: step must be >= 1")
    rule = parse_rule(cond)
    if rule.stat != "value":
        raise ValueError(
            f"bad scale rule {text!r}: scale signals are scalars "
            f"(no .{rule.stat} statistics)")
    return ScaleRule(text.strip(), rule, action, step)


class ScalePolicy:
    """The declarative half of tpuscale: triggers + flap control.

    rules: scale-rule strings (or ScaleRule objects). Up rules are
        checked first and win ties — under pressure, growing beats
        shrinking.
    min_replicas / max_replicas: hard bounds on every target.
    up_cooldown_s / down_cooldown_s: freeze after a grow / shrink.
    up_dwell / down_dwell: consecutive triggering ticks required
        before acting.
    """

    def __init__(self, rules, min_replicas=1, max_replicas=4,
                 up_cooldown_s=5.0, down_cooldown_s=30.0,
                 up_dwell=1, down_dwell=3):
        self.rules = [r if isinstance(r, ScaleRule)
                      else parse_scale_rule(r) for r in rules]
        if not self.rules:
            raise ValueError("a ScalePolicy needs at least one rule")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.up_dwell = int(up_dwell)
        self.down_dwell = int(down_dwell)
        if self.up_dwell < 1 or self.down_dwell < 1:
            raise ValueError("dwell counts must be >= 1")

    def first_triggered(self, action, signals):
        """(rule_index, ScaleRule) of the first `action` rule whose
        condition holds, or (None, None)."""
        for i, sr in enumerate(self.rules):
            if sr.action == action and sr.triggered(signals):
                return i, sr
        return None, None

    def describe(self):
        return {"rules": [sr.text for sr in self.rules],
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "up_cooldown_s": self.up_cooldown_s,
                "down_cooldown_s": self.down_cooldown_s,
                "up_dwell": self.up_dwell,
                "down_dwell": self.down_dwell}
