"""ScalePlanner: decisions -> safe transitions on a live ReplicaGroup.

The controller says "grow" or "shrink"; this class owns HOW:

- **grow** re-runs the farm's pre-spawn verify gate at the NEW count
  (`FarmConfig.verify` -> meshlint device-footprint pass, so a plan
  whose per-replica KV bytes exceed ``PADDLE_TPU_DEVICE_MEM_CAP`` is
  rejected with the same typed diagnostics a bad static config gets),
  takes a device slice from the `SliceAllocator` ledger, and calls
  `group.add_replica` — which warms the new replica through the
  SharedBuildCache, so a same-config grow compiles nothing new.
  Weights default to the group's current version; a PR-11
  topology-independent `checkpoint_dir` spawns from disk instead.
- **shrink** drains the least-loaded replica to empty through the
  group's rolling-update discipline (`group.remove_replica`) and
  returns its slice to the ledger for the next grow.
- **ceiling** (`at_ceiling`) is the physical truth the controller
  relays to tpuguard: no free devices for another exclusive slice (or
  the policy max). Below it, brownout entry is deferred — scale-out
  beats shedding; at it, shedding is correct and allowed.

With the memory ledger on (`PADDLE_TPU_MEMLEDGER=1`) both gates also
consult MEASURED per-replica bytes: a grow whose measured replica peak
exceeds the per-device cap is rejected with reason ``"measured"`` even
when the static floor fits, and `at_ceiling` flips true — so the
brownout headroom relay runs on truth, not just the prediction.

The allocator ledger is seeded lazily from the group's own slices
(`adopt`), so an unscaled group never constructs one — and a
wrap-shared CPU layout adopts as shared, keeping free() honest.
"""
import threading

from ...parallel.mesh import SliceAllocator
from ... import telemetry as _tm

__all__ = ["ScalePlanner", "ScalePlanRejected"]


class ScalePlanRejected(RuntimeError):
    """A grow plan failed the pre-spawn gate (footprint over the
    device cap, no devices free, or policy bounds). `.reason` is the
    short machine tag, the message carries the diagnostics."""

    def __init__(self, reason, detail=""):
        self.reason = reason
        super().__init__(detail or reason)


class ScalePlanner:
    """Transition executor for one ReplicaGroup."""

    def __init__(self, group, devices=None, width=None, verify=True,
                 checkpoint_dir=None, measured_bytes=None):
        self.group = group
        self.verify = bool(verify)
        self.checkpoint_dir = checkpoint_dir
        self._lock = threading.Lock()
        self._alloc = None
        self._devices = devices     # explicit universe (default: the
        self._width = width         # group's), slice width (default:
        self.grows = 0              # the group's existing width)
        self.shrinks = 0
        self.rejections = 0
        # () -> peak bytes a replica was MEASURED to occupy, or None
        # when unknown. Default: the memory ledger's per-replica peaks
        # (only when PADDLE_TPU_MEMLEDGER is on — off-path never
        # imports the ledger). Injectable for tests/selftest.
        self._measured_bytes = measured_bytes

    # ------------------------------------------------------- measured
    def measured_replica_peak(self):
        """Largest measured per-replica footprint in bytes, or None
        when no measurement exists (ledger off / nothing sampled)."""
        if self._measured_bytes is not None:
            try:
                v = self._measured_bytes()
                return int(v) if v else None
            except Exception:
                return None
        if not _tm.memledger_enabled():
            return None
        from ...telemetry import memledger as _ml
        peaks = _ml.replica_peaks()
        return max(peaks.values()) if peaks else None

    def _measured_overrun(self):
        """(peak, cap) when measured bytes rule out another replica on
        a fresh slice; None otherwise."""
        peak = self.measured_replica_peak()
        if peak is None:
            return None
        if not _tm.memledger_enabled() and self._measured_bytes is None:
            return None
        if _tm.memledger_enabled():
            from ...telemetry import memledger as _ml
            cap = _ml.device_cap_bytes()
        else:
            cap = self._env_cap_bytes()
        if cap and peak > cap:
            return peak, cap
        return None

    @staticmethod
    def _env_cap_bytes():
        import os
        env = os.environ.get("PADDLE_TPU_DEVICE_MEM_CAP")
        if not env:
            return None
        try:
            return int(float(env) * (1 << 20))
        except ValueError:
            return None

    # ------------------------------------------------------ allocator
    def _allocator(self):
        """Build the ledger on first use: universe = the group's
        device config (or an explicit list), minus the prefill
        reserve; existing replica slices are adopted so shrink can
        free them."""
        with self._lock:
            if self._alloc is not None:
                return self._alloc
            devices = self._devices
            if devices is None:
                devices = self.group.config.devices
            alloc = SliceAllocator(
                devices=devices,
                reserve=len(self.group.prefill_devices))
            for r in list(self.group.replicas):
                alloc.adopt(r.devices)
            if self._width is None:
                widths = [len(r.devices)
                          for r in list(self.group.replicas)]
                self._width = max(1, min(widths) if widths else 1)
            self._alloc = alloc
            return alloc

    @property
    def width(self):
        self._allocator()
        return self._width

    # -------------------------------------------------------- ceiling
    def at_ceiling(self, extra=1):
        """No room for `extra` more exclusive slices: the physical
        device ceiling (policy bounds are the controller's job). THIS
        is the signal that flips brownout from deferred to allowed.

        Measured memory counts as ceiling too: when the ledger has
        seen a replica peak past the per-device cap, another slice
        would not actually fit, whatever the allocator says."""
        alloc = self._allocator()
        if alloc.free_count() < self.width * extra:
            return True
        return self._measured_overrun() is not None

    def free_devices(self):
        return self._allocator().free_count()

    # ----------------------------------------------------------- grow
    def grow(self, n=1, params=None, checkpoint_dir=None):
        """Spawn `n` replicas. Verify-gate first, allocate second,
        spawn third — a rejected plan changes nothing. Returns the new
        Replica list; raises ScalePlanRejected on gate failure or
        device exhaustion."""
        alloc = self._allocator()
        group = self.group
        if self.verify:
            import copy
            probe = copy.copy(group.config)
            probe.replicas = len(group.replicas) + int(n)
            try:
                probe.verify(
                    devices=list(alloc.reserved) + list(alloc.pool),
                    model_config=group.model_cfg,
                    raise_on_error=True)
            except Exception as e:
                self.rejections += 1
                raise ScalePlanRejected(
                    "verify", f"pre-spawn gate rejected the grow to "
                    f"{probe.replicas} replicas: {e}") from e
        over = self._measured_overrun()
        if over is not None:
            peak, cap = over
            self.rejections += 1
            raise ScalePlanRejected(
                "measured", f"measured per-replica peak {peak} bytes "
                f"exceeds the per-device cap {cap} bytes — the static "
                f"floor fit, the runtime ledger says a new replica "
                f"won't (shrink the KV cache / kv_quant=int8 first)")
        if alloc.free_count() < self.width * int(n):
            self.rejections += 1
            raise ScalePlanRejected(
                "ceiling", f"device ceiling: want {n} slice(s) of "
                f"width {self.width}, only {alloc.free_count()} "
                f"device(s) free")
        new = []
        ckpt = checkpoint_dir if checkpoint_dir is not None \
            else self.checkpoint_dir
        for _ in range(int(n)):
            slc = alloc.alloc(self.width)
            try:
                rep = group.add_replica(
                    slc, params=params,
                    checkpoint_dir=None if params is not None
                    else ckpt)
            except Exception:
                alloc.free(slc)     # failed spawn leaks no devices
                raise
            new.append(rep)
            self.grows += 1
        return new

    # --------------------------------------------------------- shrink
    def shrink(self, n=1, drain_timeout=30.0, drive=False):
        """Drain-then-release `n` replicas; freed slices rejoin the
        ledger. Returns the number actually removed (the group refuses
        to drop below one)."""
        alloc = self._allocator()
        removed = 0
        for _ in range(int(n)):
            if len(self.group.replicas) <= 1:
                break
            devices = self.group.remove_replica(
                drain_timeout=drain_timeout, drive=drive)
            alloc.free(devices)
            self.shrinks += 1
            removed += 1
        return removed

    def stats(self):
        alloc = self._allocator()
        return {"grows": self.grows, "shrinks": self.shrinks,
                "rejections": self.rejections,
                "free_devices": alloc.free_count(),
                "slice_width": self.width,
                "at_ceiling": self.at_ceiling(),
                "measured_replica_peak":
                    self.measured_replica_peak() or 0}
