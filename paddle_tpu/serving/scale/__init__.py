"""tpuscale: SLO-driven autoscaling for the serving farm.

The control loop the rest of the serving stack was built for: tpuscope
measures, tpuguard defends, tpuelastic re-shards, tpufarm routes — and
this package turns the knob. A `ScaleController` watches the group's
live signals through declarative `ScalePolicy` rules (tpuscope's
SLO grammar plus ``-> up/down`` actions, cooldowns, dwell hysteresis)
and a `ScalePlanner` executes verified transitions: grow through the
SharedBuildCache onto ledgered device slices (zero new compiles),
shrink by drain-then-release, shed only at the device ceiling.

Minimal session::

    from paddle_tpu.serving.scale import ScaleController, ScalePolicy

    policy = ScalePolicy(
        ["queue_per_replica > 6 -> up",
         "free_slot_ratio > 0.8 -> down"],
        min_replicas=1, max_replicas=4)
    ctl = ScaleController(group, policy).start(interval_s=0.5)

Strictly opt-in: a farm without a controller NEVER imports this
package and routes byte-identically to PR 17 — pinned by the bench
contract, like guard/farm/kern before it.
"""
from .controller import DECISION_CODES, ScaleController, ScaleDecision
from .planner import ScalePlanner, ScalePlanRejected
from .policy import SIGNALS, ScalePolicy, ScaleRule, parse_scale_rule

__all__ = ["ScaleController", "ScaleDecision", "ScalePlanner",
           "ScalePlanRejected", "ScalePolicy", "ScaleRule",
           "parse_scale_rule", "SIGNALS", "DECISION_CODES"]
