"""paddle_tpu.serving — dynamic-batching model server.

The TF-Serving-shaped answer to "every distinct input signature is a
fresh XLA compile": requests are coalesced by a `DynamicBatcher` into
batches padded to a fixed set of **shape buckets** (so the
per-signature jit cache in `paddle_tpu.inference` is actually hit),
run by `ModelServer` worker threads against a `ModelRegistry` of
`InferenceEngine`s, and exposed over a stdlib HTTP frontend
(`POST /v1/models/<name>:predict`, `GET /healthz`, `GET /metrics`).

Overload is handled by **admission control**, not by queueing: the
request queue is bounded (submits beyond it fail fast with
`RejectedError`) and every request can carry a deadline (expired
requests are dropped with `DeadlineExceeded` instead of being
computed). Every queue/batch/reject/warmup event lands in the
`paddle_tpu.telemetry` registry when telemetry is enabled.

Autoregressive traffic gets its own tier: `paddle_tpu.serving.decode`
(tpudecode) does continuous (iteration-level) batching over a
static-shape KV-cache slot pool with weighted-fair-queuing multi-tenant
QoS — attach one to a served model with `ModelServer.attach_decoder`
and drive it over HTTP via the predict route's `max_new_tokens` /
`tenant` fields. The decode package is imported lazily: servers that
never attach a decoder never pay for it (pinned by the bench
contract).

Scale-out stacks on top, each tier opt-in and lazily imported (the
bench contract pins that unused tiers are never even imported):
`serving.farm` replicates the decode tier behind a least-loaded
router, `serving.guard` adds overload defense (health probation,
hedging, brownout), and `serving.scale` (tpuscale) closes the control
loop — SLO-rule-driven grow/shrink of the replica group, shedding
only at the device ceiling.

`tools/tpuserve.py` is the CLI: serve a `save_inference_model` dir,
load-test it (`--bench`, `--bench-decode`), or run the CI self-tests
(`--selftest`, `--selftest-decode`, ... `--selftest-scale`).
"""
from .batcher import (BatchConfig, DynamicBatcher, Future,
                      RejectedError, DeadlineExceeded, PreemptedError,
                      ServerClosed, CancelledError,
                      RetryBudgetExhausted, BrownoutShed)
from .server import ModelRegistry, ModelServer, ServerConfig
from .http import HttpFrontend

__all__ = ["BatchConfig", "DynamicBatcher", "Future", "RejectedError",
           "DeadlineExceeded", "PreemptedError", "ServerClosed",
           "CancelledError", "RetryBudgetExhausted", "BrownoutShed",
           "ModelRegistry", "ModelServer", "ServerConfig",
           "HttpFrontend"]
