"""tpufarm replica groups: N decode engines over disjoint device
slices behind one least-loaded router.

The decode tier (serving/decode) is one engine: one slot pool, one
device set, one scheduler loop. This module is the scale-out layer
above it — the piece of the reference's Paddle Serving / pserver fleet
story rebuilt TPU-native:

- **Replica groups.** `ReplicaGroup` instantiates N `DecodeEngine`s
  over disjoint device slices (`parallel.mesh.device_slices`), each
  with its own `ContinuousScheduler`, and routes submissions through a
  `LeastLoadedRouter` scoring free slots against queue depth. The
  group duck-types the scheduler surface (`submit` / `decode` /
  `start` / `stop` / `queued`), so `ModelServer.attach_decoder(name,
  group)` serves a whole fleet under one registry name and the HTTP
  `max_new_tokens` route works unchanged.

- **Disaggregated prefill.** With `prefill_devices=k`, the first k
  devices are reserved as a prefill pool: each replica's encoder
  executables are pinned there and the prefilled KV state is handed
  device-to-device into the replica's slot pool
  (`DecodeEngine._handoff`), so a long prompt's prefill never stalls
  another replica's token loop. Every replica keeps its OWN prefill
  decoder instance (possibly sharing a physical device) so rolling
  updates swap prefill+decode weights atomically per replica.

- **Crash containment.** A replica whose loop dies (e.g. the
  `worker_crash` chaos fault) fails its in-flight futures and is
  skipped by the router until its supervisor respawns it; the
  `GroupFuture` wrapper resubmits crash-failed requests to another
  replica, so the GROUP drops zero requests through a
  one-replica-down window.

- **Rolling weight updates.** `rolling_update` drains one replica at
  a time (router skips it, in-flight work finishes), swaps its
  parameter set under the compiled executables (same shapes -> zero
  recompile), bumps its version, and moves on — the group serves both
  versions mid-update and never stops serving.

- **Shared compiles.** Same-config replicas share jit traces through
  `SharedBuildCache` (single-flight: concurrent warmups build once,
  waiters block), so group warmup cost is per GROUP, not per replica
  — `ReplicaGroup.compile_count` pins the cache's build count.

- **Elastic membership.** `add_replica` / `remove_replica` grow and
  shrink the routed set mid-flight: a joining replica warms up through
  the shared build cache (same config -> zero new compiles) and serves
  the group's current weight version; a leaving replica drains to
  empty first (the rolling-update discipline) and hands its device
  slice back. Replica indices are monotonic — never reused — so
  telemetry and health rows stay unambiguous across cycles. The
  POLICY for when to do either lives above, in `serving.scale`
  (tpuscale), which this module never imports.

- **Overload defense (opt-in).** `FarmConfig(guard=GuardConfig(...))`
  attaches a `serving.guard.GroupGuard`: per-replica health probation
  / ejection / half-open probing consulted by the router, hedged
  requests (re-issue at the live p99, loser cancelled and its slot
  reclaimed), a retry budget shared by hedges and crash
  resubmissions, and brownout shedding of the lowest QoS class. A
  group WITHOUT a guard never imports the package and routes exactly
  as before — pinned by the bench contract.

Telemetry lands under ``serving.replica.<i>.*`` gauges plus
``serving.farm.*`` rollups, consumed by tpustat --watch/--fleet and
the fleet report.
"""
import logging
import threading
import time

import numpy as np

from ... import telemetry as _tm
from ...parallel.mesh import device_slices
from ...resilience import chaos as _chaos
from ..batcher import (DeadlineExceeded, PreemptedError, RejectedError,
                       RetryBudgetExhausted, ServerClosed)
from ..decode import (ContinuousScheduler, DecodeConfig, DecodeEngine,
                      DecodeEngineConfig)
from .router import LeastLoadedRouter

_LOG = logging.getLogger("paddle_tpu.serving.farm")

__all__ = ["FarmConfig", "Replica", "ReplicaGroup", "SharedBuildCache",
           "GroupFuture", "load_checkpoint_params"]


class SharedBuildCache:
    """Single-flight jit-build sharing across same-config replicas.

    `get_or_build(key, build)` returns ``(fn, built)``: the first
    caller for a key runs `build` while concurrent callers for the
    same key wait on its completion instead of duplicating the trace
    (the inference-engine compile-lock discipline, applied across
    decoder instances). `builds` is the group-level compile count the
    selftest pins."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fns = {}
        self._inflight = {}     # key -> Event other callers wait on
        self.builds = 0

    def get_or_build(self, key, build):
        while True:
            with self._lock:
                if key in self._fns:
                    return self._fns[key], False
                ev = self._inflight.get(key)
                if ev is None:
                    ev = self._inflight[key] = threading.Event()
                    building = True
                else:
                    building = False
            if not building:
                ev.wait()
                continue        # re-check: hit, or builder failed
            try:
                fn = build()
                with self._lock:
                    self._fns[key] = fn
                    self.builds += 1
                return fn, True
            finally:
                with self._lock:
                    del self._inflight[key]
                ev.set()


class FarmConfig:
    """Shape of one replica group.

    replicas: decode replica count (each gets a disjoint device slice).
    prefill_devices: devices reserved up front for disaggregated
        prefill (0 = pooled: each replica prefills on its own slice).
    engine: per-replica `DecodeEngineConfig` (slots, buckets, kv_quant
        — int8 KV opts in HERE, per model).
    decode: per-replica scheduler `DecodeConfig` (queue bound,
        deadlines, bos/eos).
    devices: explicit device list to slice (default: all local).
    share_compiles: share jit traces across replicas (single-flight).
    retries: how many times a GroupFuture resubmits a crash-failed
        request to another replica before giving up (with a guard,
        additionally capped by the group retry budget).
    qos_factory: () -> QosPolicy per replica (None = default WFQ).
    guard: a `serving.guard.GuardConfig` (or True for defaults) to
        attach overload defense — health probation, hedging, retry
        budget, brownout. None (default) adds nothing: the guard
        package is not even imported.
    """

    def __init__(self, replicas=2, prefill_devices=0, engine=None,
                 decode=None, devices=None, share_compiles=True,
                 retries=1, qos_factory=None, guard=None):
        self.replicas = int(replicas)
        if self.replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        self.prefill_devices = int(prefill_devices)
        self.engine = engine or DecodeEngineConfig()
        self.decode = decode or DecodeConfig()
        self.devices = devices
        self.share_compiles = bool(share_compiles)
        self.retries = int(retries)
        self.qos_factory = qos_factory
        self.guard = guard

    def verify(self, devices=None, model_config=None,
               raise_on_error=False):
        """Static pre-spawn verification of the farm shape via the
        meshlint pipeline: device-slice arithmetic (replicas sharing a
        physical device, reserved prefill heads eating the decode
        pool), engine knob consistency, and the per-replica KV-cache
        byte footprint vs the device cap — the serving-tier twin of
        ParallelExecutor.verify(). Returns the diagnostics; imports
        meshlint only when called (the serve path never pays for it)."""
        from ...analysis.diagnostics import (Diagnostic, ERROR, WARNING,
                                             ProgramVerificationError)
        from ...analysis.meshlint import (MeshLintContext, MeshSpec,
                                          run_mesh_passes)
        import jax

        diags = []
        devs = list(devices if devices is not None
                    else self.devices if self.devices is not None
                    else jax.devices())
        need = self.prefill_devices + self.replicas
        if len(devs) < need:
            diags.append(Diagnostic(
                WARNING, "collective-consistency",
                f"farm wants {self.replicas} replica slice(s) + "
                f"{self.prefill_devices} prefill head(s) but only "
                f"{len(devs)} device(s) exist: device_slices wraps "
                f"and replicas SHARE devices — correct but serialized",
                hint="drop replicas/prefill_devices or add devices"))
        eng = self.engine
        if eng.kv_quant is not None and eng.kv_quant != "int8":
            diags.append(Diagnostic(
                ERROR, "collective-consistency",
                f"engine kv_quant={eng.kv_quant!r} is not a known KV "
                f"cache quantization (int8 or None)",
                hint="DecodeEngineConfig(kv_quant='int8')"))
        if self.retries < 0:
            diags.append(Diagnostic(
                ERROR, "collective-consistency",
                f"retries={self.retries} is negative"))
        # per-replica KV footprint: slots x len x layers x 2 (k+v) x
        # heads*head_dim, int8 = 1 byte + per-block scales, fp32 = 4
        extra = 0
        if model_config is not None:
            mc = model_config
            hid = getattr(mc, "hidden", None) or getattr(
                mc, "d_model", 0)
            layers = (getattr(mc, "layers", None)
                      or getattr(mc, "n_layers", None)
                      or getattr(mc, "n_layer", 0))
            max_len = eng.max_len or getattr(mc, "max_len", 0)
            if hid and layers and max_len:
                per_elem = 1 if eng.kv_quant == "int8" else 4
                extra = (2 * layers * eng.num_slots * max_len * hid
                         * per_elem)
                if eng.kv_quant == "int8":
                    block = eng.kv_block or hid
                    extra += (2 * layers * eng.num_slots * max_len
                              * -(-hid // block) * 4)  # scales
        per_slice = max(1, (len(devs) - self.prefill_devices)
                        // self.replicas)
        mctx = MeshLintContext(
            MeshSpec({"replica": per_slice}),
            extra_state_bytes=extra,
            label=f"FarmConfig[replicas={self.replicas}]")
        diags += run_mesh_passes(mctx, passes=["device-footprint"])
        diags.sort(key=Diagnostic.sort_key)
        if raise_on_error and any(d.severity == "error" for d in diags):
            raise ProgramVerificationError(
                [d for d in diags if d.severity == "error"])
        return diags


class Replica:
    """One decode engine + scheduler bound to a device slice."""

    __slots__ = ("index", "engine", "scheduler", "devices", "draining",
                 "version")

    def __init__(self, index, engine, scheduler, devices):
        self.index = index
        self.engine = engine
        self.scheduler = scheduler
        self.devices = list(devices)
        self.draining = False    # rolling update in progress
        self.version = 1

    @property
    def routable(self):
        return not self.draining and self.scheduler.alive


class GroupFuture:
    """A decode future that survives replica crashes.

    Wraps the routed replica's future; `result()` resubmits to another
    routable replica when the underlying request died WITH its replica
    (loop crash — e.g. an injected worker_crash) rather than by a
    structured shed (deadline / preemption / rejection / shutdown
    propagate unchanged). Bounded by the group's `retries` budget and
    the caller's timeout.

    With a guard configured, `result()` runs the guarded path instead:
    it races a candidate set (primary + at most one hedge launched at
    the live-p99 delay), cancels the losing leg so its slot is
    reclaimed, feeds every leg's outcome to the health tracker, and
    draws resubmissions from the group retry budget — exhaustion is a
    fast typed `RetryBudgetExhausted`, not a storm."""

    def __init__(self, group, kwargs, replica, future, retries):
        self._group = group
        self._kwargs = kwargs
        self._replica = replica
        self._future = future
        self._retries = retries
        self._failed = set()
        self._guard = group.guard
        if self._guard is not None:
            # candidate legs racing for this request: primary now,
            # plus at most one hedge later
            self._cands = [{"rep": replica, "fut": future,
                            "t0": time.monotonic(), "hedge": False}]
            self._hedged = False

    def done(self):
        if self._guard is not None:
            return any(c["fut"].done() for c in self._cands)
        return self._future.done()

    @property
    def replica_index(self):
        """Which replica currently carries the request."""
        return self._replica.index

    def result(self, timeout=None):
        if self._guard is not None:
            return self._result_guarded(timeout)
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            left = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            try:
                return self._future.result(timeout=left)
            except (DeadlineExceeded, PreemptedError, RejectedError,
                    ServerClosed, TimeoutError):
                raise
            except Exception as e:     # noqa: BLE001 — replica death
                if self._retries <= 0:
                    raise
                self._retries -= 1
                self._failed.add(self._replica)
                rep, fut = self._group._route(
                    self._kwargs, exclude=self._failed,
                    leg="resubmit")
                _LOG.warning(
                    "farm %s: request resubmitted from crashed "
                    "replica %d to %d (%s)", self._group.name,
                    self._replica.index, rep.index, type(e).__name__)
                if _tm.enabled():
                    _tm.counter("serving.farm.retries").inc()
                rid = self._kwargs.get("request_id")
                if rid and _tm.reqtrace_enabled():
                    _tm.reqtrace.flag(rid, "resubmit")
                    _tm.reqtrace.event(rid, "farm.resubmit",
                                       replica=rep.index,
                                       dead=self._replica.index,
                                       cause=type(e).__name__)
                self._replica, self._future = rep, fut

    # ------------------------------------------------- guarded path
    def _result_guarded(self, timeout):
        g = self._guard
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            for c in list(self._cands):
                if not c["fut"].done():
                    continue
                try:
                    res = c["fut"].result(timeout=0)
                except (DeadlineExceeded, PreemptedError,
                        RejectedError, ServerClosed) as e:
                    # a structured shed, not a replica death: drop the
                    # leg; only when it was the LAST leg does the shed
                    # become the caller's answer
                    self._cands.remove(c)
                    if isinstance(e, DeadlineExceeded):
                        g.on_deadline_miss()
                    if not self._cands:
                        raise
                except TimeoutError:
                    continue          # raced done(); not resolved yet
                except Exception as e:  # noqa: BLE001 — replica death
                    self._cands.remove(c)
                    self._failed.add(c["rep"])
                    g.on_error(c["rep"].index)
                    if not self._cands:
                        self._resubmit(e)   # refills or raises typed
                else:
                    self._settle(c, time.monotonic() - c["t0"])
                    return res
            self._maybe_hedge()
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("timed out waiting for result")
            time.sleep(g.poll_s)

    def _settle(self, winner, latency_s):
        """First completion wins: record health, cancel the losers
        (their decode slots are reclaimed by the loser's iteration
        loop — the pool's single writer)."""
        g = self._guard
        g.on_result(winner["rep"].index, latency_s,
                    hedge=winner["hedge"])
        rid = self._kwargs.get("request_id")
        trace = rid and _tm.reqtrace_enabled()
        for c in self._cands:
            if c is not winner and c["rep"].scheduler.cancel(c["fut"]):
                g.on_cancelled()
                if trace:
                    _tm.reqtrace.event(
                        rid, "farm.hedge.cancel",
                        replica=c["rep"].index, outcome="loser",
                        hedge=c["hedge"])
        if trace:
            _tm.reqtrace.event(
                rid, "farm.win", replica=winner["rep"].index,
                outcome="winner", hedge=winner["hedge"],
                latency_ms=round(latency_s * 1e3, 3))
        self._cands = [winner]
        self._replica, self._future = winner["rep"], winner["fut"]

    def _maybe_hedge(self):
        """Launch the backup leg once the primary has been pending
        longer than the hedge delay (live p99 derived). At most one
        hedge per request; denied budgets mean no hedge, never an
        error."""
        g = self._guard
        if self._hedged or len(self._cands) != 1:
            return
        c0 = self._cands[0]
        delay = g.hedge_delay()
        if delay is None or time.monotonic() - c0["t0"] < delay:
            return
        self._hedged = True
        rid = self._kwargs.get("request_id")
        if not g.allow_hedge(request_id=rid):
            return
        exclude = set(self._failed)
        exclude.add(c0["rep"])
        try:
            rep, fut = self._group._route(self._kwargs,
                                          exclude=exclude,
                                          leg="hedge")
        except RejectedError:
            g.refund_hedge()        # nowhere to hedge to
            return
        g.on_hedge()
        if _tm.enabled():
            _tm.instant_event(
                "serving.guard.hedge", farm=self._group.name,
                primary=c0["rep"].index, hedge=rep.index,
                request_id=rid)
        if rid and _tm.reqtrace_enabled():
            _tm.reqtrace.flag(rid, "hedge")
            _tm.reqtrace.event(
                rid, "farm.hedge.launch", primary=c0["rep"].index,
                hedge=rep.index, **g.hedge.describe())
        self._cands.append({"rep": rep, "fut": fut,
                            "t0": time.monotonic(), "hedge": True})

    def _resubmit(self, exc):
        """All legs died with their replicas: resubmit if both the
        per-request retry count and the group retry budget allow,
        else fail fast and typed."""
        g = self._guard
        rid = self._kwargs.get("request_id")
        if self._retries <= 0:
            raise exc
        if not g.allow_resubmit(request_id=rid):
            raise RetryBudgetExhausted(
                f"farm {self._group.name!r}: retry budget exhausted "
                f"resubmitting after {type(exc).__name__}") from exc
        self._retries -= 1
        rep, fut = self._group._route(self._kwargs,
                                      exclude=self._failed,
                                      leg="resubmit")
        g.on_resubmit()
        if rid and _tm.reqtrace_enabled():
            # the ORIGINAL id rides self._kwargs: the resubmitted leg
            # re-enters scheduler spans under the same trace, not a
            # fresh context
            _tm.reqtrace.flag(rid, "resubmit")
            _tm.reqtrace.event(rid, "farm.resubmit",
                               replica=rep.index,
                               dead=self._replica.index,
                               cause=type(exc).__name__)
        _LOG.warning(
            "farm %s: request resubmitted from crashed replica %d "
            "to %d (%s)", self._group.name, self._replica.index,
            rep.index, type(exc).__name__)
        if _tm.enabled():
            _tm.counter("serving.farm.retries").inc()
        self._cands.append({"rep": rep, "fut": fut,
                            "t0": time.monotonic(), "hedge": False})
        self._replica, self._future = rep, fut


class ReplicaGroup:
    """N continuous-decode replicas behind one least-loaded router —
    the serving unit `ModelServer.attach_decoder` registers under a
    single model name."""

    def __init__(self, model_cfg, params, config=None, router=None,
                 name="farm", warmup=True):
        self.config = config or FarmConfig()
        self.model_cfg = model_cfg
        self.name = name
        self.router = router or LeastLoadedRouter()
        # overload defense is strictly opt-in: an unconfigured farm
        # never imports serving.guard (bench-contract pin)
        self.guard = None
        if self.config.guard is not None:
            from ..guard import GroupGuard
            gc = self.config.guard
            self.guard = GroupGuard(
                None if gc is True else gc,
                num_replicas=self.config.replicas)
            if getattr(self.router, "health", None) is None:
                self.router.health = self.guard.health
        # pre-spawn verification gate: same PADDLE_TPU_VALIDATE
        # tri-state as the executors — lint the farm shape (slice
        # arithmetic, engine knobs, KV footprint) before any engine
        # compiles; off (the default) never imports meshlint
        import os as _os
        if _os.environ.get("PADDLE_TPU_VALIDATE", "").lower() \
                not in ("", "0", "false", "off"):
            self.config.verify(model_config=model_cfg,
                               raise_on_error=True)
        self.build_cache = SharedBuildCache() \
            if self.config.share_compiles else None
        reserved, slices = device_slices(
            self.config.replicas, devices=self.config.devices,
            reserve=self.config.prefill_devices)
        self.prefill_devices = reserved
        self.version = 1
        self._lock = threading.Lock()
        self._rate = {}          # index -> (t, tokens) goodput sample
        self._params = params    # current weights (scale-up spawns)
        self._started = False
        self._next_index = 0     # monotonic: removed indices never reused
        self.scale = None        # a ScaleController attaches itself here
        self.replicas = []
        for i in range(self.config.replicas):
            self._spawn_replica(slices[i], warmup=warmup)
        if _tm.enabled():
            _tm.gauge("serving.farm.replicas").set(len(self.replicas))
            _tm.gauge("serving.farm.compile_count").set(
                self.compile_count)
        self._publish()

    # ------------------------------------------------------- properties
    @property
    def compile_count(self):
        """Executables built for the whole group — with compile
        sharing this is the CACHE's build count (per group, not per
        replica), the satellite pin."""
        if self.build_cache is not None:
            return self.build_cache.builds
        return sum(r.engine.compile_count
                   for r in list(self.replicas))

    @property
    def queued(self):
        return sum(r.scheduler.queued for r in list(self.replicas))

    @property
    def num_slots(self):
        return sum(r.scheduler.pool.num_slots
                   for r in list(self.replicas))

    @property
    def free_slots(self):
        return sum(r.scheduler.pool.num_slots
                   - r.scheduler.pool.active_count()
                   for r in list(self.replicas))

    # ---------------------------------------------------------- serving
    def submit(self, src, src_len=None, tenant="default",
               max_new_tokens=None, deadline_ms=None, request_id=None):
        """Route one sequence to the least-loaded replica; returns a
        `GroupFuture` (resolves to a DecodeResult, resubmitting across
        replicas on a crash)."""
        if request_id is None and _tm.reqtrace_enabled():
            # one request, one id: hedge duplicates and crash
            # resubmissions must join the SAME trace, so a request
            # that arrived without an id gets one here — before any
            # leg exists to diverge
            import uuid
            request_id = uuid.uuid4().hex[:16]
        kwargs = dict(src=src, src_len=src_len, tenant=tenant,
                      max_new_tokens=max_new_tokens,
                      deadline_ms=deadline_ms, request_id=request_id)
        if request_id and _tm.reqtrace_enabled():
            _tm.reqtrace.trace_begin(request_id, farm=self.name,
                                     tenant=str(tenant))
        if self.guard is not None:
            # brownout shed/clamp + hedge-allowance deposit
            kwargs["max_new_tokens"] = self.guard.admit(
                str(tenant), self.replicas[0].scheduler.qos,
                self.queued, max_new_tokens, request_id=request_id)
        if _chaos.armed():
            # the serving.request chaos point: request_poison tags the
            # N-th farm submission; the tag rides resubmissions, so
            # the request stays lethal wherever it lands.
            # traffic_spike amplifies this submission x-fold with
            # shadow copies through the normal route — REAL queue and
            # slot pressure, the tpuscale ramp driver.
            f = _chaos.hit("serving.request")
            if f is not None and f["name"] == "request_poison":
                kwargs["poison"] = True
            elif f is not None and f["name"] == "traffic_spike":
                self._spike(kwargs, int(f.get("x", 2)))
        rep, fut = self._route(kwargs, exclude=())
        return GroupFuture(self, kwargs, rep, fut,
                           retries=self.config.retries)

    def _spike(self, kwargs, x):
        """Route x-1 shadow copies of a spiking request. Shadows are
        fire-and-forget synthetic load: a full queue sheds them
        (counted, never raised to the real caller) and nobody waits on
        their futures — the scheduler retires them like any other
        request."""
        for j in range(max(0, x - 1)):
            shadow = dict(kwargs)
            rid = kwargs.get("request_id")
            shadow["request_id"] = f"spike-{j}" if rid is None \
                else f"{rid}.spike-{j}"
            try:
                self._route(shadow, exclude=())
            except RejectedError:
                if _tm.enabled():
                    _tm.counter("serving.farm.spike_shed").inc()
                continue
            if _tm.enabled():
                _tm.counter("serving.farm.spike_shadows").inc()

    def decode(self, src, timeout=None, **kw):
        """Blocking convenience: submit + wait -> DecodeResult."""
        return self.submit(src, **kw).result(timeout=timeout)

    def _route(self, kwargs, exclude, leg="primary"):
        with self._lock:
            rep = self.router.pick(self.replicas, exclude=exclude)
            if rep is None:
                # nothing routable (all draining/dead/excluded): keep
                # accepting on the least-queued live replica rather
                # than going dark — its queue serves when it recovers
                live = [r for r in self.replicas if r not in exclude]
                if not live:
                    raise RejectedError(
                        f"farm {self.name!r}: no replica available")
                rep = min(live, key=lambda r: r.scheduler.queued)
        rid = kwargs.get("request_id")
        if rid and _tm.reqtrace_enabled():
            # the routing decision opens the request's per-replica
            # leg; scheduler/engine events on this replica parent to
            # it, which is what stitches the cross-replica chain
            _tm.reqtrace.leg(rid, rep.index, kind=leg,
                             queued=rep.scheduler.queued)
        fut = rep.scheduler.submit(**kwargs)
        if _tm.enabled():
            _tm.counter("serving.farm.routed").inc()
            _tm.counter(
                f"serving.replica.{rep.index}.routed").inc()
        self._publish()
        return rep, fut

    # -------------------------------------------------------- iteration
    def run_iteration(self):
        """Manual deterministic drive: one retire/admit/step cycle on
        EVERY replica (tests and the selftest use this instead of the
        loop threads). Returns total active slots stepped."""
        stepped = 0
        for r in list(self.replicas):
            stepped += r.scheduler.run_iteration()
        self._publish()
        return stepped

    # ------------------------------------------------------- lifecycle
    def start(self):
        self._started = True
        for r in self.replicas:
            r.scheduler.start()
        return self

    def stop(self, drain=True, timeout=30.0):
        self._started = False
        for r in self.replicas:
            r.scheduler.stop(drain=drain, timeout=timeout)

    # ------------------------------------------------------- scaling
    def _spawn_replica(self, devices, params=None, warmup=True):
        """Build one replica on `devices` at the next monotonic index
        and add it to the routed set. Indices are never reused —
        telemetry/health rows stay unambiguous across grow/shrink
        cycles. Shared-build-cache groups compile NOTHING new when a
        same-config replica already warmed up (the scale-up pin)."""
        params = self._params if params is None else params
        with self._lock:
            i = self._next_index
            self._next_index += 1
        engine = DecodeEngine(
            self.model_cfg, params, config=self.config.engine,
            device=devices[0],
            prefill_device=(self.prefill_devices[
                i % len(self.prefill_devices)]
                if self.prefill_devices else None),
            build_cache=self.build_cache)
        qos = self.config.qos_factory() \
            if self.config.qos_factory else None
        # index BEFORE the scheduler builds slot state: the memory
        # ledger attributes KV blocks to their replica at creation
        engine.replica_index = i
        sched = ContinuousScheduler(
            engine, qos=qos, config=self.config.decode,
            name=f"{self.name}.r{i}", warmup=warmup)
        sched.replica_index = i
        rep = Replica(i, engine, sched, devices)
        rep.version = self.version
        if self.guard is not None:
            self.guard.on_replica_added(i)
        with self._lock:
            self.replicas.append(rep)
        if self._started:
            sched.start()
        return rep

    def add_replica(self, devices, params=None,
                    checkpoint_dir=None, warmup=True):
        """Grow the group by one replica serving the CURRENT weights
        (or an explicit `params` dict / PR-11 `checkpoint_dir`) on the
        given device slice. The new replica enters the routed set as
        soon as its warmup lands; with a shared build cache and a
        same-config sibling, warmup is all cache hits — zero new
        compiles (`compile_count` unchanged). Returns the Replica.

        This is the mechanism layer only: placement policy, the
        pre-spawn verify gate, and WHEN to grow live in
        `serving.scale` (never imported from here — bench-contract
        pin)."""
        if checkpoint_dir is not None:
            if params is not None:
                raise ValueError("pass params or checkpoint_dir, "
                                 "not both")
            params = load_checkpoint_params(checkpoint_dir)
        rep = self._spawn_replica(list(devices), params=params,
                                  warmup=warmup)
        _LOG.info("farm %s: replica %d joined (now %d live)",
                  self.name, rep.index, len(self.replicas))
        if _tm.enabled():
            _tm.counter("serving.farm.replicas_added").inc()
            _tm.gauge("serving.farm.replicas").set(len(self.replicas))
            _tm.gauge("serving.farm.compile_count").set(
                self.compile_count)
        self._publish()
        return rep

    def remove_replica(self, index=None, drain_timeout=30.0,
                       poll_s=0.002, drive=False):
        """Shrink the group by draining one replica to empty and
        detaching it — zero dropped requests, same discipline as a
        rolling update's per-replica drain. Picks the least-loaded
        routable replica when `index` is None; refuses to remove the
        last one (an autoscaler bug must not take the group dark).
        `drive=True` pumps `run_iteration()` to drain (manual mode).
        Returns the freed device slice for the caller's allocator."""
        with self._lock:
            if len(self.replicas) <= 1:
                raise ValueError(
                    f"farm {self.name!r}: refusing to remove the "
                    f"last replica")
            if index is None:
                cands = [r for r in self.replicas if r.routable] \
                    or list(self.replicas)
                rep = min(cands,
                          key=lambda r: (r.scheduler.queued
                                         + r.scheduler.pool
                                         .active_count()))
            else:
                match = [r for r in self.replicas
                         if r.index == index]
                if not match:
                    raise ValueError(f"no replica with index {index}")
                rep = match[0]
            rep.draining = True     # router skips it from here on
        self._publish()
        try:
            deadline = time.monotonic() + drain_timeout
            while (rep.scheduler.pool.active_count() > 0
                   or rep.scheduler.queued > 0):
                if drive:
                    self.run_iteration()
                else:
                    time.sleep(poll_s)
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replica {rep.index} did not drain within "
                        f"{drain_timeout}s for removal")
            rep.scheduler.stop(drain=True, timeout=drain_timeout)
        except Exception:
            rep.draining = False    # failed removal: keep serving
            self._publish()
            raise
        with self._lock:
            self.replicas.remove(rep)
        _LOG.info("farm %s: replica %d drained and released "
                  "(now %d live)", self.name, rep.index,
                  len(self.replicas))
        if _tm.enabled():
            _tm.counter("serving.farm.replicas_removed").inc()
            _tm.gauge("serving.farm.replicas").set(len(self.replicas))
            _tm.gauge(
                f"serving.replica.{rep.index}.alive").set(0.0)
        self._publish()
        return rep.devices

    # -------------------------------------------------- rolling updates
    def rolling_update(self, params=None, checkpoint_dir=None,
                       version=None, drain_timeout=30.0, poll_s=0.002,
                       drive=False):
        """Load a new weight version into each replica IN TURN while
        the others keep serving, then flip `version`.

        Per replica: mark draining (router skips it), wait for its
        slots + queue to empty, swap the parameter set under the
        compiled executables (`DecodeEngine.set_params` — zero
        recompile, prefill + decode atomically), bump its version,
        undrain. `params` is a checkpoint array dict; alternatively
        `checkpoint_dir` names a PR-11 topology-independent checkpoint
        (a CheckpointSaver root resolves to its newest valid
        checkpoint_N). `drive=True` is for manual mode: the update
        itself pumps `run_iteration()` to drain (no loop threads)."""
        if params is None:
            if checkpoint_dir is None:
                raise ValueError("rolling_update needs params or "
                                 "checkpoint_dir")
            params = load_checkpoint_params(checkpoint_dir)
        version = int(version if version is not None
                      else self.version + 1)
        with _tm.span("serving.farm.rolling_update", farm=self.name,
                      version=version):
            for r in list(self.replicas):
                r.draining = True
                self._publish()
                try:
                    deadline = time.monotonic() + drain_timeout
                    while (r.scheduler.pool.active_count() > 0
                           or r.scheduler.queued > 0):
                        if drive:
                            self.run_iteration()
                        else:
                            time.sleep(poll_s)
                        if time.monotonic() > deadline:
                            raise TimeoutError(
                                f"replica {r.index} did not drain "
                                f"within {drain_timeout}s for the "
                                f"rolling update")
                    r.engine.set_params(params)
                    r.version = version
                finally:
                    r.draining = False
                if _tm.enabled():
                    _tm.counter("serving.farm.replicas_updated").inc()
                _LOG.info("farm %s: replica %d now serving version %d",
                          self.name, r.index, version)
        self.version = version
        self._params = params    # scale-up spawns serve this version
        self._publish()
        return version

    # -------------------------------------------------------- telemetry
    def stats(self):
        """Per-replica serving stats (also pushed as
        serving.replica.<i>.* gauges): slots in use, queue depth, KV
        bytes, lifetime tokens, goodput tokens/s, restarts, version,
        liveness, device slice."""
        out = {"name": self.name, "version": self.version,
               "replicas": [],
               "compile_count": self.compile_count,
               "prefill_devices": [str(d)
                                   for d in self.prefill_devices]}
        if self.guard is not None:
            out["guard"] = self.guard.stats()
        if self.scale is not None:
            out["scale"] = self.scale.stats()
        for r in list(self.replicas):
            s = r.scheduler
            out["replicas"].append({
                "index": r.index,
                **({"guard_state":
                    self.guard.health.state(r.index)}
                   if self.guard is not None else {}),
                "slots_in_use": s.pool.active_count(),
                "num_slots": s.pool.num_slots,
                "queue_depth": s.queued,
                "kv_cache_bytes": r.engine.kv_cache_bytes,
                "tokens_total": s.tokens_generated,
                "goodput_tps": self._goodput(r),
                "restarts": s.restarts,
                "alive": s.alive,
                "draining": r.draining,
                "version": r.version,
                "devices": [str(d) for d in r.devices]})
        self._publish()
        return out

    def _goodput(self, r, update=False):
        """Tokens/s since the previous goodput sample of replica r."""
        now = time.monotonic()
        tokens = r.scheduler.tokens_generated
        last = self._rate.get(r.index)
        if update or last is None:
            self._rate[r.index] = (now, tokens)
        if last is None:
            return 0.0
        dt = now - last[0]
        return (tokens - last[1]) / dt if dt > 1e-6 else 0.0

    def _publish(self):
        if not _tm.enabled():
            return
        for r in list(self.replicas):
            s = r.scheduler
            pre = f"serving.replica.{r.index}"
            _tm.gauge(f"{pre}.slots_in_use").set(
                float(s.pool.active_count()))
            _tm.gauge(f"{pre}.num_slots").set(float(s.pool.num_slots))
            _tm.gauge(f"{pre}.queue_depth").set(float(s.queued))
            _tm.gauge(f"{pre}.kv_cache_bytes").set(
                float(r.engine.kv_cache_bytes))
            _tm.gauge(f"{pre}.tokens_total").set(
                float(s.tokens_generated))
            _tm.gauge(f"{pre}.goodput_tps").set(
                self._goodput(r, update=True))
            _tm.gauge(f"{pre}.restarts").set(float(s.restarts))
            _tm.gauge(f"{pre}.alive").set(1.0 if s.alive else 0.0)
            _tm.gauge(f"{pre}.draining").set(
                1.0 if r.draining else 0.0)
            _tm.gauge(f"{pre}.version").set(float(r.version))
        if self.guard is not None:
            self.guard.publish()


def load_checkpoint_params(dirname):
    """Dense params out of a PR-11 topology-independent checkpoint:
    resolve a CheckpointSaver root to its newest VALID checkpoint_N
    (torn/corrupt candidates skipped), verify the checksum manifest,
    and load params.npz — the array dict `rolling_update` feeds to
    every replica."""
    import os

    from ... import io as _io
    from ...resilience import checkpoint as _rckpt

    d = dirname
    if not os.path.exists(os.path.join(d, _io.META_FILE)):
        latest = _io.latest_checkpoint(d)
        if latest is None:
            raise FileNotFoundError(
                f"{dirname!r} is neither a checkpoint dir nor a "
                f"root holding a valid checkpoint_N")
        d = latest
    ok, reason = _rckpt.validate(d)
    if not ok:
        raise ValueError(f"checkpoint {d!r} failed validation: "
                         f"{reason}")
    with np.load(os.path.join(d, _io.PARAMS_FILE)) as z:
        return {k: z[k] for k in z.files}
