"""tpufarm: replicated & disaggregated serving above the decode tier.

NEVER imported by `paddle_tpu.serving` itself — a server with no
replica group configured must not load this package (lazy-import pin
in tests/test_bench_contract.py). Import it explicitly:

    from paddle_tpu.serving.farm import FarmConfig, ReplicaGroup

    group = ReplicaGroup(model_cfg, params, FarmConfig(
        replicas=2, prefill_devices=1,
        engine=DecodeEngineConfig(num_slots=8, kv_quant="int8")))
    server.attach_decoder("nmt", group)      # one registry name
"""
from .group import (FarmConfig, GroupFuture, Replica, ReplicaGroup,
                    SharedBuildCache, load_checkpoint_params)
from .router import LeastLoadedRouter

__all__ = ["FarmConfig", "GroupFuture", "Replica", "ReplicaGroup",
           "SharedBuildCache", "LeastLoadedRouter",
           "load_checkpoint_params"]
