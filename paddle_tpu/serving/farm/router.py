"""Least-loaded routing across decode replicas.

The reference's Paddle Serving scaled out by running N independent
server instances behind an external load balancer that knew nothing
about slot pools or queues — round-robin at best. Here the router sits
IN-PROCESS with full visibility into every replica's scheduler, so it
can score actual capacity: free decode slots (work starts this
iteration) discounted by queue depth (work waits behind others).

Routability is a hard filter before scoring: a replica that is
draining for a rolling weight update, or whose loop thread has died
and not yet been respawned by its supervisor, takes no new work. The
group falls back to least-queued among whatever is left only when
NOTHING is routable (one-replica groups mid-update keep accepting
rather than going dark — availability over update latency).
"""

__all__ = ["LeastLoadedRouter"]


class LeastLoadedRouter:
    """score = (free_slots + 1) / (1 + queue_weight * queue_depth).

    Free slots dominate (a request admitted now beats any queue), the
    +1 keeps fully-busy replicas comparable by backlog, and
    `queue_weight` tunes how hard queueing repels new work. Ties break
    toward the lowest replica index for determinism."""

    def __init__(self, queue_weight=1.0):
        self.queue_weight = float(queue_weight)

    def score(self, replica):
        s = replica.scheduler
        return (s.pool.free_count() + 1.0) / \
            (1.0 + self.queue_weight * s.queued)

    def pick(self, replicas, exclude=()):
        """The routable replica with the best score, or None when no
        replica is routable (all draining/dead/excluded)."""
        best, best_score = None, 0.0
        for r in replicas:
            if r in exclude or not r.routable:
                continue
            sc = self.score(r)
            if best is None or sc > best_score:
                best, best_score = r, sc
        return best
