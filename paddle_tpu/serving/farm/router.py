"""Least-loaded routing across decode replicas.

The reference's Paddle Serving scaled out by running N independent
server instances behind an external load balancer that knew nothing
about slot pools or queues — round-robin at best. Here the router sits
IN-PROCESS with full visibility into every replica's scheduler, so it
can score actual capacity: free decode slots (work starts this
iteration) discounted by queue depth (work waits behind others).

Routability is a hard filter before scoring: a replica that is
draining for a rolling weight update, or whose loop thread has died
and not yet been respawned by its supervisor, takes no new work. The
group falls back to least-queued among whatever is left only when
NOTHING is routable (one-replica groups mid-update keep accepting
rather than going dark — availability over update latency).

With a guard configured (`FarmConfig(guard=...)`), the router also
consults the group's `HealthTracker`: ejected replicas are filtered
like the dead, probation discounts the score, and a half-open replica
with probe capacity is picked FIRST — live traffic is the probe that
re-admits it. `health=None` (the default) is byte-for-byte the PR-13
decision function, pinned by the bench contract.
"""

__all__ = ["LeastLoadedRouter"]


class LeastLoadedRouter:
    """score = (free_slots + 1) / (1 + queue_weight * queue_depth).

    Free slots dominate (a request admitted now beats any queue), the
    +1 keeps fully-busy replicas comparable by backlog, and
    `queue_weight` tunes how hard queueing repels new work. Ties break
    toward the lowest replica index for determinism."""

    def __init__(self, queue_weight=1.0, health=None):
        self.queue_weight = float(queue_weight)
        self.health = health        # guard HealthTracker, or None

    def score(self, replica):
        s = replica.scheduler
        return (s.pool.free_count() + 1.0) / \
            (1.0 + self.queue_weight * s.queued)

    def pick(self, replicas, exclude=()):
        """The routable replica with the best score, or None when no
        replica is routable (all draining/dead/excluded — and, with a
        guard, all ejected)."""
        h = self.health
        best, best_score = None, 0.0
        for r in replicas:
            if r in exclude or not r.routable:
                continue
            if h is None:
                sc = self.score(r)
            else:
                if not h.routable(r.index):
                    continue
                if h.wants_probe(r.index):
                    h.on_probe_routed(r.index)
                    return r
                sc = self.score(r) * h.penalty(r.index)
            if best is None or sc > best_score:
                best, best_score = r, sc
        return best
