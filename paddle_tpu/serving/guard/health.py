"""Per-replica health: rolling EWMAs feeding a circuit breaker.

The farm router (PR 13) is death-aware only: a replica is skipped when
its loop thread is gone, period. This tracker adds the judgment call —
a replica that is *alive but wrong* (slow straggler, crash-flapping,
burning through respawns) is walked through a state machine:

    HEALTHY ──bad streak / error EWMA──▶ PROBATION
    PROBATION ──persists──▶ EJECTED          (score 0, no traffic)
    PROBATION ──recovers──▶ HEALTHY
    EJECTED ──cooldown──▶ HALF_OPEN          (probe_max live probes)
    HALF_OPEN ──probe ok──▶ HEALTHY          (re-admitted, no operator)
    HALF_OPEN ──probe bad──▶ EJECTED         (cooldown doubles, capped)

Samples arrive from the guarded `GroupFuture.result` path: one
``record(index, latency_s, ok)`` per completed request leg. "Slow" is
judged *relatively* — a sample is bad when its latency exceeds
``slow_factor`` x the median of the OTHER replicas' latency EWMAs — so
a uniformly loaded group never ejects anybody, while one straggler
among peers stands out immediately.

Safety rail: a replica is never ejected when no OTHER replica is
healthy or on probation — degraded capacity beats zero capacity.
"""
import statistics
import threading
import time

from ... import telemetry as _tm

__all__ = ["HealthTracker", "HEALTHY", "PROBATION", "EJECTED",
           "HALF_OPEN", "STATE_CODES"]

HEALTHY = "healthy"
PROBATION = "probation"
EJECTED = "ejected"
HALF_OPEN = "half_open"

# gauge encoding for serving.replica.<i>.guard_state
STATE_CODES = {HEALTHY: 0.0, PROBATION: 1.0, EJECTED: 2.0,
               HALF_OPEN: 3.0}


class _ReplicaHealth:
    __slots__ = ("state", "lat_ewma", "err_ewma", "samples",
                 "bad_streak", "good_streak", "ejected_at",
                 "cooldown_s", "probes_inflight")

    def __init__(self, cooldown_s):
        self.state = HEALTHY
        self.lat_ewma = None
        self.err_ewma = 0.0
        self.samples = 0
        self.bad_streak = 0
        self.good_streak = 0
        self.ejected_at = 0.0
        self.cooldown_s = cooldown_s
        self.probes_inflight = 0


class HealthTracker:
    """EWMA health accounting + state machine for one replica group."""

    def __init__(self, num_replicas, latency_alpha=0.3,
                 error_alpha=0.3, min_samples=4, slow_factor=3.0,
                 slow_floor_s=0.005, err_probation=0.3, err_exit=0.1,
                 enter_streak=3, probation_grace=4, probation_good=3,
                 probation_penalty=0.1, cooldown_s=5.0,
                 cooldown_max_s=60.0, probe_max=1,
                 clock=time.monotonic):
        self.latency_alpha = float(latency_alpha)
        self.error_alpha = float(error_alpha)
        self.min_samples = int(min_samples)
        self.slow_factor = float(slow_factor)
        self.slow_floor_s = float(slow_floor_s)
        self.err_probation = float(err_probation)
        self.err_exit = float(err_exit)
        self.enter_streak = int(enter_streak)
        self.probation_grace = int(probation_grace)
        self.probation_good = int(probation_good)
        self.probation_penalty = float(probation_penalty)
        self.cooldown_base_s = float(cooldown_s)
        self.cooldown_max_s = float(cooldown_max_s)
        self.probe_max = int(probe_max)
        self._clock = clock
        self._lock = threading.Lock()
        self._reps = [_ReplicaHealth(self.cooldown_base_s)
                      for _ in range(int(num_replicas))]
        self.ejections = 0
        self.readmissions = 0
        self.probes = 0

    # -------------------------------------------------------- sampling
    def record(self, index, latency_s=None, ok=True):
        """One completed request leg on replica `index`. Updates the
        EWMAs and runs the state machine."""
        with self._lock:
            h = self._reps[index]
            self._maybe_half_open(h)
            h.samples += 1
            if h.probes_inflight > 0:
                h.probes_inflight -= 1
            if latency_s is not None:
                h.lat_ewma = latency_s if h.lat_ewma is None else (
                    (1.0 - self.latency_alpha) * h.lat_ewma
                    + self.latency_alpha * latency_s)
            h.err_ewma = ((1.0 - self.error_alpha) * h.err_ewma
                          + self.error_alpha * (0.0 if ok else 1.0))
            bad = (not ok) or self._slow(index, latency_s)
            if bad:
                h.bad_streak += 1
                h.good_streak = 0
            else:
                h.good_streak += 1
                h.bad_streak = 0
            self._transition(index, h, bad)

    def _slow(self, index, latency_s):
        """Is this sample a straggler relative to the peer group?"""
        if latency_s is None:
            return False
        peers = [r.lat_ewma for i, r in enumerate(self._reps)
                 if i != index and r.lat_ewma is not None
                 and r.samples >= self.min_samples]
        if not peers:
            return False
        bar = self.slow_factor * max(statistics.median(peers),
                                     self.slow_floor_s)
        return latency_s > bar

    def _transition(self, index, h, bad):
        if h.state == HEALTHY:
            if h.err_ewma > self.err_probation \
                    or h.bad_streak >= self.enter_streak:
                h.state = PROBATION
                self._count("probations")
        elif h.state == PROBATION:
            if bad and h.bad_streak >= self.probation_grace:
                self._eject(index, h, escalate=False)
            elif not bad and h.good_streak >= self.probation_good \
                    and h.err_ewma < self.err_exit:
                h.state = HEALTHY
        elif h.state == HALF_OPEN:
            if bad:
                self._eject(index, h, escalate=True)
            else:
                h.state = HEALTHY
                h.cooldown_s = self.cooldown_base_s
                h.err_ewma = 0.0
                self.readmissions += 1
                self._count("readmissions")
        # EJECTED: stragglers may still report; EWMAs updated above

    def _eject(self, index, h, escalate):
        # never go dark: keep the last routable replica taking traffic
        others = [r for i, r in enumerate(self._reps)
                  if i != index and r.state in (HEALTHY, PROBATION)]
        if not others:
            h.bad_streak = 0        # stay in probation, retry later
            return
        h.state = EJECTED
        h.ejected_at = self._clock()
        h.probes_inflight = 0
        if escalate:
            h.cooldown_s = min(self.cooldown_max_s, h.cooldown_s * 2.0)
        self.ejections += 1
        self._count("ejections")

    def _maybe_half_open(self, h):
        if h.state == EJECTED \
                and self._clock() - h.ejected_at >= h.cooldown_s:
            h.state = HALF_OPEN
            h.good_streak = 0
            h.bad_streak = 0
            h.probes_inflight = 0

    @staticmethod
    def _count(what):
        if _tm.enabled():
            _tm.counter(f"serving.guard.{what}").inc()

    # -------------------------------------------------------- routing
    def state(self, index):
        with self._lock:
            h = self._reps[index]
            self._maybe_half_open(h)
            return h.state

    def routable(self, index):
        """May the router send this replica regular traffic?"""
        with self._lock:
            h = self._reps[index]
            self._maybe_half_open(h)
            if h.state == EJECTED:
                return False
            if h.state == HALF_OPEN:
                return h.probes_inflight < self.probe_max
            return True

    def penalty(self, index):
        """Score multiplier for the router (1.0 = full confidence)."""
        with self._lock:
            h = self._reps[index]
            self._maybe_half_open(h)
            if h.state == EJECTED:
                return 0.0
            if h.state == PROBATION:
                return self.probation_penalty
            if h.state == HALF_OPEN:
                return self.probation_penalty * 0.5
            return 1.0

    def wants_probe(self, index):
        """HALF_OPEN with probe capacity: the router sends the next
        request here deliberately — live traffic IS the probe."""
        with self._lock:
            h = self._reps[index]
            self._maybe_half_open(h)
            return (h.state == HALF_OPEN
                    and h.probes_inflight < self.probe_max)

    def on_probe_routed(self, index):
        with self._lock:
            h = self._reps[index]
            if h.state == HALF_OPEN:
                h.probes_inflight += 1
                self.probes += 1
                self._count("probes")

    # ----------------------------------------------------- membership
    def ensure(self, num_replicas):
        """Grow the tracked-replica table to cover indices up to
        `num_replicas - 1` (autoscale scale-up: a fresh replica starts
        HEALTHY with empty EWMAs). Shrinking keeps the rows — indices
        are stable for a group's lifetime, and a later re-grow at the
        same index inherits nothing because scale-up always mints a
        NEW index."""
        with self._lock:
            while len(self._reps) < int(num_replicas):
                self._reps.append(_ReplicaHealth(self.cooldown_base_s))
            return len(self._reps)

    # ------------------------------------------------------ inspection
    def set_state(self, index, state):
        """Operator/test override (tpustat drain-style intervention)."""
        if state not in STATE_CODES:
            raise ValueError(f"unknown guard state {state!r}")
        with self._lock:
            h = self._reps[index]
            h.state = state
            if state == EJECTED:
                h.ejected_at = self._clock()
            h.bad_streak = 0
            h.good_streak = 0
            h.probes_inflight = 0

    def snapshot(self):
        with self._lock:
            out = []
            for h in self._reps:
                self._maybe_half_open(h)
                out.append({
                    "state": h.state,
                    "latency_ewma_s": h.lat_ewma,
                    "error_ewma": round(h.err_ewma, 4),
                    "samples": h.samples,
                    "cooldown_s": h.cooldown_s})
            return out
