"""Token buckets: the arithmetic that stops retries from amplifying.

Two shapes of the same primitive:

- ``RetryBudget`` refills at a fixed rate (`rate` tokens/s up to
  `burst`): the group-wide cap shared by hedges and crash
  resubmissions. When a mass failure tries to turn every in-flight
  request into N retries, the bucket empties in milliseconds and the
  excess becomes fast `RetryBudgetExhausted` rejections instead of a
  self-sustaining storm.

- ``FractionBucket`` refills per *event*: every submitted request
  deposits `fraction` tokens, a hedge withdraws one — so hedge volume
  is bounded to a fraction of real traffic by construction, whatever
  the arrival rate. An idle group banks at most `burst`.

Both are lock-per-op and allocation-free on the acquire path; neither
is imported unless a guard is configured (guard-off pays nothing).
"""
import threading
import time

from ... import telemetry as _tm

__all__ = ["RetryBudget", "FractionBucket"]


def _trace_denial(request_id, bucket, tokens, need):
    """Budget denials are exactly the events a tail exemplar must
    explain — mark the request's trace (no-op when tracing is off)."""
    if request_id is None or not _tm.reqtrace_enabled():
        return
    _tm.reqtrace.flag(request_id, "budget")
    _tm.reqtrace.event(request_id, "guard.budget.denied",
                       bucket=bucket, tokens=round(tokens, 3),
                       need=need)


class RetryBudget:
    """Time-refilled token bucket: `rate` tokens/s, capacity `burst`.

    `rate=0` makes the bucket non-refilling — exactly `burst` retries
    ever, the deterministic shape the selftests pin."""

    def __init__(self, rate=8.0, burst=16, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()
        self.denied = 0

    def _refill(self, now):
        if self.rate > 0.0:
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate)
        self._last = now

    def acquire(self, n=1.0, request_id=None):
        """Take `n` tokens; False (and `denied` grows) when short."""
        with self._lock:
            self._refill(self._clock())
            if self._tokens + 1e-9 < n:
                self.denied += 1
                tokens = self._tokens
            else:
                self._tokens -= n
                return True
        _trace_denial(request_id, "retry", tokens, n)
        return False

    def refund(self, n=1.0):
        """Give tokens back (an acquire whose action never launched)."""
        with self._lock:
            self._tokens = min(self.burst, self._tokens + n)

    @property
    def tokens(self):
        with self._lock:
            self._refill(self._clock())
            return self._tokens


class FractionBucket:
    """Event-refilled bucket: deposits ride traffic, not the clock.

    With `fraction=f`, over any interval the withdrawals (hedges)
    cannot exceed f x deposits (submissions) + `burst` — the "bounded
    fraction of traffic" contract."""

    def __init__(self, fraction=0.25, burst=8.0):
        self.fraction = float(fraction)
        self.burst = float(burst)
        self._tokens = min(1.0, self.burst)   # allow one early hedge
        self._lock = threading.Lock()
        self.denied = 0

    def deposit(self):
        with self._lock:
            self._tokens = min(self.burst, self._tokens + self.fraction)

    def acquire(self, n=1.0, request_id=None):
        with self._lock:
            if self._tokens + 1e-9 < n:
                self.denied += 1
                tokens = self._tokens
            else:
                self._tokens -= n
                return True
        _trace_denial(request_id, "hedge_fraction", tokens, n)
        return False

    def refund(self, n=1.0):
        with self._lock:
            self._tokens = min(self.burst, self._tokens + n)

    @property
    def tokens(self):
        with self._lock:
            return self._tokens
