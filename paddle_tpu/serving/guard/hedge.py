"""Hedged-request timing: a rolling latency window feeding the delay.

The hedge delay is *derived from the group's live p99*, not a fixed
timeout: re-issuing at ``factor x p99`` means ~99% of requests never
hedge (they finish first), while the tail — exactly the requests stuck
behind a straggler — gets a second replica racing on their behalf.
First completion wins; the loser is cancelled and its decode slot
reclaimed. This is the backup-task trick of the TensorFlow paper's
straggler mitigation, applied at the serving tier.

`LatencyWindow` keeps the last `size` completed-request latencies in a
ring; quantiles are computed on demand over a copy (the window is
small — a few hundred floats — so sorting on the hedge decision path
is cheaper than maintaining a sketch, and exact).
"""
import threading

__all__ = ["LatencyWindow", "HedgePolicy"]


class LatencyWindow:
    """Fixed-size ring of recent request latencies (seconds)."""

    def __init__(self, size=512):
        self.size = int(size)
        self._buf = [0.0] * self.size
        self._n = 0                  # lifetime count
        self._lock = threading.Lock()

    def observe(self, latency_s):
        with self._lock:
            self._buf[self._n % self.size] = float(latency_s)
            self._n += 1

    def __len__(self):
        with self._lock:
            return min(self._n, self.size)

    def quantile(self, q):
        """Exact q-quantile over the window, or None when empty."""
        with self._lock:
            n = min(self._n, self.size)
            if n == 0:
                return None
            vals = sorted(self._buf[:n])
        idx = min(n - 1, max(0, int(q * (n - 1) + 0.5)))
        return vals[idx]


class HedgePolicy:
    """When (and whether) to re-issue a pending request.

    delay() returns the seconds a request should wait on its primary
    replica before hedging, or None while hedging is disabled or the
    window is too thin to know what "slow" means (`min_samples`).
    `fixed_delay_s` pins the delay for deterministic tests; production
    leaves it None and rides the live quantile."""

    def __init__(self, enabled=True, quantile=0.99, factor=1.5,
                 floor_s=0.02, min_samples=8, fixed_delay_s=None,
                 window=None):
        self.enabled = bool(enabled)
        self.quantile = float(quantile)
        self.factor = float(factor)
        self.floor_s = float(floor_s)
        self.min_samples = int(min_samples)
        self.fixed_delay_s = fixed_delay_s
        self.window = window or LatencyWindow()

    def observe(self, latency_s):
        self.window.observe(latency_s)

    def delay(self):
        if not self.enabled:
            return None
        if self.fixed_delay_s is not None:
            return float(self.fixed_delay_s)
        if len(self.window) < self.min_samples:
            return None
        q = self.window.quantile(self.quantile)
        if q is None:
            return None
        return max(self.floor_s, self.factor * q)

    def p99_ms(self):
        q = self.window.quantile(0.99)
        return None if q is None else q * 1000.0

    def describe(self):
        """Why-this-delay provenance for a hedge-launch trace event:
        the effective delay plus whether it was pinned or p99-derived
        (and from how many window samples)."""
        d = self.delay()
        return {"delay_ms": None if d is None else round(d * 1e3, 3),
                "fixed": self.fixed_delay_s is not None,
                "p99_ms": self.p99_ms(),
                "window_n": len(self.window)}
