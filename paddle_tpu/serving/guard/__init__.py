"""tpuguard — serving-tier overload defense for replica groups.

The farm tier (serving/farm) scales decode out; this package keeps it
honest under the traffic that scale invites. Four mechanisms, one
`GroupGuard` per `ReplicaGroup`, opted in via `FarmConfig(guard=...)`:

- **Health probation & circuit breaking** (`health.HealthTracker`):
  per-replica latency/error EWMAs drive healthy → probation → ejected
  → half-open; slow or flapping replicas stop taking traffic and are
  re-admitted by live probe requests, not operator action.
- **Hedged requests** (`hedge.HedgePolicy`): after a delay derived
  from the group's live p99, a pending request is re-issued on the
  next-best replica; first completion wins, the loser is cancelled
  and its decode slot reclaimed. Bounded to a fraction of traffic.
- **Retry budget** (`budget.RetryBudget`): one token bucket shared by
  hedges and crash resubmissions — retry storms become fast
  `RetryBudgetExhausted` rejections instead of amplification.
- **Brownout** (`brownout.BrownoutController`): past queue-depth /
  deadline-miss thresholds, shed the lowest QoS tenant class with
  `Retry-After` hints, clamp `max_new_tokens`, recover with
  hysteresis.

A farm constructed without `guard=` never imports this package, adds
no per-request work, and routes exactly as PR 13 did — pinned by
tests/test_bench_contract.py. Proven end-to-end by `tpuserve
--selftest-guard` against the `replica_slow` / `replica_flap` /
`request_poison` chaos faults.
"""
from .brownout import BrownoutController
from .budget import FractionBucket, RetryBudget
from .core import GroupGuard, GuardConfig
from .health import (EJECTED, HALF_OPEN, HEALTHY, PROBATION,
                     STATE_CODES, HealthTracker)
from .hedge import HedgePolicy, LatencyWindow

__all__ = ["GuardConfig", "GroupGuard", "HealthTracker",
           "HedgePolicy", "LatencyWindow", "RetryBudget",
           "FractionBucket", "BrownoutController", "HEALTHY",
           "PROBATION", "EJECTED", "HALF_OPEN", "STATE_CODES"]
