"""GroupGuard: one object bundling the four defense mechanisms for a
replica group — health tracking, hedging, retry budget, brownout —
behind the narrow surface `serving.farm.group` calls into.

Configured via `GuardConfig` on `FarmConfig(guard=...)`; a farm
without one never imports this package (the bench contract pins it).
Every event lands in `serving.guard.*` counters when telemetry is on.
"""
import time

from ... import telemetry as _tm
from .brownout import BrownoutController
from .budget import FractionBucket, RetryBudget
from .health import STATE_CODES, HealthTracker
from .hedge import HedgePolicy, LatencyWindow

__all__ = ["GuardConfig", "GroupGuard"]


class GuardConfig:
    """Knobs for one group's guard. Defaults are production-shaped
    (seconds-scale cooldowns, p99-derived hedge delay); the selftests
    tighten them for CI clocks.

    health: EWMA smoothing, relative-slowness bar (`slow_factor` x
        peer median), probation/ejection streaks, half-open cooldown.
    hedge: `hedge=False` disables re-issue; delay = `hedge_factor` x
        live p`hedge_quantile` (floored), or `hedge_fixed_delay_s`
        when pinned. `hedge_fraction` bounds hedges to that fraction
        of submitted traffic.
    retry: token bucket shared by hedges and crash resubmissions
        (`retry_rate` tokens/s, burst `retry_burst`; rate 0 = a fixed
        allowance, the deterministic test shape).
    brownout: queue-depth / deadline-miss thresholds with hysteresis;
        `clamp_new_tokens` caps generation length while active.
    """

    def __init__(self,
                 # health
                 latency_alpha=0.3, error_alpha=0.3, min_samples=4,
                 slow_factor=3.0, slow_floor_s=0.005,
                 err_probation=0.3, err_exit=0.1, enter_streak=3,
                 probation_grace=4, probation_good=3,
                 probation_penalty=0.1, cooldown_s=5.0,
                 cooldown_max_s=60.0, probe_max=1,
                 # hedging
                 hedge=True, hedge_quantile=0.99, hedge_factor=1.5,
                 hedge_floor_s=0.02, hedge_min_samples=8,
                 hedge_fixed_delay_s=None, hedge_fraction=0.25,
                 hedge_burst=8.0, window_size=512,
                 # retry budget
                 retry_rate=8.0, retry_burst=16,
                 # brownout
                 queue_high=32, queue_low=8, miss_high=0.2,
                 miss_low=0.05, miss_alpha=0.2, clamp_new_tokens=None,
                 retry_after_s=1.0, dwell_s=0.25,
                 # guarded result() poll tick
                 poll_s=0.001):
        self.latency_alpha = latency_alpha
        self.error_alpha = error_alpha
        self.min_samples = min_samples
        self.slow_factor = slow_factor
        self.slow_floor_s = slow_floor_s
        self.err_probation = err_probation
        self.err_exit = err_exit
        self.enter_streak = enter_streak
        self.probation_grace = probation_grace
        self.probation_good = probation_good
        self.probation_penalty = probation_penalty
        self.cooldown_s = cooldown_s
        self.cooldown_max_s = cooldown_max_s
        self.probe_max = probe_max
        self.hedge = hedge
        self.hedge_quantile = hedge_quantile
        self.hedge_factor = hedge_factor
        self.hedge_floor_s = hedge_floor_s
        self.hedge_min_samples = hedge_min_samples
        self.hedge_fixed_delay_s = hedge_fixed_delay_s
        self.hedge_fraction = hedge_fraction
        self.hedge_burst = hedge_burst
        self.window_size = window_size
        self.retry_rate = retry_rate
        self.retry_burst = retry_burst
        self.queue_high = queue_high
        self.queue_low = queue_low
        self.miss_high = miss_high
        self.miss_low = miss_low
        self.miss_alpha = miss_alpha
        self.clamp_new_tokens = clamp_new_tokens
        self.retry_after_s = retry_after_s
        self.dwell_s = dwell_s
        self.poll_s = float(poll_s)


class GroupGuard:
    """The guard instance one ReplicaGroup owns."""

    def __init__(self, config=None, num_replicas=1,
                 clock=time.monotonic):
        self.config = cfg = config or GuardConfig()
        self.poll_s = cfg.poll_s
        self.health = HealthTracker(
            num_replicas, latency_alpha=cfg.latency_alpha,
            error_alpha=cfg.error_alpha, min_samples=cfg.min_samples,
            slow_factor=cfg.slow_factor,
            slow_floor_s=cfg.slow_floor_s,
            err_probation=cfg.err_probation, err_exit=cfg.err_exit,
            enter_streak=cfg.enter_streak,
            probation_grace=cfg.probation_grace,
            probation_good=cfg.probation_good,
            probation_penalty=cfg.probation_penalty,
            cooldown_s=cfg.cooldown_s,
            cooldown_max_s=cfg.cooldown_max_s,
            probe_max=cfg.probe_max, clock=clock)
        self.hedge = HedgePolicy(
            enabled=cfg.hedge, quantile=cfg.hedge_quantile,
            factor=cfg.hedge_factor, floor_s=cfg.hedge_floor_s,
            min_samples=cfg.hedge_min_samples,
            fixed_delay_s=cfg.hedge_fixed_delay_s,
            window=LatencyWindow(cfg.window_size))
        self.hedge_budget = FractionBucket(
            fraction=cfg.hedge_fraction, burst=cfg.hedge_burst)
        self.retry_budget = RetryBudget(
            rate=cfg.retry_rate, burst=cfg.retry_burst, clock=clock)
        self.brownout = BrownoutController(
            queue_high=cfg.queue_high, queue_low=cfg.queue_low,
            miss_high=cfg.miss_high, miss_low=cfg.miss_low,
            miss_alpha=cfg.miss_alpha,
            clamp_new_tokens=cfg.clamp_new_tokens,
            retry_after_s=cfg.retry_after_s, dwell_s=cfg.dwell_s,
            clock=clock)
        self.hedges = 0
        self.hedge_wins = 0
        self.hedge_cancelled = 0
        self.resubmits = 0

    # ------------------------------------------------------ admission
    def admit(self, tenant, qos, queue_depth, max_new_tokens,
              request_id=None):
        """Group-submit admission: update brownout against the queue,
        shed/clamp, and bank this request's hedge allowance. Returns
        the max_new_tokens to submit with (possibly clamped).
        `request_id` attributes shed verdicts to the request's trace."""
        self.brownout.observe(queue_depth)
        out = self.brownout.admit(
            tenant, qos.lowest_classes() if qos is not None else (),
            max_new_tokens, request_id=request_id)
        self.hedge_budget.deposit()
        return out

    # -------------------------------------------------- result events
    def on_result(self, index, latency_s, hedge=False):
        self.health.record(index, latency_s=latency_s, ok=True)
        self.hedge.observe(latency_s)
        self.brownout.on_ok()
        if hedge:
            self.hedge_wins += 1
            if _tm.enabled():
                _tm.counter("serving.guard.hedge_wins").inc()

    def on_error(self, index):
        self.health.record(index, ok=False)

    def on_deadline_miss(self):
        self.brownout.on_deadline_miss()

    # ---------------------------------------------------- autoscaling
    def on_replica_added(self, index):
        """Scale-up joined a replica at `index`: grow the health table
        so routing/recording never indexes past it."""
        self.health.ensure(index + 1)

    def set_scale_headroom(self, flag):
        """tpuscale's shed-only-at-ceiling lever (see
        BrownoutController.set_headroom)."""
        self.brownout.set_headroom(flag)

    def on_cancelled(self):
        self.hedge_cancelled += 1
        if _tm.enabled():
            _tm.counter("serving.guard.hedge_cancelled").inc()

    # ------------------------------------------------------- budgets
    def hedge_delay(self):
        return self.hedge.delay()

    def allow_hedge(self, request_id=None):
        """One hedge = one hedge-fraction token AND one retry token
        (hedges and resubmissions drain the same storm budget)."""
        if not self.hedge.enabled:
            return False
        if not self.hedge_budget.acquire(request_id=request_id):
            if _tm.enabled():
                _tm.counter("serving.guard.hedge_denied").inc()
            return False
        if not self.retry_budget.acquire(request_id=request_id):
            self.hedge_budget.refund()
            if _tm.enabled():
                _tm.counter("serving.guard.hedge_denied").inc()
            return False
        return True

    def refund_hedge(self):
        """The routed hedge never launched (no second replica)."""
        self.hedge_budget.refund()
        self.retry_budget.refund()

    def on_hedge(self):
        self.hedges += 1
        if _tm.enabled():
            _tm.counter("serving.guard.hedges").inc()

    def allow_resubmit(self, request_id=None):
        if not self.retry_budget.acquire(request_id=request_id):
            if _tm.enabled():
                _tm.counter("serving.guard.retry_denied").inc()
            return False
        return True

    def on_resubmit(self):
        self.resubmits += 1
        if _tm.enabled():
            _tm.counter("serving.guard.resubmits").inc()

    # ----------------------------------------------------- telemetry
    def stats(self):
        p99 = self.hedge.p99_ms()
        return {
            "replicas": self.health.snapshot(),
            "ejections": self.health.ejections,
            "readmissions": self.health.readmissions,
            "probes": self.health.probes,
            "hedges": self.hedges,
            "hedge_wins": self.hedge_wins,
            "hedge_cancelled": self.hedge_cancelled,
            "resubmits": self.resubmits,
            "retry_tokens": round(self.retry_budget.tokens, 2),
            "retry_denied": self.retry_budget.denied,
            "brownout": self.brownout.active,
            "brownout_entries": self.brownout.entries,
            "brownout_sheds": self.brownout.sheds,
            "brownout_deferred": self.brownout.deferred,
            "scale_headroom": self.brownout.headroom,
            "clamped": self.brownout.clamped,
            "p99_ms": None if p99 is None else round(p99, 3)}

    def publish(self):
        """Push the guard gauges (piggybacks on group._publish)."""
        if not _tm.enabled():
            return
        snap = self.health.snapshot()
        for i, h in enumerate(snap):
            _tm.gauge(f"serving.replica.{i}.guard_state").set(
                STATE_CODES[h["state"]])
        _tm.gauge("serving.guard.brownout").set(
            1.0 if self.brownout.active else 0.0)
        _tm.gauge("serving.guard.retry_tokens").set(
            self.retry_budget.tokens)
        p99 = self.hedge.p99_ms()
        if p99 is not None:
            _tm.gauge("serving.guard.p99_ms").set(p99)
