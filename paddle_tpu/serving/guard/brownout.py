"""Brownout: planned partial degradation instead of unplanned collapse.

When a group is overloaded — queue depth past `queue_high`, or the
deadline-miss EWMA past `miss_high` — rejecting everything is as wrong
as accepting everything. The brownout controller degrades in order of
pain:

1. **Shed the lowest QoS class.** Tenants in the cheapest weight class
   get `BrownoutShed` (HTTP 429, kind "brownout") with a `Retry-After`
   hint; paying classes keep flowing. Shedding never touches work
   already admitted — only new arrivals.
2. **Clamp `max_new_tokens`.** Surviving requests are capped at
   `clamp_new_tokens`, trading answer length for admission rate — each
   slot turns over faster, so more callers get *something*.

Recovery is hysteretic: brownout exits only when the queue has fallen
below `queue_low` AND the miss EWMA below `miss_low` AND `dwell_s` has
elapsed since entry — a controller that flaps at the threshold would
hand clients a 429/200 strobe light.
"""
import threading
import time

from ... import telemetry as _tm

__all__ = ["BrownoutController"]


class BrownoutController:
    """Hysteretic overload state machine for one replica group."""

    def __init__(self, queue_high=32, queue_low=8, miss_high=0.2,
                 miss_low=0.05, miss_alpha=0.2, clamp_new_tokens=None,
                 retry_after_s=1.0, dwell_s=0.25,
                 clock=time.monotonic):
        self.queue_high = int(queue_high)
        self.queue_low = int(queue_low)
        self.miss_high = float(miss_high)
        self.miss_low = float(miss_low)
        self.miss_alpha = float(miss_alpha)
        self.clamp_new_tokens = clamp_new_tokens if \
            clamp_new_tokens is None else int(clamp_new_tokens)
        self.retry_after_s = float(retry_after_s)
        self.dwell_s = float(dwell_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._active = False
        self._entered_at = 0.0
        self._miss_ewma = 0.0
        self._headroom = False
        self.entries = 0
        self.sheds = 0
        self.clamped = 0
        self.deferred = 0

    # -------------------------------------------------------- signals
    def on_deadline_miss(self):
        with self._lock:
            self._miss_ewma = ((1.0 - self.miss_alpha) * self._miss_ewma
                               + self.miss_alpha)

    def on_ok(self):
        with self._lock:
            self._miss_ewma *= (1.0 - self.miss_alpha)

    @property
    def miss_ewma(self):
        with self._lock:
            return self._miss_ewma

    @property
    def active(self):
        with self._lock:
            return self._active

    # ------------------------------------------------------- headroom
    def set_headroom(self, flag):
        """tpuscale's demotion lever: while an autoscale controller
        reports spare device capacity (`flag=True`), overload must be
        answered by GROWING, not shedding — brownout ENTRY is deferred
        (counted on `deferred`) until the controller reports the
        device ceiling. Exit and already-active behavior are
        untouched, and the flag defaults False — a group without a
        controller sheds exactly as PR 14 shipped it."""
        with self._lock:
            self._headroom = bool(flag)

    @property
    def headroom(self):
        with self._lock:
            return self._headroom

    # ------------------------------------------------------ admission
    def observe(self, queue_depth):
        """Update the state machine against the current queue depth;
        called on every group submit. Returns the active flag."""
        with self._lock:
            if not self._active:
                if queue_depth >= self.queue_high \
                        or self._miss_ewma >= self.miss_high:
                    if self._headroom:
                        # scale-out beats brownout: the autoscaler has
                        # free slices, let it absorb the surge
                        self.deferred += 1
                        if _tm.enabled():
                            _tm.counter(
                                "serving.guard.brownout_deferred").inc()
                        return self._active
                    self._active = True
                    self._entered_at = self._clock()
                    self.entries += 1
                    if _tm.enabled():
                        _tm.counter("serving.guard.brownouts").inc()
            else:
                calm = (queue_depth <= self.queue_low
                        and self._miss_ewma <= self.miss_low)
                dwelt = (self._clock() - self._entered_at
                         >= self.dwell_s)
                if calm and dwelt:
                    self._active = False
            return self._active

    def admit(self, tenant, shed_classes, max_new_tokens,
              request_id=None):
        """Admission verdict while the controller may be active.
        Returns the (possibly clamped) max_new_tokens, or raises
        BrownoutShed for the shed classes. No-op when inactive.
        `request_id` lands the shed verdict on the request's trace."""
        with self._lock:
            if not self._active:
                return max_new_tokens
        if tenant in shed_classes:
            with self._lock:
                self.sheds += 1
            if _tm.enabled():
                _tm.counter("serving.guard.brownout_sheds").inc()
            if request_id is not None and _tm.reqtrace_enabled():
                _tm.reqtrace.flag(request_id, "shed")
                _tm.reqtrace.event(request_id, "guard.brownout.shed",
                                   tenant=tenant,
                                   retry_after_s=self.retry_after_s)
            from ..batcher import BrownoutShed
            raise BrownoutShed(
                f"brownout: tenant {tenant!r} is in the lowest QoS "
                f"class and the group is overloaded; retry after "
                f"{self.retry_after_s:g}s",
                retry_after_s=self.retry_after_s)
        if self.clamp_new_tokens is not None and (
                max_new_tokens is None
                or max_new_tokens > self.clamp_new_tokens):
            with self._lock:
                self.clamped += 1
            if _tm.enabled():
                _tm.counter("serving.guard.clamped").inc()
            if request_id is not None and _tm.reqtrace_enabled():
                _tm.reqtrace.event(request_id, "guard.brownout.clamp",
                                   clamp=self.clamp_new_tokens)
            return self.clamp_new_tokens
        return max_new_tokens
