"""High-level Trainer / Inferencer.

Parity: python/paddle/fluid/trainer.py + inferencer.py — train_func-based
loop with event callbacks (BeginEpochEvent/EndStepEvent...), checkpoint
config, and test(); and an Inferencer wrapping load_inference_model.
"""
import os
import time

import numpy as np

from .core.framework import Program, program_guard
from .core.executor import Executor
from .core.place import core_place_of
from .data_feeder import DataFeeder
from . import io as _io

__all__ = ["Trainer", "Inferencer", "BeginEpochEvent", "EndEpochEvent",
           "BeginStepEvent", "EndStepEvent", "CheckpointConfig"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or "/tmp/paddle_tpu_ckpt"
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = epoch_interval
        self.step_interval = step_interval


class Trainer:
    """ref trainer.py:Trainer — builds train/startup programs from
    train_func, runs the loop, owns checkpointing."""

    def __init__(self, train_func, optimizer_func, place=None,
                 param_path=None, parallel=False, checkpoint_config=None):
        self.place = core_place_of(place)
        self.parallel = parallel
        self.checkpoint_cfg = checkpoint_config
        self.train_program = Program()
        self.startup_program = Program()
        with program_guard(self.train_program, self.startup_program):
            outs = train_func()
            if isinstance(outs, (list, tuple)):
                self.loss = outs[0]
                self.fetch_vars = list(outs)
            else:
                self.loss = outs
                self.fetch_vars = [outs]
            self.test_program = self.train_program.clone(for_test=True)
            optimizer = optimizer_func()
            optimizer.minimize(self.loss)
        self.exe = Executor(self.place)
        self.exe.run(self.startup_program)
        if param_path:
            _io.load_params(self.exe, param_path)
        self._step = 0

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        feed_vars = [self.train_program.global_block().var(n)
                     for n in feed_order]
        feeder = DataFeeder(feed_vars, self.place)
        runner = self.exe
        if self.parallel:
            from .parallel.parallel_executor import ParallelExecutor
            runner = ParallelExecutor(loss_name=self.loss.name,
                                      main_program=self.train_program)
        for epoch in range(num_epochs):
            event_handler(BeginEpochEvent(epoch))
            for step, data in enumerate(reader()):
                begin = BeginStepEvent(epoch, step)
                event_handler(begin)
                fetch = self.fetch_vars if begin.fetch_metrics else []
                if self.parallel:
                    metrics = runner.run(feed=feeder.feed(data),
                                         fetch_list=fetch)
                else:
                    metrics = runner.run(self.train_program,
                                         feed=feeder.feed(data),
                                         fetch_list=fetch)
                self._step += 1
                if (self.checkpoint_cfg and
                        self._step % self.checkpoint_cfg.step_interval == 0):
                    _io.save_checkpoint(self.exe,
                                        self.checkpoint_cfg.checkpoint_dir,
                                        self.train_program, step=self._step)
                event_handler(EndStepEvent(epoch, step, metrics))
            event_handler(EndEpochEvent(epoch))

    def test(self, reader, feed_order):
        feed_vars = [self.test_program.global_block().var(n)
                     for n in feed_order]
        feeder = DataFeeder(feed_vars, self.place)
        totals = None
        count = 0
        for data in reader():
            vals = self.exe.run(self.test_program, feed=feeder.feed(data),
                                fetch_list=self.fetch_vars, is_test=True)
            vals = [np.mean(v) for v in vals]
            totals = vals if totals is None else [a + b for a, b in zip(totals, vals)]
            count += 1
        return [t / max(count, 1) for t in (totals or [])]

    def save_params(self, param_path):
        _io.save_params(self.exe, param_path, self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes=(0,)):
        targets = [self.fetch_vars[i] for i in target_var_indexes]
        _io.save_inference_model(param_path, feeded_var_names, targets,
                                 self.exe, self.train_program)

    def stop(self):
        pass


class Inferencer:
    """ref inferencer.py:Inferencer."""

    def __init__(self, infer_func=None, param_path=None, place=None,
                 parallel=False):
        self.place = core_place_of(place)
        self.exe = Executor(self.place)
        if infer_func is not None:
            self.program = Program()
            startup = Program()
            with program_guard(self.program, startup):
                outs = infer_func()
                self.fetch_vars = outs if isinstance(outs, (list, tuple)) else [outs]
                self.feed_names = [v.name for v in self.program.list_vars()
                                   if v.is_data]
            self.exe.run(startup)
            if param_path:
                _io.load_params(self.exe, param_path)
            self.program = self.program.clone(for_test=True)
        else:
            self.program, self.feed_names, self.fetch_vars = \
                _io.load_inference_model(param_path, self.exe)

    def infer(self, inputs, return_numpy=True):
        return self.exe.run(self.program, feed=inputs,
                            fetch_list=self.fetch_vars,
                            return_numpy=return_numpy, is_test=True)
