"""Weight-decay regularizers.

Parity: python/paddle/fluid/regularizer.py — L1/L2 decay appended as ops
on the grad vars between backward and the update ops (same placement as
the reference), so decay math fuses into the optimizer XLA module.
"""
__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class Regularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(Regularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        # grad += coeff * param  (one scale + one add op)
        from . import unique_name
        decay = block.create_var(
            name=unique_name.generate(param.name + "@L2DECAY"),
            shape=param.shape, dtype=param.dtype, stop_gradient=True)
        block.append_op("scale", {"X": [param]}, {"Out": [decay]},
                        {"scale": self._coeff})
        block.append_op("elementwise_add", {"X": [grad], "Y": [decay]},
                        {"Out": [grad]}, {"axis": -1})
        return grad


class L1DecayRegularizer(Regularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        from . import unique_name
        sign = block.create_var(
            name=unique_name.generate(param.name + "@L1SIGN"),
            shape=param.shape, dtype=param.dtype, stop_gradient=True)
        block.append_op("sign", {"X": [param]}, {"Out": [sign]}, {})
        decay = block.create_var(
            name=unique_name.generate(param.name + "@L1DECAY"),
            shape=param.shape, dtype=param.dtype, stop_gradient=True)
        block.append_op("scale", {"X": [sign]}, {"Out": [decay]},
                        {"scale": self._coeff})
        block.append_op("elementwise_add", {"X": [grad], "Y": [decay]},
                        {"Out": [grad]}, {"axis": -1})
        return grad


def append_regularization_ops(params_grads, regularization=None):
    """Apply per-param regularizer (ParamAttr) or the global one
    (ref regularizer.py:append_regularization_ops)."""
    out = []
    for param, grad in params_grads:
        reg = getattr(param, "regularizer", None) or regularization
        if reg is not None and getattr(param, "trainable", True):
            block = grad.block
            grad = reg(param, grad, block) or grad
        out.append((param, grad))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
