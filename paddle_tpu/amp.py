"""Mixed precision (bfloat16) utilities.

Parity: paddle/contrib/float16/float16_transpiler.py — the reference
rewrites a fp32 inference ProgramDesc to fp16. On TPU the native fast
dtype is bfloat16 (MXU-preferred, no loss-scaling needed thanks to fp32
exponent range), so the transpiler casts params + feeds to bf16 and
keeps normalization/softmax/losses in fp32 (the kernels in ops/kernels_nn
already upcast internally).
"""
import numpy as np

from .core.scope import global_scope

__all__ = ["bf16_guard", "cast_program_to_bf16", "cast_params_to_bf16",
           "master_weight_note"]

# dtype-sensitive ops that must keep fp32 params (norm stats/scales)
_KEEP_FP32_PARAM_SUFFIX = ("batch_norm", "layer_norm", "group_norm")


def cast_program_to_bf16(program, keep_io_fp32=True):
    """Rewrite var dtypes float32→bfloat16 for Parameters and activations.

    Never touched: data IO vars, norm scales/biases, and ALL persistable
    non-Parameter state (optimizer moments, beta-pow scalars, LR vars,
    counters, bn moving stats) — bf16 cannot represent e.g. beta2=0.999
    (rounds to 1.0, zeroing Adam's bias-corrected LR), so optimizer state
    must stay fp32 (master-weight style; the update kernels already
    compute in fp32). Returns the modified program (in place, like the
    ref float16 transpiler)."""
    from .core.framework import Parameter
    for block in program.blocks:
        for var in block.vars.values():
            if var.dtype != "float32":
                continue
            if keep_io_fp32 and var.is_data:
                continue
            if isinstance(var, Parameter):
                # norm scales stay fp32 (kernels compute stats in fp32)
                if any(s in var.name for s in _KEEP_FP32_PARAM_SUFFIX):
                    continue
            elif var.persistable:
                continue
            var.dtype = "bfloat16"
    program._bump_version()
    return program


def cast_params_to_bf16(program, scope=None):
    """Cast already-initialized scope params to match program dtypes."""
    import jax.numpy as jnp
    scope = scope or global_scope()
    for var in program.persistable_vars():
        val = scope.get(var.name)
        if val is None:
            continue
        want = var.dtype
        have = str(np.asarray(val).dtype) if not hasattr(val, "dtype") else str(val.dtype)
        if want == "bfloat16" and have == "float32":
            scope.set(var.name, jnp.asarray(val, dtype=jnp.bfloat16))


import contextlib


@contextlib.contextmanager
def bf16_guard(program=None):
    """Build-time guard: layers created inside default to bfloat16 data.
    (Declare data vars with dtype='bfloat16' for full effect.)"""
    yield


def master_weight_note():
    return ("Optimizer update kernels (ops/kernels_optim.py) keep all "
            "moments in fp32 and upcast params for the update — master "
            "weights are implicit; no loss scaling needed with bf16.")
