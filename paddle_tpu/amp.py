"""Mixed precision (bfloat16) utilities.

Parity: paddle/contrib/float16/float16_transpiler.py — the reference
rewrites a fp32 inference ProgramDesc to fp16. On TPU the native fast
dtype is bfloat16 (MXU-preferred, no loss-scaling needed thanks to fp32
exponent range), so the transpiler casts params + feeds to bf16 and
keeps normalization/softmax/losses in fp32 (the kernels in ops/kernels_nn
already upcast internally).
"""
import numpy as np

from .core.scope import global_scope

__all__ = ["bf16_guard", "cast_program_to_bf16", "cast_params_to_bf16"]

# dtype-sensitive ops that must keep fp32 params (norm stats/scales)
_KEEP_FP32_PARAM_SUFFIX = ("batch_norm", "layer_norm", "group_norm")


def cast_program_to_bf16(program, keep_io_fp32=True):
    """Rewrite var dtypes float32→bfloat16 for Parameters and activations.

    Never touched: data IO vars, norm scales/biases, and ALL persistable
    non-Parameter state (optimizer moments, beta-pow scalars, LR vars,
    counters, bn moving stats) — bf16 cannot represent e.g. beta2=0.999
    (rounds to 1.0, zeroing Adam's bias-corrected LR), so optimizer state
    must stay fp32 (master-weight style; the update kernels already
    compute in fp32). Returns the modified program (in place, like the
    ref float16 transpiler)."""
    from .core.framework import Parameter
    for block in program.blocks:
        for var in block.vars.values():
            if var.dtype != "float32":
                continue
            if keep_io_fp32 and var.is_data:
                continue
            if isinstance(var, Parameter):
                # norm scales stay fp32 (kernels compute stats in fp32)
                if any(s in var.name for s in _KEEP_FP32_PARAM_SUFFIX):
                    continue
            elif var.persistable:
                continue
            var.dtype = "bfloat16"
    program._bump_version()
    return program


def cast_params_to_bf16(program, scope=None):
    """Cast already-initialized scope params to match program dtypes."""
    import jax.numpy as jnp
    scope = scope or global_scope()
    for var in program.persistable_vars():
        val = scope.get(var.name)
        if val is None:
            continue
        want = var.dtype
        have = str(np.asarray(val).dtype) if not hasattr(val, "dtype") else str(val.dtype)
        if want == "bfloat16" and have == "float32":
            scope.set(var.name, jnp.asarray(val, dtype=jnp.bfloat16))


import contextlib


@contextlib.contextmanager
def bf16_guard(program=None):
    """Build-time scoped bf16 region (ref contrib amp bf16_guard): ops
    appended to `program` (default main) INSIDE this context get their
    float32 Parameters and intermediate vars rewritten to bfloat16 on
    exit — the scoped version of cast_program_to_bf16, with the same
    keep-fp32 rules (data IO, norm scales, optimizer/persistable state).
    """
    from .core.framework import default_main_program, Parameter
    program = program or default_main_program()
    block = program.global_block()
    start = len(block.ops)
    yield
    new_ops = list(block.ops[start:])
    # include ops nested in control-flow sub-blocks created in the region
    def expand(ops):
        out = []
        for op in ops:
            out.append(op)
            for key in ("true_block", "false_block", "cond_block",
                        "body_block", "step_block"):
                bidx = op.attrs.get(key)
                if bidx is not None:
                    out.extend(expand(program.blocks[bidx].ops))
        return out
    new_ops = expand(new_ops)
    # outputs created inside the region, plus the Parameters its ops
    # consume — NOT inputs produced outside (those keep their dtype;
    # the kernels' autocast handles the boundary)
    all_vars = {}
    for blk in program.blocks:
        all_vars.update(blk.vars)
    touched = set()
    for op in new_ops:
        touched.update(op.output_names())
        for n in op.input_names():
            if isinstance(all_vars.get(n), Parameter):
                touched.add(n)
    for blk in program.blocks:
        for var in blk.vars.values():
            if var.name not in touched or var.dtype != "float32":
                continue
            if var.is_data:
                continue
            if isinstance(var, Parameter):
                if any(s in var.name for s in _KEEP_FP32_PARAM_SUFFIX):
                    continue
            elif var.persistable:
                continue
            var.dtype = "bfloat16"
    program._bump_version()
