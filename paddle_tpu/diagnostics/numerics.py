"""Numerics primitives for the training doctor (tpudoctor).

The reference runtime's `FLAGS_check_nan_inf` aborts inside the exact
kernel that produced a NaN (paddle/fluid/framework/operator.cc:
CheckNanInf); our whole-program XLA compile erases that per-op boundary,
so the doctor reconstructs it after the fact: tensor statistics,
a structured `NumericsReport` naming the culprit op, and `NanInfError`
carrying the report for programmatic consumers (CI gates, the flight
recorder, tools/tpudoctor.py).
"""
import hashlib
import json

import numpy as np

__all__ = ["TensorStats", "tensor_stats", "NumericsReport",
           "NanInfError", "feed_fingerprint", "fix_hint",
           "nonfinite_count"]


def nonfinite_count(arr):
    """(nan_count, inf_count) for a host array; (0, 0) for non-floats."""
    arr = np.asarray(arr)
    if arr.dtype.kind not in "fc":
        return 0, 0
    return int(np.isnan(arr).sum()), int(np.isinf(arr).sum())


class TensorStats:
    """Summary statistics of one tensor (the per-op record the reference
    prints from CheckNanInf, plus the counts it lacks)."""

    __slots__ = ("name", "shape", "dtype", "min", "max", "absmax",
                 "mean", "nan_count", "inf_count", "size")

    def __init__(self, name, shape, dtype, min, max, absmax, mean,
                 nan_count, inf_count, size):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = str(dtype)
        self.min, self.max = min, max
        self.absmax, self.mean = absmax, mean
        self.nan_count, self.inf_count = nan_count, inf_count
        self.size = size

    @property
    def finite(self):
        return self.nan_count == 0 and self.inf_count == 0

    def to_dict(self):
        return {s: getattr(self, s) for s in self.__slots__}

    def __str__(self):
        s = (f"{self.name}: {self.dtype}{list(self.shape)} "
             f"min={self.min:.4g} max={self.max:.4g} "
             f"absmax={self.absmax:.4g} mean={self.mean:.4g}")
        if not self.finite:
            s += f"  ** nan={self.nan_count} inf={self.inf_count} **"
        return s


def tensor_stats(arr, name=""):
    """Host-side TensorStats of `arr` (device arrays are read back —
    this only runs on the diagnosis path, never in the hot loop)."""
    arr = np.asarray(arr)
    if arr.dtype.kind not in "biufc":      # bfloat16 etc.: view-cast up
        arr = arr.astype(np.float32)
    size = int(arr.size)
    if size == 0:
        return TensorStats(name, arr.shape, arr.dtype, 0.0, 0.0, 0.0,
                           0.0, 0, 0, 0)
    with np.errstate(all="ignore"):     # stats OF an overflow must not warn
        if arr.dtype.kind in "fc":
            nan_c, inf_c = nonfinite_count(arr)
            finite = arr[np.isfinite(arr)]
            if finite.size:
                mn, mx = float(finite.min()), float(finite.max())
                absmax = float(np.abs(finite).max())
                mean = float(finite.astype(np.float64).mean())
            else:
                mn = mx = absmax = mean = float("nan")
        else:
            nan_c = inf_c = 0
            mn, mx = float(arr.min()), float(arr.max())
            absmax = float(np.abs(arr).max())
            mean = float(arr.mean())
    return TensorStats(name, arr.shape, arr.dtype, mn, mx, absmax,
                       mean, nan_c, inf_c, size)


def feed_fingerprint(feed):
    """Stable digest of a feed dict: names, shapes, dtypes, and a
    content hash — lets a NumericsReport say "THIS batch diverged" so
    the failing input can be replayed from a data log."""
    h = hashlib.sha256()
    for k in sorted(feed):
        arr = np.asarray(feed[k])
        h.update(k.encode())
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        if arr.dtype.kind not in "biufc":
            arr = arr.astype(np.float32)
        h.update(np.ascontiguousarray(arr).tobytes()[:1 << 16])
    return h.hexdigest()[:16]


# proglint-style fix hints by culprit op type; backward/update phases
# get phase-level fallbacks. Keyed on substrings so e.g.
# softmax_with_cross_entropy and cross_entropy both match.
_HINTS = (
    (("cross_entropy", "log"),
     "log of a zero/negative probability — clip inputs away from 0 "
     "(layers.clip) or use softmax_with_cross_entropy, whose fused "
     "form is stable"),
    (("softmax", "exp"),
     "exp overflow — inputs too large; normalize/scale activations or "
     "subtract the row max before exp"),
    (("sqrt", "rsqrt"),
     "sqrt/rsqrt at <= 0 — add an epsilon inside the sqrt (its "
     "gradient at 0 is infinite even when the forward value is fine)"),
    (("elementwise_div", "div", "mean_grad"),
     "division by zero — add an epsilon to the denominator"),
    (("pow",),
     "pow with a negative base or huge exponent — clip the base or "
     "lower the exponent"),
    (("adam", "sgd", "momentum", "rmsprop", "adagrad", "lamb", "ftrl",
      "adadelta", "adamax"),
     "optimizer update went non-finite — lower the learning rate or "
     "add clip.GradientClipByGlobalNorm before minimize()"),
    (("batch_norm", "layer_norm"),
     "normalization variance collapsed — check for constant inputs or "
     "raise the epsilon attr"),
    (("matmul", "mul", "conv"),
     "overflow in a matmul/conv — activations or weights too large; "
     "consider loss scaling (amp) or weight-decay/clipping"),
)

_PHASE_HINTS = {
    "backward": "gradient explosion — add gradient clipping "
                "(clip.GradientClipByGlobalNorm) or lower the "
                "learning rate",
    "update": "optimizer state went non-finite — lower the learning "
              "rate, or reset stale accumulators from a checkpoint",
    "input": "a feed or persistable var was already non-finite BEFORE "
             "the step — check the data pipeline, initializers, or "
             "the previous step's update",
}


def fix_hint(op_type, phase="forward"):
    """One-line remediation suggestion (same contract as
    analysis.Diagnostic.hint)."""
    for keys, hint in _HINTS:
        if any(k in (op_type or "") for k in keys):
            return hint
    return _PHASE_HINTS.get(
        phase, "inspect the input stats above; if inputs are finite "
               "the op's own math overflowed — consider fp32 for this "
               "op or rescaling")


class NumericsReport:
    """Structured culprit record produced by diagnostics.bisect.

    phase: "forward" (op output went non-finite), "backward" (the op's
    GRADIENT went non-finite while its forward output was fine),
    "update" (an optimizer-tail op corrupted state), or "input"
    (feeds/persistables were already bad before the step ran).
    """

    def __init__(self, phase, op_type=None, block_idx=0, op_idx=None,
                 pruned_idx=None, input_stats=(), output_stats=(),
                 nonfinite_vars=(), feed_fingerprint="", step=None,
                 program_version=None, seed=None, hint=None,
                 detail=""):
        self.phase = phase
        self.op_type = op_type
        self.block_idx = block_idx
        self.op_idx = op_idx          # index in the ORIGINAL block
        self.pruned_idx = pruned_idx  # index in the executed (pruned) list
        self.input_stats = list(input_stats)
        self.output_stats = list(output_stats)
        self.nonfinite_vars = list(nonfinite_vars)
        self.feed_fingerprint = feed_fingerprint
        self.step = step
        self.program_version = program_version
        self.seed = seed
        self.hint = hint if hint is not None else fix_hint(op_type, phase)
        self.detail = detail

    def location(self):
        if self.op_idx is None:
            return "(no single op)"
        return (f"block {self.block_idx}, op {self.op_idx} "
                f"({self.op_type})")

    def to_dict(self):
        return {
            "phase": self.phase, "op_type": self.op_type,
            "block_idx": self.block_idx, "op_idx": self.op_idx,
            "pruned_idx": self.pruned_idx,
            "input_stats": [s.to_dict() for s in self.input_stats],
            "output_stats": [s.to_dict() for s in self.output_stats],
            "nonfinite_vars": self.nonfinite_vars,
            "feed_fingerprint": self.feed_fingerprint,
            "step": self.step, "program_version": self.program_version,
            "seed": self.seed, "hint": self.hint, "detail": self.detail,
        }

    @classmethod
    def from_dict(cls, d):
        rep = cls(d["phase"], d.get("op_type"), d.get("block_idx", 0),
                  d.get("op_idx"), d.get("pruned_idx"),
                  nonfinite_vars=d.get("nonfinite_vars", ()),
                  feed_fingerprint=d.get("feed_fingerprint", ""),
                  step=d.get("step"),
                  program_version=d.get("program_version"),
                  seed=d.get("seed"), hint=d.get("hint"),
                  detail=d.get("detail", ""))
        rep.input_stats = [TensorStats(**s)
                           for s in d.get("input_stats", ())]
        rep.output_stats = [TensorStats(**s)
                            for s in d.get("output_stats", ())]
        return rep

    def to_json(self):
        return json.dumps(self.to_dict(), default=str)

    def format(self):
        lines = [f"NumericsReport [{self.phase}] @ {self.location()}"]
        if self.step is not None:
            lines.append(f"  step {self.step}, program version "
                         f"{self.program_version}, seed {self.seed}")
        if self.feed_fingerprint:
            lines.append(f"  feed fingerprint {self.feed_fingerprint}")
        if self.detail:
            lines.append(f"  {self.detail}")
        if self.nonfinite_vars:
            lines.append("  non-finite vars: "
                         + ", ".join(self.nonfinite_vars[:8]))
        if self.input_stats:
            lines.append("  inputs:")
            lines += [f"    {s}" for s in self.input_stats]
        if self.output_stats:
            lines.append("  outputs:")
            lines += [f"    {s}" for s in self.output_stats]
        if self.hint:
            lines.append(f"  hint: {self.hint}")
        return "\n".join(lines)

    __str__ = format

    def __repr__(self):
        return (f"<NumericsReport {self.phase} op={self.op_type!r} "
                f"idx={self.op_idx}>")


class NanInfError(FloatingPointError):
    """The doctor's verdict: a FloatingPointError (so existing
    `except FloatingPointError` callers keep working — the pre-doctor
    Executor raised exactly that) carrying the localization report."""

    def __init__(self, report, message=None):
        self.report = report
        super().__init__(
            message if message is not None else
            "NaN/Inf detected; culprit localized:\n" + report.format())
